"""jaxpr/AST lint passes over the zoo and the package sources.

Four families of defects this harness has actually hit (or nearly
shipped) are checked statically:

- **host-sync-in-jit** (error): a host round-trip inside traced code —
  ``.item()``, ``jax.device_get``, ``block_until_ready``,
  ``np.array``/``np.asarray`` on a traced value.  At best these bake a
  constant at trace time; at worst (under real jit) they throw a
  ``ConcretizationTypeError`` only on the first hardware run.  Checked
  two ways: over the AST of functions that are traced (passed to
  ``jax.jit``/``jax.shard_map``/``pallas_call``/``lax`` control flow,
  flax ``nn.Module`` methods, and anything nested in those), and over
  the model's jaxpr (``pure_callback``/``io_callback``/host callbacks).
- **recompile-hazard**: Python-scalar closure leaks — a traced function
  reading a free variable its enclosing scope *mutates* (for-loop
  target / augmented assignment), which bakes a different constant per
  call and recompiles every step (warning) — and shape-dependent
  branching against numeric literals, which silently forks compilations
  per shape class (info; shape-vs-shape residual branches are the
  normal static idiom and do not flag).
- **donated-buffer-misuse** (warning): a buffer passed in a
  ``donate_argnums`` position of a jitted call and then read again
  later in the same scope — donation invalidates it, and XLA's runtime
  error surfaces far from the offending read.
- **checkpoint-topology** (warning): a checkpoint-writing call site
  (``ckpt.save``/``save_pp``/``write_host_payload``/an async writer's
  ``submit``) that does not pass a ``topology=`` sidecar record.  The
  elastic-resume path (round 12) can only re-place a checkpoint whose
  save recorded the world/mesh/arm it was written under; a save path
  added without the sidecar silently produces checkpoints that resume
  on the identical mesh only.
- **input-pool-width** (warning): an ImageNet/TFRecord pipeline
  constructed with an explicit decode pool wider than the host budget
  cap (``max(32, cpu_count())`` — machine-stable up to 32 cores), or a
  full-host-width *private* pool — at workers-per-host > 1 the
  per-process pools oversubscribe the CPUs and bypass the shared input
  service's one-pool-per-host budget (``data/service.py``).
- **memory-probe-in-hot-loop** (warning): a device-memory probe
  (``jax.live_arrays``, ``jax.profiler.device_memory_profile``,
  ``obs.memory.device_memory_sample``/``device_memory_stats``, a
  memory ledger's ``.sample``) called in the body of a loop without a
  sync-window boundary guard.  Every one of these walks the backend's
  live-buffer table (or serializes a pprof blob) on the host — inside
  the timed step loop that is a per-step host stall the async-dispatch
  design exists to avoid.  The accepted idiom is the driver's: one poll
  per sync window, under an ``i % sync_every == 0``-shaped guard (any
  modulo test, or a condition spelling ``sync``/``window``).  The check
  is lexical — a probe wrapped in a helper called from the loop is on
  the reviewer — and loop headers (``for a in jax.live_arrays():``,
  the probes' own implementation) are exempt.
- **span-in-compiled-fn** (error): an ``obs.timeline`` flight-recorder
  call (``span``/``record_span``/``instant``/``transition``) inside
  traced code.  The recorder reads the host monotonic clock and stores
  into a host-side ring; traced, the clock read bakes ONE constant
  timestamp into the compiled program and the span lies in every
  execution after the first.  Recorder calls wrap the *dispatch* of
  compiled work (the driver/serve-engine idiom), never live inside it.
- **span-name-registry** (warning): a literal span name at a
  ``timeline.span``/``record_span``/``instant`` call site that is not
  registered in ``obs.timeline.KNOWN_SPANS``.  Folds key on span names,
  so a typo'd name records fine and silently vanishes from every
  timeline consumer; the registry makes the typo a CI finding.
- **fleet-blocking-wait** (error): a no-timeout ``.wait()``/``.join()``
  inside a loop body under ``tpu_hc_bench/fleet/`` — the fleet control
  loop is one thread supervising N jobs, and an unbounded block on any
  single process/thread freezes scheduling (reaps, liveness, churn)
  for the whole pool.  Bounded forms (``wait(5)``,
  ``join(timeout=...)``) and poll+sleep loops pass.
- **sharding-consistency** (warning): per model, the Megatron
  annotation table (``train.step.tp_param_spec``) is replayed against
  the abstractly-initialized param tree: a rule whose *name* matches a
  param but whose *rank* doesn't (annotation drift after a model
  refactor), a model-axis-sharded dimension not divisible by the
  minimum TP degree, and column/row rule pairs where one direction of a
  block matched but its partner did not (the asymmetry that makes GSPMD
  insert per-layer reshards at the pjit boundary).

Suppression: append ``# tpu-hc: disable=<lint>`` (or the legacy
``# thb:lint-ok[<lint>]``) to the offending line — suppression hits are
counted into the findings JSON so they stay auditable — or accept the
finding into the checked-in baseline (see ``report.py``).

Round 21: the passes register themselves in ``analysis.registry`` (one
``@register_pass`` per check carrying name/severity/scope/docs), and
``run()`` iterates the registry instead of a hand-coded sequence — the
distributed-correctness passes in ``analysis.dataflow`` plug in without
touching this file.
"""

from __future__ import annotations

import ast
import collections
import functools
import os
import re
import symtable
from pathlib import Path

from tpu_hc_bench.analysis import registry
from tpu_hc_bench.analysis.registry import register_pass
from tpu_hc_bench.analysis.report import Finding

__all__ = [
    "lint_source_text", "lint_file", "lint_repo_sources", "lint_model",
    "check_zero1_collectives", "check_tuned_registry", "ALL_SOURCE_LINTS",
]

HOST_SYNC = "host-sync-in-jit"
RECOMPILE = "recompile-hazard"
DONATION = "donated-buffer-misuse"
SHARDING = "sharding-consistency"
COLLECTIVE_SHAPE = "collective-shape"
CKPT_TOPOLOGY = "checkpoint-topology"
INPUT_POOL = "input-pool-width"
TUNED_STALENESS = "tuned-config-staleness"
HOT_MEMORY = "memory-probe-in-hot-loop"
SERVE_RECOMPILE = "serve-bucket-recompile"
SPAN_IN_JIT = "span-in-compiled-fn"
DEQUANT_HOT = "dequantize-in-hot-loop"
FLEET_WAIT = "fleet-blocking-wait"
SPAN_REGISTRY = "span-name-registry"
RETIRE_STATUS = "retire-without-status"
SIGNAL_REGISTRY = "signal-name-registry"
PAGE_REFCOUNT = "page-refcount-discipline"
ALL_SOURCE_LINTS = (HOST_SYNC, RECOMPILE, DONATION, CKPT_TOPOLOGY,
                    INPUT_POOL, HOT_MEMORY, SERVE_RECOMPILE, SPAN_IN_JIT,
                    DEQUANT_HOT, FLEET_WAIT, SPAN_REGISTRY, RETIRE_STATUS,
                    SIGNAL_REGISTRY, PAGE_REFCOUNT)

# callables whose function-valued arguments are traced (jit contexts)
_TRACING_CALLEES = {
    "jit", "pjit", "shard_map", "pallas_call", "checkpoint", "remat",
    "scan", "while_loop", "fori_loop", "cond", "switch", "vmap", "pmap",
    "grad", "value_and_grad", "custom_vjp", "custom_jvp",
}
# attribute/function calls that force a host round-trip on traced values
_HOST_SYNC_METHODS = {"item", "block_until_ready", "tolist"}
_HOST_SYNC_FUNCS = {"device_get", "block_until_ready"}
_NUMPY_ALIASES = {"np", "numpy", "onp"}
_NUMPY_MATERIALIZERS = {"array", "asarray"}

_SUPPRESS_TOKEN = "thb:lint-ok["
_DISABLE_RE = re.compile(r"tpu-hc:\s*disable=([A-Za-z0-9_,-]+)")


def _suppressed_lines(source: str) -> dict[int, set[str]]:
    """Per-line suppressions, by 1-based line number: the round-21
    ``# tpu-hc: disable=<name>[,<name>…]`` spelling plus the legacy
    ``# thb:lint-ok[name]``."""
    out: dict[int, set[str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        pos = line.find(_SUPPRESS_TOKEN)
        while pos != -1:
            end = line.find("]", pos)
            if end == -1:
                break
            out.setdefault(i, set()).add(
                line[pos + len(_SUPPRESS_TOKEN):end].strip())
            pos = line.find(_SUPPRESS_TOKEN, end)
        for m in _DISABLE_RE.finditer(line):
            out.setdefault(i, set()).update(
                name.strip() for name in m.group(1).split(",")
                if name.strip())
    return out


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of a call target ('jax.jit', 'np.array')."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _callee_basename(call: ast.Call) -> str:
    name = _dotted(call.func)
    base = name.rsplit(".", 1)[-1]
    if base == "partial":  # functools.partial(jax.jit, ...) etc.
        if call.args:
            return _callee_basename(
                call.args[0]) if isinstance(call.args[0], ast.Call) \
                else _dotted(call.args[0]).rsplit(".", 1)[-1]
    return base


class _FileLinter:
    """All AST passes over one Python source file."""

    def __init__(self, source: str, filename: str, model: str = "repo",
                 cpu_count: int | None = None):
        self.source = source
        self.filename = filename
        self.model = model
        self.cpu_count = cpu_count or (os.cpu_count() or 1)
        self.tree = ast.parse(source, filename=filename)
        self.suppressed = _suppressed_lines(source)
        try:
            self.symtab = symtable.symtable(source, filename, "exec")
        except Exception:
            self.symtab = None
        # parent links + enclosing-function chains
        self._parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
        self.findings: list[Finding] = []
        self.suppression_hits: collections.Counter = collections.Counter()

    # -- shared helpers ------------------------------------------------

    def _emit(self, lint: str, node: ast.AST, message: str,
              severity: str | None = None):
        """Record a finding.  ``severity`` defaults to the pass's
        registered severity; pass it explicitly only for a site that
        deliberately deviates (the recompile pass's info-grade
        shape-vs-literal branch)."""
        line = getattr(node, "lineno", 0)
        if lint in self.suppressed.get(line, ()):
            self.suppression_hits[lint] += 1
            return
        if severity is None:
            severity = registry.default_severity(lint)
        self.findings.append(Finding(
            lint=lint, severity=severity, model=self.model,
            location=f"{self.filename}:{line}", message=message))

    def _enclosing_functions(self, node: ast.AST) -> list[ast.AST]:
        chain = []
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                chain.append(cur)
            cur = self._parents.get(cur)
        return chain

    def _is_flax_module_class(self, cls: ast.ClassDef) -> bool:
        for base in cls.bases:
            name = _dotted(base)
            if name.endswith("Module") or name in ("nn.Module",):
                return True
        return False

    # -- jit-context discovery ----------------------------------------

    def _jit_contexts(self) -> list[ast.AST]:
        """FunctionDefs whose bodies run under trace.

        A function is a jit context if it is (a) decorated with a tracing
        transform or ``nn.compact``, (b) referenced by name as an
        argument to a tracing callee (``jax.jit(f)``,
        ``jax.shard_map(step, ...)``, ``lax.scan(body, ...)``,
        ``pl.pallas_call(kernel, ...)`` — including through
        ``functools.partial(kernel, ...)``), (c) a method of a flax
        ``nn.Module`` subclass named ``__call__``/``setup``, or (d)
        nested inside any of those.
        """
        traced_names: set[str] = set()   # function names used as traced args
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            base = _callee_basename(node)
            args = list(node.args)
            if base in _TRACING_CALLEES:
                for a in args:
                    if isinstance(a, ast.Name):
                        traced_names.add(a.id)
                    elif isinstance(a, ast.Call) and \
                            _callee_basename(a) == "partial":
                        for pa in a.args:
                            if isinstance(pa, ast.Name):
                                traced_names.add(pa.id)

        contexts: list[ast.AST] = []
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            is_ctx = node.name in traced_names
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                base = _dotted(target).rsplit(".", 1)[-1]
                if base in _TRACING_CALLEES or base == "compact":
                    is_ctx = True
                if base == "partial" and isinstance(dec, ast.Call) \
                        and dec.args:
                    if _dotted(dec.args[0]).rsplit(".", 1)[-1] \
                            in _TRACING_CALLEES:
                        is_ctx = True
            parent = self._parents.get(node)
            if isinstance(parent, ast.ClassDef) \
                    and self._is_flax_module_class(parent) \
                    and node.name in ("__call__", "setup"):
                is_ctx = True
            if is_ctx:
                contexts.append(node)
        # close over nesting: functions defined inside a context trace too
        closed: list[ast.AST] = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node in contexts or any(
                        f in contexts for f in
                        self._enclosing_functions(node)):
                    closed.append(node)
        return closed

    # -- pass: host sync inside traced code ---------------------------

    @register_pass(
        HOST_SYNC, "error", "jit",
        doc="host round-trip (.item(), device_get, np.array on traced "
            "values) inside traced code — bakes a constant or throws on "
            "first hardware run",
        example="`.item()` inside a shard_map'd step fn")
    def _check_host_sync(self, ctx: ast.AST):
        for node in ast.walk(ctx):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            base = name.rsplit(".", 1)[-1]
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _HOST_SYNC_METHODS \
                    and not node.args:
                self._emit(
                    HOST_SYNC, node,
                    f".{node.func.attr}() forces a device->host sync at "
                    f"trace time inside `{getattr(ctx, 'name', '?')}`; "
                    "return the array and sync outside the jitted region")
            elif base in _HOST_SYNC_FUNCS and name.startswith(
                    ("jax.", "device_get", "block_until_ready")):
                self._emit(
                    HOST_SYNC, node,
                    f"{name}() inside traced `{getattr(ctx, 'name', '?')}` "
                    "is a host round-trip; hoist it out of the jit")
            elif "." in name and name.split(".", 1)[0] in _NUMPY_ALIASES \
                    and base in _NUMPY_MATERIALIZERS:
                self._emit(
                    HOST_SYNC, node,
                    f"{name}() materializes a traced value on host inside "
                    f"`{getattr(ctx, 'name', '?')}`; use jnp instead")

    # -- pass: recompilation hazards ----------------------------------

    def _locals_of(self, func: ast.AST) -> set[str]:
        """Parameter + locally-bound names of a FunctionDef (via symtable,
        matched by name and line)."""
        if self.symtab is None:
            return set()

        def find(table):
            if table.get_type() == "function" \
                    and table.get_name() == getattr(func, "name", None) \
                    and table.get_lineno() == func.lineno:
                return table
            for child in table.get_children():
                got = find(child)
                if got is not None:
                    return got
            return None

        table = find(self.symtab)
        if table is None:
            return set()
        return {s.get_name() for s in table.get_symbols()
                if s.is_local() or s.is_parameter()}

    def _free_vars_of(self, func: ast.AST) -> set[str]:
        if self.symtab is None:
            return set()

        def find(table):
            if table.get_type() == "function" \
                    and table.get_name() == getattr(func, "name", None) \
                    and table.get_lineno() == func.lineno:
                return table
            for child in table.get_children():
                got = find(child)
                if got is not None:
                    return got
            return None

        table = find(self.symtab)
        if table is None:
            return set()
        return {s.get_name() for s in table.get_symbols() if s.is_free()}

    @register_pass(
        RECOMPILE, "warning", "jit",
        doc="recompilation hazards: traced fn closing over a mutated "
            "Python scalar (warning), shape-vs-numeric-literal branching "
            "(info)",
        example="`for step in range(n): jitted_fn()` where the traced fn "
                "reads `step` as a free variable")
    def _check_recompile(self, ctx: ast.AST):
        # (a) closure leaks: free vars the enclosing scope mutates
        free = self._free_vars_of(ctx)
        if free:
            for enclosing in self._enclosing_functions(ctx):
                mutated: dict[str, ast.AST] = {}
                for node in ast.walk(enclosing):
                    if isinstance(node, ast.AugAssign) \
                            and isinstance(node.target, ast.Name):
                        mutated.setdefault(node.target.id, node)
                    elif isinstance(node, ast.For) \
                            and isinstance(node.target, ast.Name):
                        mutated.setdefault(node.target.id, node)
                for name in sorted(free & set(mutated)):
                    self._emit(
                        RECOMPILE, mutated[name],
                        f"traced `{getattr(ctx, 'name', '?')}` closes over "
                        f"`{name}`, which this scope mutates — each new "
                        "value bakes a fresh constant and recompiles; pass "
                        "it as a traced argument instead")
        # (b) shape-vs-literal branching (shape-vs-shape is the normal
        # static residual-path idiom and stays silent)
        for node in ast.walk(ctx):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            for cmp in ast.walk(node.test):
                if not isinstance(cmp, ast.Compare):
                    continue
                sides = [cmp.left, *cmp.comparators]
                shapeish = [s for s in sides if self._mentions_shape(s)]
                literal = [s for s in sides
                           if isinstance(s, ast.Constant)
                           and isinstance(s.value, (int, float))]
                if shapeish and literal:
                    self._emit(
                        RECOMPILE, cmp,
                        "branching on a shape vs a numeric literal forks "
                        "one compilation per shape class; make sure every "
                        "class is intended (use static_argnums/config if "
                        "it encodes a mode)", severity="info")

    @staticmethod
    def _mentions_shape(node: ast.AST) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Attribute) and n.attr == "shape":
                return True
            if isinstance(n, ast.Call) and _dotted(n.func) == "len":
                return True
        return False

    # -- pass: donated-buffer misuse ----------------------------------

    @staticmethod
    def _own_nodes(scope: ast.AST):
        """Walk a scope WITHOUT descending into nested scopes, so a
        nested function's parameters never alias this scope's names."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop(0)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    @register_pass(
        DONATION, "warning", "file",
        doc="a buffer passed in a donate_argnums position of a jitted "
            "call and read again afterwards — donation invalidated it",
        example="`loss = step(state, batch); print(state)` with "
                "donate_argnums=(0,)")
    def _check_donation(self):
        """Within each function scope: a name passed in a donated
        position of a jitted callable, then *read* again afterwards.

        Only the scope's OWN statements participate — a nested function
        calling the jitted callable with its own parameters is a fresh
        binding per call and is fine by construction.
        """
        scopes = [self.tree] + [
            n for n in ast.walk(self.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for scope in scopes:
            jitted: dict[str, tuple[int, ...]] = {}
            for node in self._own_nodes(scope):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call) \
                        and _callee_basename(node.value) in ("jit", "pjit"):
                    donate = self._donated_positions(node.value)
                    if donate and len(node.targets) == 1 \
                            and isinstance(node.targets[0], ast.Name):
                        jitted[node.targets[0].id] = donate
            if not jitted:
                continue
            self._scan_donation_scope(scope, jitted)

    @staticmethod
    def _donated_positions(call: ast.Call) -> tuple[int, ...]:
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)):
                    return tuple(e.value for e in v.elts
                                 if isinstance(e, ast.Constant)
                                 and isinstance(e.value, int))
        return ()

    def _scan_donation_scope(self, scope: ast.AST,
                             jitted: dict[str, tuple[int, ...]]):
        # document-order scan of the scope's OWN statements; per stmt:
        # flag reads of donated names, then record new donations, then
        # clear rebound targets (so `state = jitted(state, ...)` — the
        # idiomatic donate-and-rebind — never flags)
        stmts: list[ast.stmt] = [n for n in self._own_nodes(scope)
                                 if isinstance(n, ast.stmt)]
        stmts.sort(key=lambda n: (n.lineno, n.col_offset))
        donated_at: dict[str, ast.AST] = {}
        for stmt in stmts:
            sub = [stmt] + [n for n in self._own_nodes(stmt)]
            for node in sub:
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Load) \
                        and node.id in donated_at:
                    call = donated_at.pop(node.id)
                    self._emit(
                        DONATION, node,
                        f"`{node.id}` was donated to a jitted call "
                        f"(line {call.lineno}) and is read again here "
                        "— the buffer is invalidated by donation; "
                        "rebind the result or drop donate_argnums")
            for node in sub:
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Name) \
                        and node.func.id in jitted:
                    for pos in jitted[node.func.id]:
                        if pos < len(node.args) and isinstance(
                                node.args[pos], ast.Name):
                            donated_at[node.args[pos].id] = node
            for tgt in self._assigned_names(stmt):
                donated_at.pop(tgt, None)

    @staticmethod
    def _assigned_names(stmt: ast.stmt) -> set[str]:
        out: set[str] = set()
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)) \
                and isinstance(stmt.target, ast.Name):
            out.add(stmt.target.id)
        return out

    # -- pass: checkpoint writes without a topology sidecar ------------

    # module aliases under which this repo's checkpoint API is called
    # (`ckptr`, the orbax PyTreeCheckpointer convention, deliberately
    # does NOT match: its .save is the raw writer the protocol wraps)
    _CKPT_MODULE_ALIASES = {"ckpt", "ckpt_mod", "checkpoint"}

    @register_pass(
        CKPT_TOPOLOGY, "warning", "file",
        doc="a checkpoint-writing call site without a topology= sidecar "
            "— the save resumes on the identical mesh only",
        example="`ckpt.save(path, state)` with no topology record")
    def _check_checkpoint_topology(self):
        """Checkpoint-writing call sites must pass ``topology=``: the
        elastic-resume sidecar is only as complete as the save paths
        that record it, and a new call site that forgets it produces
        checkpoints that resume on the identical mesh only."""
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            base = name.rsplit(".", 1)[-1]
            prefix = name.rsplit(".", 2)[-2] if "." in name else ""
            hit = (base in ("save_pp", "write_host_payload")
                   or (base == "save"
                       and prefix in self._CKPT_MODULE_ALIASES)
                   or (base == "submit" and "ckpt" in prefix.lower()))
            if not hit:
                continue
            if any(kw.arg == "topology" for kw in node.keywords):
                continue
            if any(kw.arg is None for kw in node.keywords):
                continue    # **kwargs splat: can't see inside
            self._emit(
                CKPT_TOPOLOGY, node,
                f"checkpoint write `{name}(...)` without a `topology=` "
                "sidecar record — the checkpoint will refuse/skip "
                "elastic resume; pass topology.topology_record(...) "
                "(or None deliberately, with a thb:lint-ok note)")

    # -- pass: input decode-pool width ---------------------------------

    # call sites that construct a per-worker input pipeline with its
    # own decode pool (the service factories own the HOST budget and
    # are deliberately exempt)
    _INPUT_PIPELINE_CALLEES = {"ImageNetDataset"}

    @register_pass(
        INPUT_POOL, "warning", "file",
        doc="a private input decode pool wider than the host budget cap "
            "(or full-host-width) — oversubscribes CPUs at "
            "workers-per-host > 1",
        example="`ImageNetDataset(decode_workers=cpu_count())` in a "
                "per-worker pipeline")
    def _check_input_pool(self):
        """An ImageNet/TFRecord pipeline constructed with an explicit
        decode pool wider than the host, or a full-host-width private
        pool — at workers-per-host > 1 either oversubscribes the CPUs
        the input service exists to budget (``--input_service=on``
        routes every worker through ONE host pool).

        The explicit-constant threshold is ``max(32, cpu_count)`` — 32
        is the data layer's own pool cap (``imagenet
        .host_decode_budget``), so the verdict on a literal width is
        stable across dev/CI machines up to 32 cores instead of
        flapping with whatever host happens to run the gate.
        """
        limit = max(32, self.cpu_count)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if _callee_basename(node) not in self._INPUT_PIPELINE_CALLEES:
                continue
            for kw in node.keywords:
                if kw.arg != "decode_workers":
                    continue
                v = kw.value
                if isinstance(v, ast.Constant) \
                        and isinstance(v.value, int) \
                        and v.value > limit:
                    self._emit(
                        INPUT_POOL, node,
                        f"explicit decode pool width {v.value} exceeds "
                        f"the host budget cap max(32, cpu_count)="
                        f"{limit} — the pool oversubscribes the host; "
                        "size the host budget via "
                        "--service_decode_workers (input service) or "
                        "divide by the local worker count")
                elif self._full_width_expr(v):
                    self._emit(
                        INPUT_POOL, node,
                        "private decode pool sized to the FULL host "
                        "(cpu_count()) — at workers-per-host > 1 the "
                        "per-process pools oversubscribe the CPUs and "
                        "bypass the shared input service's one-pool-per-"
                        "host budget; route input through data.service "
                        "or divide the width by the local worker count")

    @staticmethod
    def _full_width_expr(node: ast.AST) -> bool:
        has_cpu = any(
            isinstance(n, ast.Call)
            and _dotted(n.func).rsplit(".", 1)[-1] == "cpu_count"
            for n in ast.walk(node))
        divided = any(
            isinstance(n, ast.BinOp)
            and isinstance(n.op, (ast.FloorDiv, ast.Div))
            for n in ast.walk(node))
        return has_cpu and not divided

    # -- pass: memory probes inside the hot loop -----------------------

    # host-stalling device-memory probe callees (obs.memory + the raw
    # jax surfaces they wrap)
    _MEMORY_PROBE_CALLEES = {"live_arrays", "device_memory_profile",
                             "device_memory_sample", "device_memory_stats",
                             "live_buffer_breakdown"}

    @register_pass(
        HOT_MEMORY, "warning", "file",
        doc="a device-memory probe in a loop body without a sync-window "
            "boundary guard — a per-iteration host stall",
        example="`jax.live_arrays()` called every step of the timed loop")
    def _check_memory_probe_hot_loop(self):
        """A device-memory probe in a loop body must sit behind a
        sync-window boundary guard (a modulo test, or a condition
        spelling ``sync``/``window``) — the driver's one-poll-per-window
        contract.  Loop headers and probes inside nested function defs
        (executed on call, not per iteration) are exempt."""
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            base = name.rsplit(".", 1)[-1]
            probe = (base in self._MEMORY_PROBE_CALLEES
                     or (base == "sample" and "mem" in name.lower()))
            if not probe:
                continue
            loop = self._enclosing_loop_body(node)
            if loop is None or self._window_guarded(node, loop):
                continue
            self._emit(
                HOT_MEMORY, node,
                f"device-memory probe `{name}(...)` inside a loop body "
                "without a sync-window boundary guard — each call walks "
                "the live-buffer table on the host, a per-iteration "
                "stall in what may be the timed step loop; poll once "
                "per sync window (`i % sync_every == 0`) like the "
                "driver's HBM ledger, or move the probe out of the loop")

    def _enclosing_loop_body(self, node: ast.AST) -> ast.AST | None:
        """The nearest For/While whose BODY contains ``node`` — None
        when the walk first crosses a function boundary (a nested def's
        body runs on call, not per iteration) or when ``node`` only
        appears in a loop's header (`for a in probe():`)."""
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return None
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                header = (cur.test if isinstance(cur, ast.While)
                          else cur.iter)
                if not any(n is node for n in ast.walk(header)):
                    return cur
            cur = self._parents.get(cur)
        return None

    def _window_guarded(self, node: ast.AST, loop: ast.AST) -> bool:
        cur = self._parents.get(node)
        while cur is not None and cur is not loop:
            if isinstance(cur, ast.If) and self._boundary_test(cur.test):
                return True
            cur = self._parents.get(cur)
        return False

    @staticmethod
    def _boundary_test(test: ast.AST) -> bool:
        for n in ast.walk(test):
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mod):
                return True
            spelled = None
            if isinstance(n, ast.Name):
                spelled = n.id
            elif isinstance(n, ast.Attribute):
                spelled = n.attr
            if spelled and ("sync" in spelled or "window" in spelled):
                return True
        return False

    # -- pass: dequantize in a hot loop --------------------------------

    # identifiers that mark a value as a quantized/cached int8 buffer
    # (lexical, like the memory-probe pass — a quantized buffer hidden
    # behind an innocent name is on the reviewer).  A bare `q` is NOT
    # quantish: it is the attention convention for the query
    _QUANTISH = re.compile(r"int8|quant|_q8?($|_)|(^|_)q8($|_)")
    _LOOP_TRACERS = {"scan", "fori_loop", "while_loop"}

    @functools.cached_property
    def _loop_traced_funcs(self) -> set[ast.AST]:
        """FunctionDefs passed (by name, incl. through partial) to
        ``lax.scan``/``fori_loop``/``while_loop`` — their bodies run
        once per iteration, same as a Python loop body."""
        names: set[str] = set()
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if _callee_basename(node) not in self._LOOP_TRACERS:
                continue
            for a in node.args:
                if isinstance(a, ast.Name):
                    names.add(a.id)
                elif isinstance(a, ast.Call) \
                        and _callee_basename(a) == "partial":
                    for pa in a.args:
                        if isinstance(pa, ast.Name):
                            names.add(pa.id)
        return {n for n in ast.walk(self.tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n.name in names}

    @register_pass(
        DEQUANT_HOT, "error", "file",
        doc="elementwise dequantize (`q.astype(f32) * scale`) of a "
            "cached int8 buffer inside a scan/loop body — a full-width "
            "f32 copy per iteration",
        example="`w_q8.astype(jnp.float32) * w_scale` inside the decode "
                "scan body instead of the scale-fused matmul form")
    def _check_dequant_hot_loop(self):
        """**dequantize-in-hot-loop** (error): ``X.astype(...)`` of a
        quantized/cached int8 buffer used as a bare operand of an
        elementwise ``*`` inside a scan/loop body.  That shape is the
        dense-dequant anti-pattern: a full-width f32 copy of the
        cached buffer materializes on every iteration of the hot loop
        (every decode layer / scan step).  The accepted forms keep the
        dequantize *scale-fused*: the int8 operand feeds the matmul
        and the per-channel scale multiplies the matmul OUTPUT
        (``einsum(spec, x, q.astype(dt)) * scale`` —
        ``serve.decode._qeinsum``), or the astype lives inside a
        Pallas kernel next to its matmul (``ops.paged_attention``).
        Detection is lexical (the buffer's identifiers must spell
        int8/quant/_q, like the memory-probe pass); loop headers and
        nested defs are exempt through the same loop-body walk.
        """
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"):
                continue
            parent = self._parents.get(node)
            if not (isinstance(parent, ast.BinOp)
                    and isinstance(parent.op, ast.Mult)):
                continue
            idents = set()
            for n in ast.walk(node.func.value):
                if isinstance(n, ast.Name):
                    idents.add(n.id)
                elif isinstance(n, ast.Attribute):
                    idents.add(n.attr)
            if not any(self._QUANTISH.search(i) for i in idents):
                continue
            in_loop = self._enclosing_loop_body(node) is not None
            if not in_loop:
                in_loop = any(f in self._loop_traced_funcs
                              for f in self._enclosing_functions(node))
            if not in_loop:
                continue
            src = _dotted(node.func.value) or "<expr>"
            self._emit(
                DEQUANT_HOT, node,
                f"`{src}.astype(...) * scale` dequantizes a cached "
                "int8 buffer elementwise inside a scan/loop body — a "
                "full-width f32 copy materializes every iteration; "
                "use the scale-fused matmul form instead (int8 feeds "
                "the einsum/dot, the per-channel scale multiplies the "
                "matmul OUTPUT — serve.decode._qeinsum), or dequantize "
                "inside the kernel next to its matmul "
                "(ops.paged_attention)")

    # -- pass: flight-recorder calls inside traced code ----------------

    # obs.timeline's recorder surface: host-clock reads + ring stores —
    # traced into a jit/AOT program they bake ONE constant timestamp at
    # trace time (and the span never measures anything again), exactly
    # the silent-lie class the recorder's host-side contract forbids
    _SPAN_CALLEES = {"record_span", "instant", "transition",
                     "dump_timeline"}
    _SPAN_MODULE_HINTS = ("timeline", "recorder", "flight")

    @functools.cached_property
    def _timeline_imported_names(self) -> set[str]:
        """Local names bound by ``from ...obs.timeline import X [as Y]``
        — a bare ``transition(...)`` call through such a binding is the
        recorder's even when no dotted prefix betrays it."""
        out: set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.rsplit(".", 1)[-1] == "timeline":
                out.update(a.asname or a.name for a in node.names)
        return out

    @register_pass(
        SPAN_IN_JIT, "error", "jit",
        doc="an obs.timeline flight-recorder call inside traced code — "
            "the host-clock read traces to one frozen timestamp",
        example="`timeline.span(\"decode\")` inside the AOT'd decode fn")
    def _check_span_in_jit(self, ctx: ast.AST):
        """**span-in-compiled-fn** (error): an ``obs.timeline`` recorder
        call (``span``/``record_span``/``instant``/``transition``)
        inside a traced function.  The recorder reads the HOST monotonic
        clock; under trace that read happens once, at trace time, so the
        compiled program carries a frozen timestamp — the span lies
        forever and recompile-guards can't save it.  Record around the
        dispatch (the driver's idiom), never inside it."""
        for node in ast.walk(ctx):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            base = name.rsplit(".", 1)[-1]
            timeline_owned = (
                any(h in name.lower() for h in self._SPAN_MODULE_HINTS)
                # a BARE call through `from ...timeline import X [as Y]`
                # is the recorder's even with no dotted prefix
                or (isinstance(node.func, ast.Name)
                    and node.func.id in self._timeline_imported_names))
            if not (base in self._SPAN_CALLEES
                    or (base == "span" and timeline_owned)):
                continue
            if base not in ("record_span", "dump_timeline") \
                    and not timeline_owned:
                continue    # a generic .instant()/.transition() that is
                            # not the flight recorder's
            self._emit(
                SPAN_IN_JIT, node,
                f"flight-recorder call `{name}(...)` inside traced "
                f"`{getattr(ctx, 'name', '?')}` — the host-clock read "
                "traces to ONE constant timestamp and the span lies in "
                "every execution; record around the jitted call, not "
                "inside it (obs.timeline is host-side by contract)")

    # -- span-name-registry --------------------------------------------

    _SPAN_NAME_CALLEES = {"record_span", "instant", "span"}

    @register_pass(
        SPAN_REGISTRY, "warning", "file",
        doc="a literal span name at a recorder call site that is not in "
            "obs.timeline.KNOWN_SPANS — a typo'd name silently vanishes "
            "from every fold",
        example="`record_span(\"prefil\", ...)` — records fine, never "
                "appears in any timeline")
    def _check_span_name_registry(self):
        """**span-name-registry** (warning): a literal span name passed
        to ``timeline.span``/``record_span``/``instant`` that is not in
        ``obs.timeline.KNOWN_SPANS``.

        Every fold keys on span names (``timeline_lines`` totals, the
        heartbeat phase column, the Chrome-trace lanes) — a typo'd name
        records fine and then silently vanishes from every consumer,
        which is the worst failure mode telemetry can have.  The
        registry is one frozenset in ``obs.timeline``; adding a span is
        a one-line registration there.  Variable names (the engine's
        ``record_span(kind, ...)``) are skipped — the lint is for
        literals, where the typo class lives.
        """
        try:
            from tpu_hc_bench.obs.timeline import KNOWN_SPANS
        except Exception:        # analysis must run without obs too
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            base = name.rsplit(".", 1)[-1]
            if base not in self._SPAN_NAME_CALLEES:
                continue
            timeline_owned = (
                any(h in name.lower() for h in self._SPAN_MODULE_HINTS)
                or (isinstance(node.func, ast.Name)
                    and node.func.id in self._timeline_imported_names))
            if not timeline_owned and base != "record_span":
                continue    # a generic .instant()/.span() that is not
                            # the flight recorder's
            if not node.args:
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue    # variable span names are the caller's
                            # contract, not a typo class
            if arg.value in KNOWN_SPANS:
                continue
            self._emit(
                SPAN_REGISTRY, node,
                f"span name {arg.value!r} at `{name or base}(...)` is "
                f"not in obs.timeline.KNOWN_SPANS — an unregistered "
                f"(or typo'd) name records fine and then silently "
                f"vanishes from every timeline fold; register it in "
                f"KNOWN_SPANS or fix the spelling")

    # -- signal-name-registry ------------------------------------------

    # health-signal lookups keyed by a LITERAL name, mapped to the
    # positional index the name rides in: spec_of(name),
    # advice_for(name), fired_count(events, name)
    _SIGNAL_NAME_CALLEES = {"spec_of": 0, "advice_for": 0,
                            "fired_count": 1}
    _SIGNAL_MODULE_HINTS = ("signals",)

    @functools.cached_property
    def _signals_imported_names(self) -> set[str]:
        """Local names bound by ``from ...obs.signals import X [as Y]``
        — a bare ``spec_of(...)`` call through such a binding is the
        signal engine's even when no dotted prefix betrays it."""
        out: set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.rsplit(".", 1)[-1] == "signals":
                out.update(a.asname or a.name for a in node.names)
        return out

    @register_pass(
        SIGNAL_REGISTRY, "warning", "file",
        doc="a literal signal name at a signals-engine call site that "
            "is not in obs.signals.KNOWN_SIGNALS — a typo'd name never "
            "matches anything any engine emits",
        example="`fired_count(events, \"KV_PRESURE\")` — always 0, "
                "never an error")
    def _check_signal_name_registry(self):
        """**signal-name-registry** (warning): a literal signal name
        passed to ``signals.spec_of``/``advice_for``/``fired_count``
        that is not in ``obs.signals.KNOWN_SIGNALS``.

        Signal names are the join key between the engine's append-only
        ``signals.jsonl`` and every consumer (the watch column, the
        supervisor's advice journal, the bench verdict counts) — a
        typo'd literal compares clean against every event and the
        consumer silently reads "never fired", the same failure class
        the span-name registry exists for.  The registry is one tuple
        in ``obs.signals``; adding a signal is a one-line registration
        there.  Variable names (the engine's own ``spec_of(name)``
        loop) are skipped — the lint is for literals, where the typo
        class lives.
        """
        try:
            from tpu_hc_bench.obs.signals import KNOWN_SIGNALS
        except Exception:        # analysis must run without obs too
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            base = name.rsplit(".", 1)[-1]
            if base not in self._SIGNAL_NAME_CALLEES:
                continue
            signals_owned = (
                any(h in name.lower() for h in self._SIGNAL_MODULE_HINTS)
                or (isinstance(node.func, ast.Name)
                    and node.func.id in self._signals_imported_names))
            if not signals_owned:
                continue    # a generic .spec_of()/.fired_count() that
                            # is not the signal engine's
            idx = self._SIGNAL_NAME_CALLEES[base]
            if len(node.args) <= idx:
                continue
            arg = node.args[idx]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue    # variable signal names are the caller's
                            # contract, not a typo class
            if arg.value in KNOWN_SIGNALS:
                continue
            self._emit(
                SIGNAL_REGISTRY, node,
                f"signal name {arg.value!r} at `{name or base}(...)` "
                f"is not in obs.signals.KNOWN_SIGNALS — an "
                f"unregistered (or typo'd) name never matches any "
                f"emitted event and the consumer silently reads "
                f"\"never fired\"; register it in KNOWN_SIGNALS or "
                f"fix the spelling")

    # -- fleet-blocking-wait -------------------------------------------

    # no-arg blocking callees: `.wait()` (Popen, Event, Condition) and
    # `.join()` (Thread, Process) block FOREVER without a timeout
    _BLOCKING_CALLEES = {"wait", "join"}

    def _in_fleet_package(self) -> bool:
        parts = Path(self.filename).as_posix().split("/")
        return "fleet" in parts and "tests" not in parts

    @register_pass(
        FLEET_WAIT, "error", "file",
        doc="a no-timeout .wait()/.join() inside a fleet control-loop "
            "body — one wedged job freezes scheduling for the pool",
        example="`proc.wait()` in the supervisor reap loop")
    def _check_fleet_blocking_wait(self):
        """**fleet-blocking-wait** (error, fleet package only): a
        ``.wait()``/``.join()`` call with no timeout inside a loop body
        of the fleet scheduler/supervisor.

        The control loop is the one thread keeping N jobs alive: an
        unbounded wait on any single job (a Popen that never exits, a
        thread stuck in I/O) freezes scheduling for the WHOLE fleet —
        no reaps, no liveness checks, no admissions — which is exactly
        the hang class the per-job watchdog cannot see from inside the
        job.  The accepted idiom is poll + bounded sleep (the
        supervisor's ``reap``) or an explicit timeout argument; a
        ``wait(5)``/``join(timeout=...)`` call is bounded and passes.
        Loop headers and nested function definitions are exempt through
        the same loop-body walk as the hot-loop passes.
        """
        if not self._in_fleet_package():
            return
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self._BLOCKING_CALLEES):
                continue
            if node.args or node.keywords:
                continue        # any argument bounds (or re-purposes) it
            if self._enclosing_loop_body(node) is None:
                continue
            name = _dotted(node.func) or f"<expr>.{node.func.attr}"
            self._emit(
                FLEET_WAIT, node,
                f"unbounded `{name}()` inside a fleet control loop — "
                "one wedged job blocks scheduling for every other job; "
                "pass a timeout (`.wait(grace_s)` / "
                "`.join(timeout=...)`) or poll with a bounded sleep "
                "like supervisor.reap")

    # -- retire-without-status -----------------------------------------

    # terminal call sites in the serve engine: every request leaving
    # the ledger goes through one of these
    _TERMINAL_CALLEES = {"finish", "shed_queued"}

    @register_pass(
        RETIRE_STATUS, "error", "file",
        doc="a serve-engine terminal call site (finish/shed_queued) "
            "without a status/cause stamp — a request would leave the "
            "ledger uncaused",
        example="`finish(fl, t_done)` with no `status=` keyword")
    def _check_retire_status(self):
        """**retire-without-status** (error, serve package only): a
        ``finish(...)``/``shed_queued(...)`` call that stamps no
        terminal disposition.

        Round 23's degradation contract is that EVERY request leaving
        the engine's ledger carries a terminal ``status`` (ok / shed /
        quarantined) and, for degraded exits, a ``cause`` — `obs
        summarize` and the faults A/B both fold on those stamps, so an
        unstamped retire is a request that silently vanishes from the
        degradation account.  A call passes when it spells a
        ``status=``/``cause=`` keyword or passes the cause positionally
        (three or more positional arguments); relying on the ``"ok"``
        default is exactly the hazard — a later degraded caller copies
        the spelling and mislabels a shed as served.
        """
        if not self._in_serve_package():
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if _callee_basename(node) not in self._TERMINAL_CALLEES:
                continue
            if len(node.args) >= 3 or any(
                    kw.arg in ("status", "cause")
                    for kw in node.keywords):
                continue
            name = _dotted(node.func) or _callee_basename(node)
            self._emit(
                RETIRE_STATUS, node,
                f"`{name}(...)` retires a request with no terminal "
                "status — stamp `status=` (and `cause=` for degraded "
                "exits) so the ledger, `obs summarize`, and the faults "
                "A/B agree on every request's disposition")

    # -- page-refcount-discipline --------------------------------------

    # mutating methods on a free-list container
    _FREELIST_MUTATORS = {"append", "extend", "insert", "pop", "remove",
                          "clear"}

    def _inside_page_allocator(self, node: ast.AST) -> bool:
        """True when ``node`` sits lexically inside ``class
        PageAllocator`` — the one namespace sanctioned to touch the
        free list and write page tables."""
        cur: ast.AST | None = node
        while cur is not None:
            if isinstance(cur, ast.ClassDef) and \
                    cur.name == "PageAllocator":
                return True
            cur = self._parents.get(cur)
        return False

    @register_pass(
        PAGE_REFCOUNT, "error", "file",
        doc="a page-table store or free-list mutation outside "
            "PageAllocator — bypasses the refcount that keeps "
            "shared/COW pages alive",
        example="`fl.table[slot] = page` instead of "
                "`allocator.bind(fl.table, slot, page)`")
    def _check_page_refcount(self):
        """**page-refcount-discipline** (error, serve package only):
        a page-table slot store or a free-list mutation reached from
        outside ``class PageAllocator``.

        Round 25 makes KV pages reference-counted: the prefix cache
        and every in-flight request may hold refs on the same physical
        page, and a page returns to the free list only when its
        refcount hits zero inside ``PageAllocator.free``.  A direct
        ``table[slot] = page`` store skips the liveness assert in
        ``PageAllocator.bind`` (binding a freed page silently corrupts
        another request's KV), and an out-of-band
        ``free_list.append(...)`` double-frees a page someone still
        reads.  Flagged: (a) mutating-method calls
        (append/extend/insert/pop/remove/clear) on a name ending in
        ``_free`` or ``free_list``; (b) subscript assignment into a
        bare ``table`` variable or ``.table`` attribute.  Plural
        spellings (``tables[i] = ...``) and anything lexically inside
        ``PageAllocator`` are exempt.
        """
        if not self._in_serve_package():
            return
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in self._FREELIST_MUTATORS:
                owner = _dotted(node.func.value)
                base = owner.rsplit(".", 1)[-1]
                if not (base.endswith("_free") or base == "free_list"):
                    continue
                if self._inside_page_allocator(node):
                    continue
                self._emit(
                    PAGE_REFCOUNT, node,
                    f"`{owner}.{node.func.attr}(...)` mutates a KV "
                    "free list outside PageAllocator — pages return "
                    "to the pool only via `PageAllocator.free`, which "
                    "decrefs and recycles at refcount zero; an "
                    "out-of-band free double-frees a page a shared "
                    "prefix or another request still reads")
                continue
            if not isinstance(node, (ast.Assign, ast.AugAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                if not isinstance(tgt, ast.Subscript):
                    continue
                val = tgt.value
                name = val.attr if isinstance(val, ast.Attribute) \
                    else val.id if isinstance(val, ast.Name) else ""
                if name != "table":
                    continue
                if self._inside_page_allocator(node):
                    continue
                self._emit(
                    PAGE_REFCOUNT, node,
                    f"`{_dotted(val) or name}[...] = ...` stores a "
                    "page id without the refcount-liveness check — "
                    "route table writes through "
                    "`PageAllocator.bind(table, slot, page)`, which "
                    "asserts the page is live before it becomes "
                    "readable by the decode kernel")

    # -- serve-bucket-recompile ----------------------------------------

    # calls that lower/trace a program (and so can compile a NEW shape):
    # the serve package's zero-recompile-after-warmup contract says
    # these may only appear in the engine's warmup namespace
    _LOWERING_CALLEES = {
        "jit", "pjit", "pmap", "shard_map", "aot_compile", "lower",
        "compile", "xla_computation", "make_jaxpr", "eval_shape",
    }
    _WARMUP_FUNCS = ("__init__", "_aot")

    def _in_serve_package(self) -> bool:
        parts = Path(self.filename).as_posix().split("/")
        return "serve" in parts and "tests" not in parts

    @register_pass(
        SERVE_RECOMPILE, "warning", "file",
        doc="a jit/lowering call site in the serve package outside the "
            "warmup namespace — re-opens the mid-traffic-recompile "
            "hazard",
        example="`jax.jit(decode_fn)` reached from the admission path")
    def _check_serve_recompile(self):
        """**serve-bucket-recompile** (warning, serve package only): a
        call site that can reach jit/lowering outside the engine's
        warmup namespace (``__init__`` / ``_aot`` / ``_warm*``).

        The serving lane's latency contract is *zero lowering after
        warmup*: every (batch, seqlen) bucket is AOT-compiled at engine
        construction, and after that the traffic path only calls AOT
        executables — an off-ladder shape raises instead of silently
        recompiling.  A ``jax.jit``/``.lower()``/``aot_compile`` call
        that creeps into the admission/decode path re-opens the
        mid-traffic-recompile hazard this subsystem exists to close
        (measured as ``post_warmup_compiles`` via compile-cache entry
        deltas).  Warmup-only namespaces are exempt; so is anything
        outside ``tpu_hc_bench/serve/``.
        """
        if not self._in_serve_package():
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            base = _callee_basename(node)
            if base not in self._LOWERING_CALLEES:
                continue
            names = [getattr(f, "name", "<lambda>")
                     for f in self._enclosing_functions(node)]
            if any(n in self._WARMUP_FUNCS or n.startswith("_warm")
                   for n in names):
                continue
            where = names[0] if names else "module level"
            self._emit(
                SERVE_RECOMPILE, node,
                f"{_dotted(node.func) or base}() in {where} can lower/"
                f"compile after engine warmup — the serving lane's "
                f"zero-recompile contract keeps jit/lowering inside "
                f"the warmup namespace (__init__/_aot/_warm*); route "
                f"this through a warmed AOT bucket instead")

    # -- driver --------------------------------------------------------

    def run(self) -> list[Finding]:
        """Registry-driven pass sequence: every registered jit-scope
        pass over every traced context, then every file-scope pass
        (including the ``analysis.dataflow`` distributed-correctness
        passes, which register themselves on import)."""
        jit = registry.jit_passes()
        for ctx in self._jit_contexts():
            for info in jit:
                info.func(self, ctx)
        for info in registry.file_passes():
            info.func(self)
        return self.findings


def lint_source_text(source: str, filename: str = "<string>",
                     model: str = "repo",
                     cpu_count: int | None = None,
                     counters: collections.Counter | None = None
                     ) -> list[Finding]:
    """AST lint passes over a source string (the test-fixture entry).
    ``cpu_count`` pins the input-pool-width threshold for deterministic
    tests (default: this host's).  ``counters`` (optional) accumulates
    per-lint suppression hits so the findings JSON can audit them."""
    linter = _FileLinter(source, filename, model, cpu_count=cpu_count)
    findings = linter.run()
    if counters is not None:
        counters.update(linter.suppression_hits)
    return findings


def lint_file(path: str | Path, model: str = "repo") -> list[Finding]:
    path = Path(path)
    return lint_source_text(path.read_text(), str(path), model)


def lint_repo_sources(root: str | Path | None = None,
                      files: list[str | Path] | None = None,
                      counters: collections.Counter | None = None
                      ) -> list[Finding]:
    """AST passes over every package + scripts source file, plus the
    repo-scope passes: tuned-config registry staleness over
    ``artifacts/tuned/`` and the stream-schema contract check.

    ``files`` (relative paths under ``root``) restricts the PER-FILE
    passes to the given sources — the ``--changed-only`` mode; the
    repo-scope passes always see the whole tree (a contract break can
    live in an UNchanged file whose partner changed).  ``counters``
    accumulates suppression hits across files.
    """
    if root is None:
        root = Path(__file__).resolve().parents[2]
    root = Path(root)
    findings: list[Finding] = []
    if files is None:
        paths: list[Path] = []
        for sub in ("tpu_hc_bench", "scripts"):
            base = root / sub
            if base.is_dir():
                paths.extend(sorted(base.rglob("*.py")))
    else:
        paths = [root / f for f in files]
    for path in paths:
        if not path.is_file():
            continue
        try:
            rel = str(path.relative_to(root))
        except ValueError:
            rel = str(path)
        findings.extend(lint_source_text(path.read_text(), rel,
                                         counters=counters))
    findings.extend(check_tuned_registry(root / "artifacts" / "tuned"))
    from tpu_hc_bench.analysis import contracts

    findings.extend(contracts.check_stream_contracts(root))
    return findings


@register_pass(
    TUNED_STALENESS, "warning", "repo",
    doc="a tuned-config registry row recording a flag that no longer "
        "exists on BenchmarkConfig (or the other lane's lever)",
    example="artifacts/tuned/v4-8.json records `fuse_steps`, renamed "
            "two rounds ago — --config=auto silently skips it")
def check_tuned_registry(
        registry_dir: str | Path | None = None) -> list[Finding]:
    """**tuned-config-staleness** (warning): a tuned-config registry row
    (``artifacts/tuned/<hardware_key>.json``, ``tpu_hc_bench.tune``)
    whose recorded flag names no longer exist on ``BenchmarkConfig``.

    ``--config=auto`` deliberately survives a stale row (it skips the
    unknown flag with a banner note rather than crash every run —
    ``tune.registry.resolve_auto``), so THIS is the loud gate that
    protects the registry across flag refactors: rename a lever and CI
    points at every registry row still spelling the old name.  An
    unreadable registry file flags too — a truncated write would
    otherwise silently disable tuning for that hardware.

    Serving rows (round 16) are keyed ``<model>@serve`` and get the
    same treatment, plus a lane check: a ``@serve`` row recording a
    training-lane lever (or a training row recording a serving knob)
    is flagged — ``resolve_auto`` skips such a key with a note, and
    this lint is what makes the skip visible in CI instead of silently
    de-tuning the lane forever.
    """
    import dataclasses
    import json

    from tpu_hc_bench.flags import BenchmarkConfig
    from tpu_hc_bench.tune.space import LEVERS, SERVE_LEVERS

    if registry_dir is None:
        from tpu_hc_bench.tune.registry import default_registry_dir

        registry_dir = default_registry_dir()
    base = Path(registry_dir)
    findings: list[Finding] = []
    if not base.is_dir():
        return findings
    fields = {f.name for f in dataclasses.fields(BenchmarkConfig)}
    for path in sorted(base.glob("*.json")):
        try:
            data = json.loads(path.read_text())
        except (OSError, ValueError) as e:
            findings.append(Finding(
                TUNED_STALENESS, "warning", "repo",
                f"artifacts/tuned/{path.name}",
                f"unreadable registry file: {e}"))
            continue
        for model, row in sorted((data.get("members") or {}).items()):
            serving = model.endswith("@serve")
            member = model[:-len("@serve")] if serving else model
            lane_levers = SERVE_LEVERS if serving else LEVERS
            crossed = SERVE_LEVERS if not serving else LEVERS
            recorded = {**(row.get("base") or {}),
                        **(row.get("overrides") or {})}
            for k in sorted(recorded):
                if k not in fields:
                    findings.append(Finding(
                        TUNED_STALENESS, "warning", member,
                        f"artifacts/tuned/{path.name}:{model}/{k}",
                        f"tuned row records flag {k!r}, which is no "
                        f"longer a BenchmarkConfig field — re-run "
                        f"`python -m tpu_hc_bench.tune search` or edit "
                        f"the row"))
                elif k in crossed and k not in lane_levers:
                    lane = "serving" if serving else "training"
                    findings.append(Finding(
                        TUNED_STALENESS, "warning", member,
                        f"artifacts/tuned/{path.name}:{model}/{k}",
                        f"{lane} row records the other lane's lever "
                        f"{k!r} — --config=auto skips it with a note; "
                        f"re-search the row or drop the key"))
    return findings


# -- per-model semantic passes (jaxpr + sharding rules) ----------------

# column-parallel -> row-parallel partners: if one side of a transformer
# block matched a TP rule and the other did not, GSPMD reshards at the
# block boundary every layer
_TP_RULE_PARTNERS = [
    ({"qkv/kernel"}, {"out/kernel"}),
    ({"Dense_0/kernel"}, {"Dense_1/kernel"}),
    ({"fc/kernel"}, {"proj/kernel"}),
    ({"wq/kernel", "wk/kernel", "wv/kernel"}, {"wo/kernel"}),
    ({"gate/kernel", "up/kernel"}, {"down/kernel"}),
]
_MIN_TP_DEGREE = 2

_HOST_CALLBACK_PRIMITIVES = {
    "pure_callback", "io_callback", "debug_callback", "callback",
    "host_callback_call", "outside_call",
}


def _abstract_model(name: str):
    """(model, spec, abstract param tree) without touching device memory."""
    import jax
    import jax.numpy as jnp

    from tpu_hc_bench.models import create_model

    model, spec = create_model(name)
    if spec.is_text:
        example = jax.ShapeDtypeStruct((1,) + tuple(spec.input_shape),
                                       jnp.int32)
    elif getattr(spec, "integer_input", False):
        example = jax.ShapeDtypeStruct((1,) + tuple(spec.input_shape),
                                       jnp.int32)
    else:
        example = jax.ShapeDtypeStruct((1,) + tuple(spec.input_shape),
                                       jnp.float32)
    rng = jax.random.PRNGKey(0)
    variables = jax.eval_shape(
        functools.partial(model.init, train=False), rng, example)
    return model, spec, variables, example


def _param_paths(tree) -> list[tuple[str, tuple[int, ...]]]:
    import jax

    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        name = "/".join(getattr(k, "key", str(k)) for k in path)
        out.append((name, tuple(leaf.shape)))
    return out


@register_pass(
    SHARDING, "warning", "model",
    doc="Megatron TP annotation table replayed against the abstract "
        "param tree: rank drift, indivisible model-axis dims, "
        "half-annotated column/row blocks",
    example="`wq/kernel` matched a TP rule but partner `wo/kernel` did "
            "not — GSPMD reshards at every layer boundary")
def check_sharding_consistency(name: str) -> list[Finding]:
    """Replay ``tp_param_spec`` over the model's abstract params."""
    from tpu_hc_bench.topology import MODEL_AXIS
    from tpu_hc_bench.train.step import tp_param_spec

    findings: list[Finding] = []
    _, spec, variables, _ = _abstract_model(name)
    params = variables.get("params", {})
    paths = _param_paths(params)
    # the rule table, re-derived: suffix -> expected rank(s)
    rule_suffixes: dict[str, set[int]] = {}
    for suffix in {s for pair in _TP_RULE_PARTNERS for side in pair
                   for s in side} | {"qkv/bias", "Dense_0/bias", "fc/bias",
                                     "moe/wi", "moe/wo"}:
        for rank in range(1, 5):
            p = tp_param_spec(suffix, rank)
            if len(p) and any(ax == MODEL_AXIS for ax in p):
                rule_suffixes.setdefault(suffix, set()).add(rank)

    matched_suffixes: set[str] = set()
    for path, shape in paths:
        ndim = len(shape)
        p = tp_param_spec(path, ndim)
        hit = [s for s in rule_suffixes if path.endswith(s)]
        if hit and not len(p):
            want = sorted(r for s in hit for r in rule_suffixes[s])
            findings.append(Finding(
                lint=SHARDING, severity="warning", model=name,
                location=f"param:{path}",
                message=f"name matches TP rule {hit[0]!r} but rank "
                        f"{ndim} matches none of its specs (rank(s) "
                        f"{want}); the rule table has drifted from the "
                        "model definition and this param silently "
                        "replicates"))
            continue
        if hit:
            matched_suffixes.update(hit)
            for dim, ax in enumerate(p):
                if ax == MODEL_AXIS and shape[dim] % _MIN_TP_DEGREE:
                    findings.append(Finding(
                        lint=SHARDING, severity="warning", model=name,
                        location=f"param:{path}",
                        message=f"dim {dim} (size {shape[dim]}) is "
                                f"model-axis-sharded but not divisible "
                                f"by the minimum TP degree "
                                f"{_MIN_TP_DEGREE}"))
    # column/row pairing only means something for the transformer
    # families the TP table targets; a lone auto-named Dense_0 head in a
    # CNN matching the BERT FFN rule is incidental (and harmless — TP on
    # non-transformers is rejected upstream by shard_state_tp)
    if not (spec.is_text or getattr(spec, "attention", False)):
        return findings
    for cols, rows in _TP_RULE_PARTNERS:
        got_col = bool(cols & matched_suffixes)
        got_row = bool(rows & matched_suffixes)
        if got_col != got_row:
            have, miss = (cols, rows) if got_col else (rows, cols)
            findings.append(Finding(
                lint=SHARDING, severity="warning", model=name,
                location=f"param:{sorted(have)[0]}",
                message=f"TP rules matched {sorted(have)} but not the "
                        f"partner direction {sorted(miss)}: the block is "
                        "half-annotated across the pjit boundary, so "
                        "GSPMD inserts a reshard every layer"))
    return findings


def check_jaxpr_host_callbacks(name: str) -> list[Finding]:
    """Trace the model's apply and flag host-callback primitives."""
    import jax

    findings: list[Finding] = []
    model, spec, variables, example = _abstract_model(name)

    def fwd(variables, x):
        return model.apply(variables, x, train=False)

    jaxpr = jax.make_jaxpr(fwd)(variables, example)

    def walk(jx, depth=0):
        for eqn in jx.eqns:
            if eqn.primitive.name in _HOST_CALLBACK_PRIMITIVES:
                findings.append(Finding(
                    lint=HOST_SYNC, severity="warning", model=name,
                    location=f"jaxpr:{eqn.primitive.name}",
                    message=f"model forward traces a "
                            f"`{eqn.primitive.name}` host callback — a "
                            "device->host round-trip inside every step"))
            for v in eqn.params.values():
                if hasattr(v, "jaxpr"):
                    walk(v.jaxpr if hasattr(v.jaxpr, "eqns") else v,
                         depth + 1)
                elif isinstance(v, (list, tuple)):
                    for item in v:
                        if hasattr(item, "eqns"):
                            walk(item, depth + 1)
                        elif hasattr(item, "jaxpr"):
                            walk(item.jaxpr, depth + 1)

    walk(jaxpr.jaxpr)
    return findings


@register_pass(
    COLLECTIVE_SHAPE, "error", "model",
    doc="the zero1 arm's lowered HLO missing its reduce-scatter/"
        "all-gather pair, or gradient buckets riding full all-reduces",
    example="world=2 zero1 step lowers with 0 reduce-scatters — the "
            "optimizer states are not actually sharded")
def check_zero1_collectives(name: str = "trivial", world: int = 2,
                            batch: int = 2,
                            **config_overrides) -> list[Finding]:
    """HLO check for the zero1 arm's collective shape.

    Lowers the member's world=N ``--variable_update=zero1`` train step
    and asserts the GRADIENT path compiled to reduce-scatter +
    all-gather, not a full all-reduce — the program property the arm
    exists for (half the ring traffic per direction, sharded update in
    between).  A small all-reduce budget remains legitimate: the loss
    ``pmean`` and, for BN members, the batch-stat sync; a gradient tree
    silently falling back to all-reduce blows well past it.  Findings
    are ``collective-shape`` errors, empty when the arm is healthy —
    the same accept-into-baseline contract as every other lint.
    """
    from tpu_hc_bench.analysis import hlo

    config_overrides.setdefault("num_classes", 10)
    text = hlo.lower_world_step_hlo(
        name, batch=batch, world=world, variable_update="zero1",
        **config_overrides)
    return zero1_shape_findings(
        name, hlo.collective_counts(text),
        location=f"hlo:{name}:zero1:world{world}")


def zero1_shape_findings(name: str, counts: dict[str, int],
                         location: str = "hlo:") -> list[Finding]:
    """The pure half of ``check_zero1_collectives``: derive findings
    from definition-site collective counts (unit-testable without a
    compile)."""
    rs = counts.get("reduce-scatter", 0)
    ag = counts.get("all-gather", 0)
    ar = counts.get("all-reduce", 0)
    findings: list[Finding] = []
    loc = location
    if rs < 1 or ag < 1:
        findings.append(Finding(
            lint=COLLECTIVE_SHAPE, severity="error", model=name,
            location=loc,
            message=f"zero1 step lowered without the reduce-scatter/"
                    f"all-gather pair (counts: {counts}) — the gradient "
                    "path is not optimizer-sharded"))
    # non-gradient all-reduces: the scalar loss pmean (1) plus the
    # BN-stat sync bucket(s) — a small fixed budget.  A gradient tree
    # falling back to all-reduce adds one per GRAD bucket and blows it.
    budget = 3
    if ar > budget:
        findings.append(Finding(
            lint=COLLECTIVE_SHAPE, severity="error", model=name,
            location=loc,
            message=f"zero1 step emits {ar} all-reduces (> budget "
                    f"{budget} for loss/BN-stat sync; counts: {counts}) "
                    "— gradient buckets are riding a full all-reduce"))
    return findings


def lint_model(name: str, source_lints: bool = True) -> list[Finding]:
    """Every per-model pass: module-source AST + jaxpr + sharding rules."""
    findings: list[Finding] = []
    if source_lints:
        import importlib

        from tpu_hc_bench.models import get_model_spec

        spec = get_model_spec(name)
        mod = importlib.import_module(spec.create.__module__)
        path = Path(mod.__file__)
        for f in lint_file(path, model=name):
            findings.append(f)
    findings.extend(check_jaxpr_host_callbacks(name))
    findings.extend(check_sharding_consistency(name))
    return findings
