"""The lint-pass registry: one table of every analysis pass.

Before round 21, ``lints.py`` hand-registered ten passes inside
``_FileLinter.run()`` and their severities were scattered across the
``_emit`` call sites — adding a pass meant editing three places and the
docs drifted (README documented the lints piecemeal across five PR-era
sections).  The registry is the ONE home:

- every pass **registers itself** with a ``@register_pass`` decorator
  at definition site (name, default severity, scope, a one-line
  "what it catches", and an example finding for the docs table);
- ``discover()`` imports the pass-defining modules so the table is
  complete without a hand-maintained list (auto-discovery: a new
  module only has to be named in ``_PASS_MODULES``, its passes
  register themselves);
- ``_FileLinter.run()`` iterates ``file_passes()``/``jit_passes()``
  instead of a hard-coded call sequence, so a registered pass runs
  without touching the driver;
- per-pass severity lives HERE (``_emit`` looks it up by default), so
  a pass's severity is declared once next to its registration;
- ``pass_index()`` renders the README/ARCHITECTURE lint table from
  the same registrations — the docs cannot drift from the code.

Scopes:

- ``jit``: runs once per traced-function context (``_jit_contexts``).
- ``file``: runs once per source file.
- ``repo``: runs once per repository (registry staleness, stream
  contracts).
- ``model``: runs per zoo member (jaxpr/sharding/HLO passes).

``changed_python_files`` backs the CLI's ``--changed-only`` mode: the
per-file passes restrict to sources ``git diff`` (plus untracked files)
names, so the CI gate stays cheap as passes multiply while repo-scope
passes still see the whole tree.
"""

from __future__ import annotations

import dataclasses
import importlib
import subprocess
from pathlib import Path
from typing import Callable

__all__ = [
    "PassInfo", "register_pass", "discover", "all_passes", "get_pass",
    "file_passes", "jit_passes", "pass_index", "changed_python_files",
]


@dataclasses.dataclass(frozen=True)
class PassInfo:
    name: str            # lint name, e.g. "rank-divergent-collective"
    severity: str        # default severity: "error" | "warning" | "info"
    scope: str           # "jit" | "file" | "repo" | "model"
    doc: str             # one line: what the pass catches
    example: str         # one example finding, for the docs table
    func: Callable | None  # the pass callable (None: run out-of-band)
    order: int           # registration order (stable run order)


_REGISTRY: dict[str, PassInfo] = {}
_ORDER = [0]

#: modules whose import populates the registry (auto-discovery: add a
#: pass module here and its ``@register_pass`` decorators do the rest)
_PASS_MODULES = (
    "tpu_hc_bench.analysis.lints",
    "tpu_hc_bench.analysis.dataflow",
    "tpu_hc_bench.analysis.contracts",
)


def register_pass(name: str, severity: str, scope: str, doc: str,
                  example: str = ""):
    """Class/function decorator: add one pass to the registry.

    ``func`` conventions by scope — ``jit``: ``func(linter, ctx)``;
    ``file``: ``func(linter)``; ``repo``/``model``: registered for the
    severity/docs table only (their drivers call them directly).
    """
    if severity not in ("error", "warning", "info"):
        raise ValueError(f"bad severity {severity!r} for pass {name!r}")
    if scope not in ("jit", "file", "repo", "model"):
        raise ValueError(f"bad scope {scope!r} for pass {name!r}")

    def deco(fn):
        _ORDER[0] += 1
        _REGISTRY[name] = PassInfo(
            name=name, severity=severity, scope=scope, doc=doc,
            example=example, func=fn, order=_ORDER[0])
        return fn

    return deco


def discover() -> dict[str, PassInfo]:
    """Import every pass module so the registry is complete; returns it."""
    for mod in _PASS_MODULES:
        importlib.import_module(mod)
    return dict(_REGISTRY)


def all_passes() -> list[PassInfo]:
    discover()
    return sorted(_REGISTRY.values(), key=lambda p: p.order)


def get_pass(name: str) -> PassInfo | None:
    return _REGISTRY.get(name)


def default_severity(name: str, fallback: str = "warning") -> str:
    info = _REGISTRY.get(name)
    return info.severity if info is not None else fallback


def file_passes() -> list[PassInfo]:
    return [p for p in all_passes() if p.scope == "file"]


def jit_passes() -> list[PassInfo]:
    return [p for p in all_passes() if p.scope == "jit"]


def pass_index() -> list[tuple[str, str, str, str, str]]:
    """Docs rows: (name, severity, scope, what-it-catches, example) —
    the README/ARCHITECTURE lint table renders from this, so the table
    cannot drift from the registrations."""
    return [(p.name, p.severity, p.scope, p.doc, p.example)
            for p in all_passes()]


# ---------------------------------------------------------------------
# --changed-only support


def changed_python_files(root: str | Path,
                         base: str = "HEAD") -> list[Path] | None:
    """Python sources changed vs ``base`` (tracked diff + untracked),
    relative paths under ``root``.  Returns ``None`` when git is
    unavailable/not a repo — the caller falls back to the full tree
    (fail open: a broken git must widen the gate, never narrow it).
    """
    root = Path(root)
    try:
        diff = subprocess.run(
            ["git", "-C", str(root), "diff", "--name-only", base, "--"],
            capture_output=True, text=True, timeout=15)
        untracked = subprocess.run(
            ["git", "-C", str(root), "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, timeout=15)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if diff.returncode != 0:
        return None
    names = set(diff.stdout.splitlines())
    if untracked.returncode == 0:
        names |= set(untracked.stdout.splitlines())
    out = []
    for name in sorted(names):
        if not name.endswith(".py"):
            continue
        p = root / name
        if p.is_file():
            out.append(Path(name))
    return out
