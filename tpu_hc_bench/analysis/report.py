"""Findings, JSON reports, and the checked-in baseline gate.

A *finding* is one lint hit: ``(lint, severity, location, message)``
plus the model it was found against (or ``"repo"`` for source-level
lints that are not per-model).  Findings serialize to stable JSON so CI
can diff runs, and the repo checks in a baseline
(``tpu_hc_bench/analysis/baseline_findings.json``) of the findings the
current tree is *known and accepted* to produce.  The gate
(``tests/test_analysis.py``, ``python -m tpu_hc_bench.analysis``) fails
only on findings NOT in the baseline — so adding a new host sync inside
a jitted region breaks CI, while a deliberate, reviewed exception is one
baseline entry away.

Suppression: either add the finding's ``key`` to the baseline (the
CLI's ``baseline --update`` subcommand rewrites it atomically with a
loud diff), or annotate the offending source line with
``# tpu-hc: disable=<lint-name>`` (or the legacy
``# thb:lint-ok[<lint-name>]``), which the AST lints honor in place —
suppression hits are counted into the report JSON so they stay
auditable.
"""

from __future__ import annotations

import json
import os
import tempfile
from dataclasses import asdict, dataclass, field
from pathlib import Path

__all__ = [
    "Finding", "findings_to_json", "load_baseline", "save_baseline",
    "compare_to_baseline", "BASELINE_PATH",
]

BASELINE_PATH = Path(__file__).parent / "baseline_findings.json"

_SEVERITIES = ("error", "warning", "info")


@dataclass(frozen=True)
class Finding:
    lint: str          # pass name, e.g. "host-sync-in-jit"
    severity: str      # "error" | "warning" | "info"
    model: str         # zoo member, or "repo" for source-level passes
    location: str      # "path/to/file.py:123" or "param:layer_0/qkv"
    message: str

    @property
    def key(self) -> str:
        """Stable identity used for baseline matching.

        Deliberately excludes the message tail and the line number (code
        motion above a finding must not churn the baseline): identity is
        the lint, the model, and the location's file/object part.  Only
        a NUMERIC suffix is stripped — ``param:layer_0/qkv`` and
        ``jaxpr:pure_callback`` locations keep their full object path,
        so accepting one sharding finding never masks another.
        """
        head, _, tail = self.location.rpartition(":")
        loc = head if head and tail.isdigit() else self.location
        return f"{self.lint}::{self.model}::{loc}"

    def render(self) -> str:
        return (f"[{self.severity}] {self.lint} ({self.model}) "
                f"{self.location} — {self.message}")


@dataclass
class Report:
    """Per-run result: findings, per-model collective counts, per-lint
    suppression-hit counts, and the analysis wall time."""

    findings: list[Finding] = field(default_factory=list)
    collectives: dict[str, dict[str, int]] = field(default_factory=dict)
    suppressed: dict[str, int] = field(default_factory=dict)
    wall_s: float | None = None

    def to_json(self) -> str:
        return findings_to_json(self.findings, self.collectives,
                                suppressed=self.suppressed,
                                wall_s=self.wall_s)


def findings_to_json(findings: list[Finding],
                     collectives: dict[str, dict[str, int]] | None = None,
                     suppressed: dict[str, int] | None = None,
                     wall_s: float | None = None,
                     ) -> str:
    payload = {
        "findings": [asdict(f) for f in sorted(
            findings, key=lambda f: (f.model, f.lint, f.location))],
    }
    if collectives:
        payload["collectives"] = {
            m: dict(sorted(c.items())) for m, c in sorted(collectives.items())
        }
    if suppressed:
        # per-lint inline-suppression hits: a suppressed finding leaves
        # the findings list but must not leave the audit trail
        payload["suppressed"] = {k: int(v) for k, v in
                                 sorted(suppressed.items()) if v}
    if wall_s is not None:
        payload["wall_s"] = round(float(wall_s), 3)
    return json.dumps(payload, indent=2, sort_keys=True) + "\n"


def load_baseline(path: Path | str = BASELINE_PATH) -> set[str]:
    """Baseline = the set of accepted finding keys."""
    path = Path(path)
    if not path.exists():
        return set()
    data = json.loads(path.read_text())
    return set(data.get("accepted", []))


def save_baseline(findings: list[Finding],
                  path: Path | str = BASELINE_PATH,
                  merge: set[str] = frozenset()
                  ) -> tuple[list[str], list[str]]:
    """Write the baseline from ``findings`` (plus ``merge``, for partial
    runs that must not erase other models' accepted keys).

    Atomic: tmp → fsync → rename in the destination directory (the
    ``tune_state.json`` idiom), so a crash mid-write can never leave a
    truncated gate file that silently accepts everything.  Returns the
    ``(added, removed)`` key diff against the previous baseline so
    callers can print WHAT changed, not just that something did.
    """
    path = Path(path)
    before = load_baseline(path) if path.exists() else set()
    accepted = {f.key for f in findings} | set(merge)
    payload = {
        "comment": "Accepted analysis findings; regenerate with "
                   "`python -m tpu_hc_bench.analysis baseline --update "
                   "--all`.  The CI gate fails only on findings whose "
                   "key is NOT listed here.",
        "accepted": sorted(accepted),
    }
    fd, tmp = tempfile.mkstemp(dir=str(path.parent),
                               prefix=path.name + ".", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(json.dumps(payload, indent=2) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return sorted(accepted - before), sorted(before - accepted)


def compare_to_baseline(findings: list[Finding],
                        baseline: set[str] | None = None,
                        ) -> list[Finding]:
    """The regressions: findings whose key the baseline does not accept.

    Severity "info" findings never gate (they are attribution output,
    not defects).
    """
    if baseline is None:
        baseline = load_baseline()
    return [f for f in findings
            if f.severity in ("error", "warning") and f.key not in baseline]
