"""Input pipelines: synthetic (default) and ImageNet TFRecords.

Reference contract: tf_cnn_benchmarks runs synthetic data unless
``--data_dir`` points at ImageNet TFRecords (the 20-of-1024-shard subset at
``run-tf-sing-ucx-openmpi.sh:19,80-81``); each Horovod rank reads its own
shard of the input.  Same here: ``make_input_fn`` returns a per-host
iterator yielding globally-batched arrays laid out for the data mesh axis.
"""

from tpu_hc_bench.data.synthetic import SyntheticImages, SyntheticTokens  # noqa: F401
