"""Sharded ImageNet TFRecord input pipeline.

Reproduces the reference's real-data contract: ``--data_dir`` points at a
directory of ImageNet TFRecord shards (the 20-of-1024-shard subset at
``run-tf-sing-ucx-openmpi.sh:19``), records carry JPEG bytes in
``image/encoded`` and a 1-based label in ``image/class/label`` (the
standard ilsvrc2012 TFRecord schema tf_cnn_benchmarks consumes), and each
data-parallel worker reads its own slice of the shard list — the per-rank
sharding Horovod ranks do (SURVEY.md §3.1 "input: ... shard by rank").

TPU-first decisions: decode/resize happen on host CPU in a *parallel decode
pool* behind a double-buffered background thread (prefetch), delivering
ready NHWC batches so the device never waits on JPEG decode; training-time
augmentation is the benchmark-standard random-resized-crop + horizontal
flip.  The pool is a ThreadPoolExecutor — the native libjpeg decoder
(`native/jpeg_decoder.cpp`) runs outside the GIL (ctypes releases it for
the C call), so threads scale to real decode parallelism without the
fork/pickle cost of multiprocessing.  Each image's augmentation RNG is
seeded by its global stream index, so the pixel stream is deterministic
per seed and independent of pool size.
"""

from __future__ import annotations

import glob
import io
import os
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path
from typing import Iterator

import numpy as np

from tpu_hc_bench.data import tfrecord

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32) * 255.0
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32) * 255.0


def host_decode_budget() -> int:
    """The ONE home of the host decode budget: ``cpu_count()-1``
    threads (one core left for step loops), capped at 32.  The shared
    input service claims it whole; a per-process pipeline's auto width
    is this divided by the local worker count."""
    return max(1, min(32, (os.cpu_count() or 2) - 1))


def find_shards(data_dir: str | Path, split: str = "train") -> list[str]:
    """Locate TFRecord shards (`train-00000-of-01024` style, or any files
    matching `<split>*`)."""
    data_dir = str(data_dir)
    patterns = [f"{data_dir}/{split}-*-of-*", f"{data_dir}/{split}*"]
    for pat in patterns:
        shards = sorted(glob.glob(pat))
        if shards:
            return shards
    raise FileNotFoundError(f"no {split} TFRecord shards under {data_dir}")


def count_examples(data_dir: str | Path, split: str = "train") -> int:
    """Total example count across ALL of a split's shards (the epoch size
    for --num_epochs — the per-worker shard split jointly covers the full
    dataset once per epoch)."""
    return sum(tfrecord.count_records(s) for s in find_shards(data_dir, split))


def shards_for_worker(
    shards: list[str], worker: int, num_workers: int
) -> list[str]:
    """Round-robin shard assignment — the per-rank input sharding."""
    mine = shards[worker::num_workers]
    return mine if mine else [shards[worker % len(shards)]]


def _decode_and_crop(
    jpeg_bytes: bytes, image_size: int, rng: np.random.Generator,
    train: bool, normalize: bool = True,
) -> np.ndarray:
    """Decode -> (random-resized | central) crop -> [size, size, 3].

    Fast path: the native libjpeg decoder (`native/jpeg_decoder.cpp`) does
    decode+crop+resize in one C call with DCT scaling; the crop box and
    flip are drawn HERE so the augmentation stream is identical to the PIL
    fallback (same rng draws in the same order).
    """
    from tpu_hc_bench import native

    try:
        dims = native.jpeg_dims(jpeg_bytes)
        if dims is None:                     # native lib unavailable
            raise ValueError
        w, h = dims
        if train:
            crop, flip = _sample_train_crop(w, h, rng)
        else:
            # central 87.5% square crop (the eval standard), resized
            cs = int(round(0.875 * min(w, h)))
            crop = ((w - cs) // 2, (h - cs) // 2, cs, cs)
            flip = False
        arr = native.jpeg_decode_crop_resize(
            jpeg_bytes, crop, image_size, flip)
    except ValueError:
        # not a baseline RGB JPEG (ImageNet has a few CMYK files and one
        # mislabeled PNG) — PIL handles those
        return _decode_and_crop_pil(jpeg_bytes, image_size, rng, train,
                                    normalize)
    if not normalize:
        return arr
    return (arr.astype(np.float32) - IMAGENET_MEAN) / IMAGENET_STD


def _sample_train_crop(w, h, rng):
    """Random-resized-crop box + flip (benchmark-standard: area 8%-100%,
    aspect 3/4..4/3, 5 attempts, fall back to the full image).  The ONLY
    sampler for both decode paths, so their augmentation RNG streams are
    identical by construction."""
    crop = (0, 0, w, h)
    area = w * h
    for _ in range(5):
        target_area = area * rng.uniform(0.08, 1.0)
        aspect = np.exp(rng.uniform(np.log(3 / 4), np.log(4 / 3)))
        cw = int(round(np.sqrt(target_area * aspect)))
        ch = int(round(np.sqrt(target_area / aspect)))
        if cw <= w and ch <= h:
            x0 = int(rng.integers(0, w - cw + 1))
            y0 = int(rng.integers(0, h - ch + 1))
            crop = (x0, y0, cw, ch)
            break
    return crop, bool(rng.random() < 0.5)


def _decode_and_crop_pil(
    jpeg_bytes: bytes, image_size: int, rng: np.random.Generator,
    train: bool, normalize: bool = True,
) -> np.ndarray:
    from PIL import Image

    img = Image.open(io.BytesIO(jpeg_bytes)).convert("RGB")
    w, h = img.size
    if train:
        (x0, y0, cw, ch), flip = _sample_train_crop(w, h, rng)
        img = img.crop((x0, y0, x0 + cw, y0 + ch))
        img = img.resize((image_size, image_size), Image.BILINEAR)
        arr = np.asarray(img)
        if flip:
            arr = arr[:, ::-1]
    else:
        # central crop at 87.5% then resize (eval standard)
        scale = image_size / (0.875 * min(w, h))
        img = img.resize((int(w * scale), int(h * scale)), Image.BILINEAR)
        w2, h2 = img.size
        x0, y0 = (w2 - image_size) // 2, (h2 - image_size) // 2
        img = img.crop((x0, y0, x0 + image_size, y0 + image_size))
        arr = np.asarray(img)
    if not normalize:          # uint8 wire format: normalize on device
        return arr
    return (arr.astype(np.float32) - IMAGENET_MEAN) / IMAGENET_STD


class ImageNetDataset:
    """Iterator of (images, labels) global batches from TFRecord shards.

    ``worker``/``num_workers`` shard the file list (per-host input
    sharding); the iterator yields the full *global* batch for this host's
    share of the data mesh axis — the driver shards it onto devices.
    """

    def __init__(
        self,
        data_dir: str | Path,
        global_batch: int,
        image_size: int = 224,
        split: str = "train",
        train: bool = True,
        worker: int = 0,
        num_workers: int = 1,
        seed: int = 0,
        prefetch: int = 2,
        labels_zero_based: bool = False,
        wire_dtype: str = "float32",
        decode_workers: int | None = None,
        local_workers: int | None = None,
        decode_pool: "ThreadPoolExecutor | None" = None,
        decode_rows: tuple[int, int] | None = None,
    ):
        if wire_dtype not in ("float32", "uint8"):
            raise ValueError(f"wire_dtype must be float32|uint8: {wire_dtype}")
        self.shards = shards_for_worker(
            find_shards(data_dir, split), worker, num_workers
        )
        self.global_batch = global_batch
        self.image_size = image_size
        self.train = train
        self.seed = seed
        self.prefetch = prefetch
        self.label_offset = 0 if labels_zero_based else 1  # ilsvrc is 1-based
        # "uint8" ships raw crops (4x less host->device traffic; the MXU-
        # feeding normalize runs on device — see driver.device_normalize)
        self.wire_dtype = wire_dtype
        # decode pool width (tf_cnn_benchmarks --datasets_num_private_threads
        # analog); 0/None = auto-size to the host's cores (matching the CLI
        # flag's 0=auto convention), 1 = serial.  ``local_workers``: how many
        # worker processes share this host — the auto width divides the host
        # budget by it, so N private pools never claim N*(cpu-1) threads
        # (the oversubscription the shared input service removes entirely).
        if not decode_workers:
            share = max(1, int(local_workers or 1))
            decode_workers = max(1, host_decode_budget() // share)
        self.decode_workers = decode_workers
        # an externally owned pool (the host input service's shared pool):
        # _batches submits here instead of spinning a private pool, and
        # never shuts it down
        self._decode_pool = decode_pool
        # decode only batch rows [lo, hi): the multi-process driver has
        # each worker decode the FULL global batch while its devices
        # consume one slice — the host input service's sliced mode
        # decodes just the consumed rows (records are still read/parsed
        # and the per-row RNG stream still advances, so the decoded
        # rows are bitwise-identical to the full pipeline's).  Rows
        # outside the slice are UNDEFINED memory — the caller must
        # slice them away before delivery.
        if decode_rows is not None:
            lo, hi = decode_rows
            if not (0 <= lo < hi <= global_batch):
                raise ValueError(
                    f"decode_rows {decode_rows} out of range for "
                    f"global_batch {global_batch}")
        self.decode_rows = decode_rows
        # decode-pool counters (obs.metrics "data" record): written by the
        # producer thread, read by the driver after the run — scalar
        # updates under the GIL, no lock needed
        self._batches_decoded = 0
        self._examples_decoded = 0
        self._decode_wall_s = 0.0

    @staticmethod
    def _read_shard(path: str) -> Iterator[bytes]:
        """Read one shard, preferring the native C++ scanner (CRC-verified,
        ~GB/s) with transparent fallback to the pure-Python codec."""
        try:
            from tpu_hc_bench import native

            recs = native.read_records_native(path, verify=True)
            if recs is not None:
                return iter(recs)
        except ImportError:
            pass
        return tfrecord.read_records(path)

    def _example_stream(self) -> Iterator[tuple[bytes, int]]:
        """Endless stream of (jpeg_bytes, zero_based_label)."""
        epoch = 0
        while True:
            order = np.random.default_rng(self.seed + epoch).permutation(
                len(self.shards)
            ) if self.train else np.arange(len(self.shards))
            for si in order:
                for rec in self._read_shard(self.shards[si]):
                    ex = tfrecord.parse_example(rec)
                    jpeg = ex["image/encoded"][0]
                    label = int(ex["image/class/label"][0]) - self.label_offset
                    yield jpeg, label
            epoch += 1

    def _batches(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        stream = self._example_stream()
        s = self.image_size
        normalize = self.wire_dtype == "float32"
        dtype = np.float32 if normalize else np.uint8

        def decode_into(images, labels, i, jpeg, label, stream_idx):
            # per-image rng: deterministic for (seed, position-in-stream)
            # regardless of decode order / pool width
            rng = np.random.default_rng((self.seed, stream_idx))
            images[i] = _decode_and_crop(jpeg, s, rng, self.train,
                                         normalize=normalize)
            labels[i] = label

        own_pool = None
        if self._decode_pool is not None:
            pool = self._decode_pool
        else:
            own_pool = pool = (ThreadPoolExecutor(self.decode_workers)
                               if self.decode_workers > 1 else None)
        stream_idx = 0
        try:
            while True:
                t0 = time.perf_counter()
                images = np.empty((self.global_batch, s, s, 3), dtype)
                labels = np.empty((self.global_batch,), np.int32)
                items = []
                for i in range(self.global_batch):
                    jpeg, label = next(stream)
                    labels[i] = label
                    items.append((i, jpeg, label, stream_idx))
                    stream_idx += 1
                if self.decode_rows is not None:
                    # sliced mode: the RNG stream above advanced over
                    # EVERY row (bitwise alignment with the full
                    # pipeline); only the consumed rows pay decode
                    lo, hi = self.decode_rows
                    items = [it for it in items if lo <= it[0] < hi]
                if pool is None:
                    for it in items:
                        decode_into(images, labels, *it)
                else:
                    # one task per pool thread, not per image: executor
                    # submit/result costs ~50-100us of GIL each, and at
                    # host-pool rates (the shared input service pushes
                    # thousands of img/s through ONE process) per-image
                    # futures convoy the GIL.  Chunking is invisible to
                    # the output: each image's augmentation RNG is keyed
                    # by its stream index, not by task placement.
                    width = max(1, getattr(pool, "_max_workers",
                                           self.decode_workers))
                    step_ = -(-len(items) // width)
                    chunks = [items[i:i + step_]
                              for i in range(0, len(items), step_)]

                    def decode_chunk(chunk):
                        for it in chunk:
                            decode_into(images, labels, *it)

                    futs = [pool.submit(decode_chunk, c) for c in chunks]
                    for f in futs:
                        f.result()   # re-raises decode errors here
                self._batches_decoded += 1
                self._examples_decoded += len(items)   # sliced mode: only
                                                       # the decoded rows
                self._decode_wall_s += time.perf_counter() - t0
                yield images, labels
        finally:
            if own_pool is not None:
                own_pool.shutdown(wait=False, cancel_futures=True)

    def stats(self) -> dict:
        """Decode-pool counters for the run's metrics artifact.

        ``decode_wall_s`` is the producer thread's wall time building
        batches (shard read + parse + parallel JPEG decode) — it
        overlaps the device step via the prefetch queue, so it bounds
        the host-side input rate rather than adding to step time.
        """
        return {
            "batches": self._batches_decoded,
            "examples": self._examples_decoded,
            "decode_wall_s": round(self._decode_wall_s, 3),
            "decode_workers": self.decode_workers,
        }

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        """Prefetching iterator: decode runs in a daemon thread."""
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def put(item) -> bool:
            # bounded put that notices consumer abandonment: a plain
            # q.put would block forever once the consumer stops draining,
            # pinning the generator frame and leaking the decode pool
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            gen = self._batches()
            try:
                for batch in gen:
                    if not put(batch):
                        return
            except Exception as e:  # surface decode errors to the consumer
                put(e)
            finally:
                gen.close()        # runs _batches' finally -> pool.shutdown

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            stop.set()


def make_synthetic_shards(
    out_dir: str | Path,
    num_shards: int = 4,
    examples_per_shard: int = 16,
    image_size: int = 32,
    num_classes: int = 1000,
    seed: int = 0,
) -> list[str]:
    """Generate tiny valid ImageNet-schema TFRecord shards (test fixtures /
    no-dataset smoke runs) — JPEG-encoded random images, 1-based labels."""
    from PIL import Image

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(seed)
    paths = []
    for s in range(num_shards):
        path = out_dir / f"train-{s:05d}-of-{num_shards:05d}"
        records = []
        for _ in range(examples_per_shard):
            arr = rng.integers(0, 256, (image_size, image_size, 3), np.uint8)
            buf = io.BytesIO()
            Image.fromarray(arr).save(buf, format="JPEG")
            label = int(rng.integers(1, num_classes + 1))
            records.append(
                tfrecord.build_example({
                    "image/encoded": [buf.getvalue()],
                    "image/class/label": [label],
                    "image/height": [image_size],
                    "image/width": [image_size],
                })
            )
        tfrecord.write_records(path, records)
        paths.append(str(path))
    return paths
