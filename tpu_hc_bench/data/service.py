"""Host-level shared input service: one decode pool per host.

The per-process pipeline (``data/imagenet.py``) gives EVERY worker its
own decode pool, each defaulting to ``cpu_count()-1`` threads — at
workers-per-host > 1 the pools oversubscribe the host CPUs, every
process pays its own shard-scan/parse machinery, and (in the worst
wrap-around sharding case) the same images are decoded once per worker.
Worse, each pool shares a GIL with its own training process: the step
loop's Python starves the very threads that feed it, and the goodput
ledger's ``data_wait`` phase is the first thing that grows.

This module moves the whole input plane into ONE owner per host (a
dedicated process, or the lowest-local-rank worker):

- **Per-worker streams, bitwise-identical**: the service runs one
  logical producer stream per local worker — the same
  ``ImageNetDataset(worker=k, num_workers=W)`` stream that worker would
  have built itself, sharing a single decode pool — so the delivered
  batch sequence is bitwise-identical to the per-process pipeline for a
  fixed seed (pinned by tests/test_input_service.py).  Determinism
  holds by construction: augmentation RNG is keyed by (seed, position-
  in-stream), independent of pool width or scheduling.

- **Shared-memory rings**: each worker gets a ring of ``depth``
  preallocated batch slots in ``multiprocessing.shared_memory``.
  Handoff is a seqlock: the producer writes the payload then publishes
  ``head``; the consumer reads slot views (zero-copy numpy views into
  the shm buffer) and publishes ``tail`` when done.  Single writer per
  counter, aligned 8-byte stores — no cross-process locks.  Slot
  assignment is round-robin in stream order (batch n lives in slot
  ``n % depth``), so delivery order IS stream order.

- **Backpressure accounting**: each ring header carries producer stall
  nanoseconds (ring full), consumer wait nanoseconds (ring empty), and
  an occupancy histogram sampled at publish time.  ``InputService
  .stats()`` / ``ServiceClient.window_stats()`` fold these for the
  ``obs/fleet`` heartbeats and the ``obs summarize`` input line — a
  starved host is visible fleet-wide.

- **Dataset mixing**: ``weighted_mixture`` interleaves several shard
  sets with a counter-keyed RNG, so the mixture schedule is
  deterministic and independent of consumer pacing.

- **Packed token batches**: the service serves the fixed-bucket packed
  sequence batches of ``data.tokens.PackedTokenDataset`` (4-array
  layout via ``packed_token_layout``) — packing happens service-side,
  so workers only ever see one batch shape and never recompile.

Memory-ordering note: publish/consume counters are aligned uint64
single-writer cells; on x86-64 (TSO) the payload-then-counter store
order is architectural.  The handoff tests hammer this under
concurrency; exotic weakly-ordered hosts should add fences before
trusting the ring at scale.
"""

from __future__ import annotations

import atexit
import dataclasses
import hashlib
import os
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from multiprocessing import shared_memory
from typing import Callable, Iterator, Sequence

import numpy as np

from tpu_hc_bench.obs import timeline as timeline_mod

__all__ = [
    "ArraySpec", "BatchLayout", "ShmRing", "InputService", "ServiceClient",
    "image_batch_layout", "packed_token_layout", "make_image_service",
    "make_packed_token_service", "weighted_mixture", "mixture_schedule",
    "service_name", "default_service_pool_width",
]

_ALIGN = 64

# ring header cells (uint64 each); single writer per cell:
#   producer: HEAD, STALL_NS, CLOSED, and the occupancy histogram
#   consumer: TAIL, WAIT_NS
#   creator (once, before any peer attaches): DEPTH, SLOT_NBYTES
_H_HEAD = 0        # batches published
_H_TAIL = 1        # batches consumed
_H_STALL_NS = 2    # producer ns blocked on a full ring
_H_WAIT_NS = 3     # consumer ns blocked on an empty ring
_H_CLOSED = 4      # 0 live, 1 clean end-of-stream, 2 producer error
_H_DEPTH = 5       # creator's ring depth (attach verifies)
_H_SLOT = 6        # creator's slot_nbytes (attach verifies)
_H_HIST = 7        # occupancy histogram: depth+1 cells (occ 0..depth)

CLOSED_OK = 1
CLOSED_ERROR = 2


@dataclasses.dataclass(frozen=True)
class ArraySpec:
    """One fixed-shape array of the batch wire format."""

    name: str
    shape: tuple[int, ...]
    dtype: str

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64)
                   * np.dtype(self.dtype).itemsize)


class BatchLayout:
    """Fixed slot layout for a tuple-of-arrays batch.

    Slots are preallocated: every array lives at a fixed 64-byte-aligned
    offset, so producer writes and consumer views are plain numpy
    operations over the shared buffer (no pickling, no per-batch
    allocation on the wire).
    """

    def __init__(self, arrays: Sequence[ArraySpec]):
        self.arrays = tuple(arrays)
        off = 0
        self.offsets = []
        for a in self.arrays:
            self.offsets.append(off)
            off += -(-a.nbytes // _ALIGN) * _ALIGN
        self.slot_nbytes = max(off, _ALIGN)

    def views(self, buf, base: int) -> tuple[np.ndarray, ...]:
        """Numpy views of one slot's arrays (zero-copy)."""
        out = []
        for a, off in zip(self.arrays, self.offsets):
            out.append(np.ndarray(a.shape, dtype=a.dtype, buffer=buf,
                                  offset=base + off))
        return tuple(out)

    def check(self, batch: Sequence[np.ndarray]) -> None:
        if len(batch) != len(self.arrays):
            raise ValueError(
                f"batch has {len(batch)} arrays, layout expects "
                f"{len(self.arrays)} ({[a.name for a in self.arrays]})")
        for arr, spec in zip(batch, self.arrays):
            if tuple(arr.shape) != spec.shape or \
                    np.dtype(arr.dtype) != np.dtype(spec.dtype):
                raise ValueError(
                    f"array {spec.name!r}: got {arr.shape}/{arr.dtype}, "
                    f"layout expects {spec.shape}/{spec.dtype}")


def image_batch_layout(global_batch: int, image_size: int,
                       wire_dtype: str = "uint8") -> BatchLayout:
    """The (images, labels) wire format of ``ImageNetDataset``."""
    img_dtype = "float32" if wire_dtype == "float32" else "uint8"
    return BatchLayout([
        ArraySpec("images", (global_batch, image_size, image_size, 3),
                  img_dtype),
        ArraySpec("labels", (global_batch,), "int32"),
    ])


def packed_token_layout(global_batch: int, seq_len: int) -> BatchLayout:
    """The (tokens, targets, weights, segment_ids) packed-sequence wire
    format of ``data.tokens.PackedTokenDataset`` — one fixed bucket, so
    service consumers never see a new shape (never recompile)."""
    return BatchLayout([
        ArraySpec("tokens", (global_batch, seq_len), "int32"),
        ArraySpec("targets", (global_batch, seq_len), "int32"),
        ArraySpec("weights", (global_batch, seq_len), "float32"),
        ArraySpec("segment_ids", (global_batch, seq_len), "int32"),
    ])


# segments THIS process created (tracker claims on those are legit and
# must survive a same-process attach — the rank-0 worker that hosts the
# service also consumes from it)
_OWNED_NAMES: set[str] = set()


def _unregister_tracker(shm) -> None:
    """Drop this process's resource_tracker claim on an ATTACHED
    segment: on 3.8-3.12 attaching registers the name too, so a
    consumer process exiting would unlink shm the producer still owns
    (observed: the segment vanishes under the service).  Never drops
    the claim of the process that CREATED the segment."""
    if shm._name in _OWNED_NAMES:
        return
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass


class ShmRing:
    """Single-producer single-consumer shared-memory ring of batch slots.

    Batch ``n`` always lands in slot ``n % depth`` (the deterministic
    round-robin assignment); ``head``/``tail`` are monotonically
    increasing batch counts, each written by exactly one side.
    """

    # blocked-side poll: start fine, back off exponentially to the cap —
    # a stalled ring must not burn GIL/CPU at kHz in the very process
    # that is trying to decode its way out of the stall
    _POLL_S = 1e-4
    _POLL_MAX_S = 2e-3

    def __init__(self, shm, layout: BatchLayout, depth: int, owner: bool):
        self._shm = shm
        self.layout = layout
        self.depth = depth
        self.owner = owner
        n_hdr = _H_HIST + depth + 1
        self._hdr = np.ndarray((n_hdr,), dtype=np.uint64, buffer=shm.buf)
        self._data_base = -(-(n_hdr * 8) // _ALIGN) * _ALIGN

    # -- construction --------------------------------------------------

    @classmethod
    def _size(cls, layout: BatchLayout, depth: int) -> int:
        n_hdr = _H_HIST + depth + 1
        return (-(-(n_hdr * 8) // _ALIGN) * _ALIGN
                + depth * layout.slot_nbytes)

    @classmethod
    def create(cls, name: str, layout: BatchLayout,
               depth: int) -> "ShmRing":
        if depth < 1:
            raise ValueError(f"ring depth must be >= 1: {depth}")
        try:        # reclaim a stale segment from a crashed prior run
            stale = shared_memory.SharedMemory(name=name)
            stale.close()
            stale.unlink()
        except FileNotFoundError:
            pass
        shm = shared_memory.SharedMemory(
            name=name, create=True, size=cls._size(layout, depth))
        _OWNED_NAMES.add(shm._name)
        ring = cls(shm, layout, depth, owner=True)
        ring._hdr[:] = 0
        ring._hdr[_H_DEPTH] = np.uint64(depth)
        ring._hdr[_H_SLOT] = np.uint64(layout.slot_nbytes)
        return ring

    @classmethod
    def attach(cls, name: str, layout: BatchLayout, depth: int,
               timeout: float = 30.0) -> "ShmRing":
        deadline = time.monotonic() + timeout
        while True:
            try:
                shm = shared_memory.SharedMemory(name=name)
                break
            except FileNotFoundError:
                if time.monotonic() >= deadline:
                    raise FileNotFoundError(
                        f"input service ring {name!r} did not appear "
                        f"within {timeout:.0f}s — is the service host "
                        f"(lowest local rank) running?") from None
                time.sleep(0.05)
        _unregister_tracker(shm)
        want = cls._size(layout, depth)
        if shm.size < want:
            shm.close()
            raise ValueError(
                f"ring {name!r}: shm segment is {shm.size}B, layout "
                f"needs {want}B — producer/consumer batch shapes or "
                f"depth disagree")
        ring = cls(shm, layout, depth, owner=False)
        # geometry handshake: a size check alone lets a SMALLER
        # depth/slot attach 'succeed' and read wrong offsets silently.
        # All-zero cells mean the creator has the segment but hasn't
        # stamped the header yet — retry inside the deadline instead of
        # dying on a microsecond startup race.
        while True:
            got = (int(ring._hdr[_H_DEPTH]), int(ring._hdr[_H_SLOT]))
            if got == (depth, layout.slot_nbytes):
                return ring
            if got != (0, 0) or time.monotonic() >= deadline:
                shm.close()
                raise ValueError(
                    f"ring {name!r}: producer geometry depth={got[0]} "
                    f"slot={got[1]}B != consumer depth={depth} "
                    f"slot={layout.slot_nbytes}B — batch shapes/dtypes "
                    f"or ring depth disagree between service and client")
            time.sleep(0.01)

    # -- producer side -------------------------------------------------

    def put(self, batch: Sequence[np.ndarray],
            stop: threading.Event | None = None,
            timeout: float | None = None) -> bool:
        """Copy one batch into the next slot; block while the ring is
        full (stall time accounted).  False when ``stop`` fired or
        ``timeout`` expired before a slot freed."""
        if self._hdr is None:       # ring torn down under the feeder
            return False            # (stop() join timeout expired)
        self.layout.check(batch)
        head = int(self._hdr[_H_HEAD])
        deadline = None if timeout is None else time.monotonic() + timeout
        t0 = None
        flushed = 0
        poll = self._POLL_S
        while head - int(self._hdr[_H_TAIL]) >= self.depth:
            if t0 is None:
                t0 = time.perf_counter()
            if (stop is not None and stop.is_set()) or (
                    deadline is not None and time.monotonic() > deadline):
                return False
            time.sleep(poll)
            poll = min(2 * poll, self._POLL_MAX_S)
            # flush incrementally: a stats() reader sees an in-progress
            # stall, not only completed ones
            el = int(1e9 * (time.perf_counter() - t0))
            self._hdr[_H_STALL_NS] += np.uint64(el - flushed)
            flushed = el
        base = self._data_base + (head % self.depth) * self.layout.slot_nbytes
        for dst, src in zip(self.layout.views(self._shm.buf, base), batch):
            np.copyto(dst, src)
        self._hdr[_H_HEAD] = np.uint64(head + 1)        # publish
        occ = min(head + 1 - int(self._hdr[_H_TAIL]), self.depth)
        self._hdr[_H_HIST + occ] += np.uint64(1)
        return True

    def close_producer(self, error: bool = False) -> None:
        if self._hdr is None:       # already torn down — nothing to mark
            return
        self._hdr[_H_CLOSED] = np.uint64(
            CLOSED_ERROR if error else CLOSED_OK)

    # -- consumer side -------------------------------------------------

    def get(self, stop: threading.Event | None = None,
            timeout: float | None = None) -> tuple[np.ndarray, ...] | None:
        """Views of the oldest unconsumed slot (zero-copy; call
        ``advance()`` when done with them).  None on clean end-of-stream
        or stop/timeout; raises on a dead producer."""
        tail = int(self._hdr[_H_TAIL])
        deadline = None if timeout is None else time.monotonic() + timeout
        t0 = None
        flushed = 0
        poll = self._POLL_S
        while int(self._hdr[_H_HEAD]) <= tail:
            closed = int(self._hdr[_H_CLOSED])
            if closed == CLOSED_ERROR:
                raise RuntimeError(
                    "input service producer died — see the service "
                    "host's log for the stream traceback")
            if closed == CLOSED_OK:
                return None
            if t0 is None:
                t0 = time.perf_counter()
            if (stop is not None and stop.is_set()) or (
                    deadline is not None and time.monotonic() > deadline):
                return None
            time.sleep(poll)
            poll = min(2 * poll, self._POLL_MAX_S)
            el = int(1e9 * (time.perf_counter() - t0))
            self._hdr[_H_WAIT_NS] += np.uint64(el - flushed)
            flushed = el
        base = self._data_base + (tail % self.depth) * self.layout.slot_nbytes
        return self.layout.views(self._shm.buf, base)

    def advance(self) -> None:
        self._hdr[_H_TAIL] += np.uint64(1)

    # -- both sides ----------------------------------------------------

    @property
    def occupancy(self) -> int:
        return int(self._hdr[_H_HEAD]) - int(self._hdr[_H_TAIL])

    def stats(self) -> dict:
        if self._hdr is None:       # torn down: a zeroed account beats
            hist = [0] * (self.depth + 1)       # a crash in telemetry
            return {"produced": 0, "consumed": 0, "depth": self.depth,
                    "producer_stall_s": 0.0, "consumer_wait_s": 0.0,
                    "occ_hist": hist, "occ_p50": 0, "occ_p99": 0}
        hist = [int(v) for v in self._hdr[_H_HIST:_H_HIST + self.depth + 1]]
        return {
            "produced": int(self._hdr[_H_HEAD]),
            "consumed": int(self._hdr[_H_TAIL]),
            "depth": self.depth,
            "producer_stall_s": round(int(self._hdr[_H_STALL_NS]) / 1e9, 4),
            "consumer_wait_s": round(int(self._hdr[_H_WAIT_NS]) / 1e9, 4),
            "occ_hist": hist,
            "occ_p50": _hist_percentile(hist, 0.50),
            "occ_p99": _hist_percentile(hist, 0.99),
        }

    def close(self) -> None:
        self._hdr = None
        try:
            self._shm.close()
        except (OSError, BufferError):
            pass

    def unlink(self) -> None:
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass
        _OWNED_NAMES.discard(self._shm._name)


def _hist_percentile(hist: list[int], q: float) -> int:
    """Occupancy percentile off the ring's integer histogram, through
    the round-24 mergeable sketch (small ints resolve to their own
    buckets at the default 1% relative error, so the rounded result
    matches the old cumulative scan for any plausible ring depth)."""
    from tpu_hc_bench.obs import sketch as sketch_mod

    sk = sketch_mod.QuantileSketch.from_counts(hist)
    if not sk.count:
        return 0
    return int(round(sk.quantile(100.0 * q)))


def service_name(*parts) -> str:
    """Deterministic shm name prefix all local workers can derive from
    their own (identical) config — no rendezvous channel needed."""
    h = hashlib.blake2b("|".join(str(p) for p in parts).encode(),
                        digest_size=6).hexdigest()
    return f"thbsvc{h}"


def default_service_pool_width() -> int:
    """One decode pool per HOST gets the WHOLE host budget (the same
    figure the per-process pipeline divides by its local worker count
    — one home, ``imagenet.host_decode_budget``)."""
    from tpu_hc_bench.data.imagenet import host_decode_budget

    return host_decode_budget()


# ---------------------------------------------------------------------
# dataset mixing


def _mixture_probs(weights: Sequence[float]) -> np.ndarray:
    w = np.asarray(weights, np.float64)
    if (w < 0).any() or w.sum() <= 0:
        raise ValueError(f"mixture weights must be >=0 and sum > 0: "
                         f"{list(weights)}")
    return w / w.sum()


def _mixture_draw(seed, i: int, p: np.ndarray) -> int:
    """The ONE home of the counter-keyed draw: ``mixture_schedule`` and
    the live ``weighted_mixture`` must agree forever."""
    return int(np.random.default_rng((seed, i)).choice(len(p), p=p))


def mixture_schedule(weights: Sequence[float], seed, n: int) -> np.ndarray:
    """First ``n`` source indices of the deterministic mixture schedule.

    Counter-keyed: draw ``i`` depends only on ``(seed, i)`` and the
    weights, so every worker/restart sees the same interleave
    regardless of consumer pacing."""
    p = _mixture_probs(weights)
    return np.asarray([_mixture_draw(seed, i, p) for i in range(n)],
                      np.int64)


def weighted_mixture(streams: Sequence[Iterator], weights: Sequence[float],
                     seed=0) -> Iterator:
    """Weighted interleave of batch iterators on the deterministic
    ``mixture_schedule`` (one draw per delivered batch).  Validation is
    EAGER — a bad config dies at construction, not as a cryptic
    producer-died error on the first feeder-thread next()."""
    if len(streams) != len(weights):
        raise ValueError(f"{len(streams)} streams vs {len(weights)} weights")
    p = _mixture_probs(weights)

    def gen():
        i = 0
        while True:
            yield next(streams[_mixture_draw(seed, i, p)])
            i += 1

    return gen()


# ---------------------------------------------------------------------
# service (producer side)


class InputService:
    """The per-host producer: one feeder thread per local worker, all
    sharing one decode pool, each filling that worker's shm ring.

    ``make_stream(worker) -> iterator of tuple-of-arrays`` builds worker
    ``w``'s logical stream; it must be deterministic in ``w`` so the
    service delivers exactly what the per-process pipeline would have.
    """

    def __init__(self, name: str, layout: BatchLayout, num_workers: int,
                 make_stream: Callable[[int], Iterator], depth: int = 2,
                 pool: ThreadPoolExecutor | None = None,
                 decode_workers: int | None = None):
        self.name = name
        self.layout = layout
        self.num_workers = num_workers
        self.depth = depth
        self.decode_workers = decode_workers or 0
        self._make_stream = make_stream
        self._pool = pool
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.errors: list[str] = []
        self.rings = [ShmRing.create(f"{name}-w{w}", layout, depth)
                      for w in range(num_workers)]
        atexit.register(self._cleanup)

    def start(self) -> "InputService":
        for w in range(self.num_workers):
            t = threading.Thread(target=self._feed, args=(w,), daemon=True,
                                 name=f"input-service-feed-{w}")
            t.start()
            self._threads.append(t)
        return self

    def _feed(self, w: int) -> None:
        ring = self.rings[w]
        gen = self._make_stream(w)
        try:
            while True:
                # flight-recorder spans (obs.timeline): decode (the
                # stream's next() — parse + jpeg decode + augment) vs
                # ring_put (copy + any ring-full stall), one span per
                # batch — a starved consumer vs a stalled producer read
                # straight off the feeder's timeline
                t0 = time.monotonic()
                try:
                    batch = next(gen)
                except StopIteration:
                    ring.close_producer()   # finite stream drained cleanly
                    return
                t_put = time.monotonic()
                timeline_mod.record_span("svc_decode", t0, t_put, worker=w)
                ok = ring.put(batch, stop=self._stop)
                timeline_mod.record_span("ring_put", t_put,
                                         time.monotonic(), worker=w)
                if not ok:
                    # service stopping: still mark the stream closed so
                    # a consumer blocked in get() unblocks instead of
                    # polling a dead ring forever
                    ring.close_producer()
                    return
        except Exception:
            self.errors.append(
                f"worker {w} stream: {traceback.format_exc()}")
            ring.close_producer(error=True)
        finally:
            if hasattr(gen, "close"):
                gen.close()

    def stats(self) -> dict:
        """Aggregate backpressure account (the ``input_service`` metrics
        record + heartbeat source): per-ring head/tail/stalls plus
        host-level occupancy percentiles folded over all rings."""
        per_ring = [r.stats() for r in self.rings]
        hist = [0] * (self.depth + 1)
        for s in per_ring:
            for occ, n in enumerate(s["occ_hist"]):
                hist[occ] += n
        return {
            "workers": self.num_workers,
            "depth": self.depth,
            "decode_workers": self.decode_workers,
            "produced": sum(s["produced"] for s in per_ring),
            "consumed": sum(s["consumed"] for s in per_ring),
            "producer_stall_s": round(
                sum(s["producer_stall_s"] for s in per_ring), 4),
            "consumer_wait_s": round(
                sum(s["consumer_wait_s"] for s in per_ring), 4),
            "occ_p50": _hist_percentile(hist, 0.50),
            "occ_p99": _hist_percentile(hist, 0.99),
            "errors": len(self.errors),
        }

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10.0)
        self._cleanup()

    def _cleanup(self) -> None:
        atexit.unregister(self._cleanup)
        self._stop.set()
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None
        for r in self.rings:
            # consumers still mapping the segment must see end-of-
            # stream, not an eternally-empty live ring (this runs on
            # the rank-0 error/preemption exit path via atexit too; a
            # SIGKILLed service host is the one case a consumer's own
            # get() timeout must cover)
            r.close_producer()
            r.close()
            r.unlink()
        self.rings = []


class ServiceClient:
    """One worker's consumer handle: attach to my ring, iterate batches.

    Iteration yields zero-copy numpy views into the shm slot; the slot
    is released when the iterator is advanced again, so a consumer must
    finish with (or copy) a batch before asking for the next — the
    driver's ``shard_batch`` host->device copy satisfies this.  Pass
    ``copy=True`` to yield owned copies instead.
    """

    def __init__(self, name: str, layout: BatchLayout, worker: int,
                 depth: int = 2, timeout: float = 30.0, copy: bool = False,
                 stall_timeout_s: float | None = None):
        self.worker = worker
        self.copy = copy
        # None = wait forever on an empty ring; a finite value turns a
        # SIGKILLed service host (whose atexit close_producer never ran)
        # into a loud error instead of an eternal data wait
        self.stall_timeout_s = stall_timeout_s
        self.ring = ShmRing.attach(f"{name}-w{worker}", layout, depth,
                                   timeout=timeout)
        self._last_wait_ns = 0

    def __iter__(self) -> Iterator[tuple[np.ndarray, ...]]:
        while True:
            t0 = time.monotonic()
            views = self.ring.get(timeout=self.stall_timeout_s)
            timeline_mod.record_span("ring_get", t0, time.monotonic(),
                                     worker=self.worker)
            if views is None:
                if not int(self.ring._hdr[_H_CLOSED]):
                    raise RuntimeError(
                        f"input service ring stalled: no batch for "
                        f"{self.stall_timeout_s:.0f}s and the producer "
                        f"never closed the stream — is the service "
                        f"host (lowest local rank) alive?")
                return
            if self.copy:
                batch = tuple(v.copy() for v in views)
                self.ring.advance()     # copy owns the data: free the
                yield batch             # slot NOW, not a full step later
            else:
                yield views
                self.ring.advance()

    def stats(self) -> dict:
        """Consumer-side counters in the shape of the per-process
        ``ImageNetDataset.stats()`` data record, plus ring fields."""
        s = self.ring.stats()
        b = self.ring.layout.arrays[0].shape[0]
        return {
            "batches": s["consumed"],
            "examples": s["consumed"] * b,
            "decode_workers": 0,      # decode lives in the service host
            "input_service": True,
            "ring_depth": s["depth"],
            "ring_occ_p50": s["occ_p50"],
            "ring_occ_p99": s["occ_p99"],
            "consumer_wait_s": s["consumer_wait_s"],
            "producer_stall_s": s["producer_stall_s"],
        }

    def window_stats(self) -> dict:
        """Per-sync-window heartbeat fields: instantaneous ring
        occupancy + the consumer-wait delta since the last window."""
        wait_ns = int(self.ring._hdr[_H_WAIT_NS])
        delta_ms = (wait_ns - self._last_wait_ns) / 1e6
        self._last_wait_ns = wait_ns
        return {"ring_occ": self.ring.occupancy,
                "ring_depth": self.ring.depth,
                "wait_ms": round(delta_ms, 3)}

    def close(self) -> None:
        self.ring.close()


# ---------------------------------------------------------------------
# stream factories


def make_image_service(
    data_dirs: Sequence[str],
    num_workers: int,
    global_batch: int,
    image_size: int,
    *,
    mix_weights: Sequence[float] | None = None,
    split: str = "train",
    train: bool = True,
    seed: int = 0,
    wire_dtype: str = "uint8",
    decode_workers: int = 0,
    depth: int = 2,
    name: str | None = None,
    labels_zero_based: bool = False,
    slice_per_worker: bool = False,
) -> InputService:
    """The image TFRecord service: per-worker ``ImageNetDataset``
    streams (bitwise-identical to the per-process pipeline) over one
    shared decode pool; several ``data_dirs`` are weighted-interleaved
    with ``weighted_mixture``.

    ``slice_per_worker=True`` is the redundancy-free serving mode: the
    multi-process driver has each worker decode the FULL global batch
    while its devices consume slice ``w`` — W-fold redundant decode per
    host.  Here worker ``w``'s ring instead carries only rows
    ``[w*b, (w+1)*b)`` of its stream (``b = global_batch //
    num_workers``), decoded once; the per-row RNG keying keeps those
    rows bitwise-identical to the full pipeline's, so the pixels that
    reach devices are unchanged while host decode work drops W-fold.
    """
    from tpu_hc_bench.data.imagenet import ImageNetDataset

    width = decode_workers or default_service_pool_width()
    pool = ThreadPoolExecutor(width, thread_name_prefix="svc-decode")
    rows = None
    ring_batch = global_batch
    if slice_per_worker:
        if global_batch % num_workers:
            raise ValueError(
                f"slice_per_worker: global_batch {global_batch} not "
                f"divisible by {num_workers} workers")
        ring_batch = global_batch // num_workers
        rows = lambda w: (w * ring_batch, (w + 1) * ring_batch)
    layout = image_batch_layout(ring_batch, image_size, wire_dtype)
    if mix_weights is None:
        mix_weights = [1.0] * len(data_dirs)
    if name is None:
        name = service_name(*data_dirs, split, seed, global_batch,
                            image_size, wire_dtype, train, os.getpid())

    def make_stream(w: int) -> Iterator:
        streams = [
            ImageNetDataset(
                d, global_batch=global_batch, image_size=image_size,
                split=split, train=train, worker=w,
                num_workers=num_workers, seed=seed,
                wire_dtype=wire_dtype, labels_zero_based=labels_zero_based,
                decode_pool=pool,
                decode_rows=rows(w) if rows is not None else None,
            )._batches()
            for d in data_dirs
        ]
        base = (streams[0] if len(streams) == 1
                else weighted_mixture(streams, mix_weights, seed=(seed, w)))
        if rows is None:
            return base
        lo, hi = rows(w)

        def sliced():
            for img, lab in base:
                yield img[lo:hi], lab[lo:hi]
        return sliced()

    return InputService(name, layout, num_workers, make_stream,
                        depth=depth, pool=pool, decode_workers=width)


def make_packed_token_service(
    data_dir: str,
    num_workers: int,
    global_batch: int,
    seq_len: int,
    *,
    eod_id: int = 0,
    split: str = "train",
    seed: int = 0,
    depth: int = 2,
    name: str | None = None,
) -> InputService:
    """Packed-sequence token service: variable-length documents are
    packed into ONE fixed bucket service-side, so consumers see a
    single batch shape forever (no recompiles)."""
    from tpu_hc_bench.data.tokens import PackedTokenDataset

    layout = packed_token_layout(global_batch, seq_len)
    if name is None:
        name = service_name(data_dir, split, seed, global_batch, seq_len,
                            "packed", os.getpid())

    def make_stream(w: int) -> Iterator:
        return iter(PackedTokenDataset(
            data_dir, global_batch, seq_len, eod_id=eod_id, split=split,
            worker=w, num_workers=num_workers, seed=seed))

    return InputService(name, layout, num_workers, make_stream, depth=depth)
