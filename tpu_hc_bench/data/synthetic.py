"""Synthetic inputs — tf_cnn_benchmarks' default data mode.

tf_cnn_benchmarks with no ``--data_dir`` trains on fixed random tensors
generated once and fed every step, making input cost ~zero so the benchmark
measures compute + allreduce only.  Reproduced here: one deterministic
random global batch, generated on host, reused for every step.  The driver
device_puts it once with the data-axis sharding, so steady-state steps do no
host->device transfer at all (stricter than the reference, which still runs
its input pipeline graph ops on synthetic data).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np


@dataclasses.dataclass
class SyntheticImages:
    """Fixed random image batch: NHWC float32 images + int labels."""

    global_batch: int
    image_shape: tuple[int, int, int]  # (H, W, C)
    num_classes: int = 1000
    seed: int = 0

    def batch(self) -> tuple[np.ndarray, np.ndarray]:
        rng = np.random.default_rng(self.seed)
        images = rng.standard_normal(
            (self.global_batch, *self.image_shape), dtype=np.float32
        )
        labels = rng.integers(
            0, self.num_classes, size=(self.global_batch,), dtype=np.int32
        )
        return images, labels

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        batch = self.batch()
        while True:
            yield batch


@dataclasses.dataclass
class SyntheticSpeech:
    """Fixed random spectrogram batch for the CTC member (deepspeech2):
    ``(features [B, T, F], labels [B, L] int32, label_paddings [B, L]
    float32)`` — labels in [1, vocab) (0 = CTC blank), per-example
    transcript lengths drawn in [L/2, L] and padded with 1.0 weights."""

    global_batch: int
    frames: int
    freq: int
    max_label: int
    vocab_size: int = 29
    seed: int = 0

    def batch(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        rng = np.random.default_rng(self.seed)
        feats = rng.standard_normal(
            (self.global_batch, self.frames, self.freq), dtype=np.float32)
        labels = rng.integers(
            1, self.vocab_size,
            size=(self.global_batch, self.max_label)).astype(np.int32)
        lengths = rng.integers(self.max_label // 2, self.max_label + 1,
                               size=(self.global_batch,))
        paddings = (np.arange(self.max_label)[None, :]
                    >= lengths[:, None]).astype(np.float32)
        return feats, labels, paddings

    def __iter__(self):
        batch = self.batch()
        while True:
            yield batch


@dataclasses.dataclass
class SyntheticIds:
    """Fixed random id-pair batch for the NCF member: ``[B, 2] int32``
    (user, item) ids + binary implicit-feedback labels — the same
    fixed-batch contract as SyntheticImages."""

    global_batch: int
    num_users: int
    num_items: int
    seed: int = 0

    def batch(self) -> tuple[np.ndarray, np.ndarray]:
        rng = np.random.default_rng(self.seed)
        ids = np.stack([
            rng.integers(0, self.num_users, self.global_batch),
            rng.integers(0, self.num_items, self.global_batch),
        ], axis=1).astype(np.int32)
        labels = rng.integers(0, 2, self.global_batch).astype(np.int32)
        return ids, labels

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        batch = self.batch()
        while True:
            yield batch


@dataclasses.dataclass
class SyntheticTokens:
    """Fixed random token batch for MLM: ids, targets, mask weights.

    15% of positions are selected as prediction targets (BERT's masking
    rate); selected input positions carry the [MASK]-style corruption (id 0).
    """

    global_batch: int
    seq_len: int
    vocab_size: int = 30522
    mask_rate: float = 0.15
    seed: int = 0
    causal_lm: bool = False            # next-token objective (GPT members)
                                       # instead of masked-LM

    def batch(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        rng = np.random.default_rng(self.seed)
        if self.causal_lm:
            tokens = rng.integers(
                1, self.vocab_size, size=(self.global_batch, self.seq_len),
                dtype=np.int32,
            )
            # predict token t+1 at position t; final position has no target
            targets = np.roll(tokens, -1, axis=1)
            weights = np.ones_like(tokens, np.float32)
            weights[:, -1] = 0.0
            return tokens, targets, weights
        targets = rng.integers(
            1, self.vocab_size, size=(self.global_batch, self.seq_len),
            dtype=np.int32,
        )
        mask = rng.random((self.global_batch, self.seq_len)) < self.mask_rate
        inputs = np.where(mask, 0, targets).astype(np.int32)
        weights = mask.astype(np.float32)
        return inputs, targets, weights

    def __iter__(self):
        batch = self.batch()
        while True:
            yield batch
