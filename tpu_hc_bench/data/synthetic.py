"""Synthetic inputs — tf_cnn_benchmarks' default data mode.

tf_cnn_benchmarks with no ``--data_dir`` trains on fixed random tensors
generated once and fed every step, making input cost ~zero so the benchmark
measures compute + allreduce only.  Reproduced here: one deterministic
random global batch, generated on host, reused for every step.  The driver
device_puts it once with the data-axis sharding, so steady-state steps do no
host->device transfer at all (stricter than the reference, which still runs
its input pipeline graph ops on synthetic data).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np


@dataclasses.dataclass
class SyntheticImages:
    """Fixed random image batch: NHWC float32 images + int labels."""

    global_batch: int
    image_shape: tuple[int, int, int]  # (H, W, C)
    num_classes: int = 1000
    seed: int = 0

    def batch(self) -> tuple[np.ndarray, np.ndarray]:
        rng = np.random.default_rng(self.seed)
        images = rng.standard_normal(
            (self.global_batch, *self.image_shape), dtype=np.float32
        )
        labels = rng.integers(
            0, self.num_classes, size=(self.global_batch,), dtype=np.int32
        )
        return images, labels

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        batch = self.batch()
        while True:
            yield batch


@dataclasses.dataclass
class SyntheticTokens:
    """Fixed random token batch for MLM: ids, targets, mask weights.

    15% of positions are selected as prediction targets (BERT's masking
    rate); selected input positions carry the [MASK]-style corruption (id 0).
    """

    global_batch: int
    seq_len: int
    vocab_size: int = 30522
    mask_rate: float = 0.15
    seed: int = 0
    causal_lm: bool = False            # next-token objective (GPT members)
                                       # instead of masked-LM

    def batch(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        rng = np.random.default_rng(self.seed)
        if self.causal_lm:
            tokens = rng.integers(
                1, self.vocab_size, size=(self.global_batch, self.seq_len),
                dtype=np.int32,
            )
            # predict token t+1 at position t; final position has no target
            targets = np.roll(tokens, -1, axis=1)
            weights = np.ones_like(tokens, np.float32)
            weights[:, -1] = 0.0
            return tokens, targets, weights
        targets = rng.integers(
            1, self.vocab_size, size=(self.global_batch, self.seq_len),
            dtype=np.int32,
        )
        mask = rng.random((self.global_batch, self.seq_len)) < self.mask_rate
        inputs = np.where(mask, 0, targets).astype(np.int32)
        weights = mask.astype(np.float32)
        return inputs, targets, weights

    def __iter__(self):
        batch = self.batch()
        while True:
            yield batch
