"""Pure-Python TFRecord + tf.train.Example codec (no TensorFlow dependency).

The reference's real-data path feeds ImageNet **TFRecord** shards
(``--data_dir=/mnt/shared/tensorflow/ilsvrc2012_tfrecords_20of1024``,
``run-tf-sing-ucx-openmpi.sh:19,80``) through tf_cnn_benchmarks' tf.data
pipeline.  This framework has no TensorFlow, so the wire formats are
implemented from scratch:

- TFRecord framing: ``uint64 length | uint32 masked_crc32c(length) |
  bytes data | uint32 masked_crc32c(data)`` per record.
- ``tf.train.Example``: a minimal protobuf wire-format codec for the
  three-field Feature oneof (bytes_list=1, float_list=2, int64_list=3)
  nested in Features' map<string, Feature>.

Both directions (read + write) are provided: the writer generates test
fixtures and synthetic-TFRecord datasets, so the real-data path is testable
without the 144-GB ImageNet archive — the multi-process-simulation test
story SURVEY.md §4 calls for.
"""

from __future__ import annotations

import struct
from pathlib import Path
from typing import Iterable, Iterator

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli), table-driven, with TFRecord's mask transform.
# ---------------------------------------------------------------------------

_CRC_TABLE = []


def _make_table():
    poly = 0x82F63B78  # reflected Castagnoli polynomial
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        _CRC_TABLE.append(crc)


_make_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    """TFRecord's CRC mask: rotate right 15 and add a constant."""
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# TFRecord framing
# ---------------------------------------------------------------------------


def write_records(path: Path | str, records: Iterable[bytes]) -> int:
    """Write records in TFRecord framing; returns the record count."""
    n = 0
    with open(path, "wb") as f:
        for rec in records:
            length = struct.pack("<Q", len(rec))
            f.write(length)
            f.write(struct.pack("<I", masked_crc32c(length)))
            f.write(rec)
            f.write(struct.pack("<I", masked_crc32c(rec)))
            n += 1
    return n


def read_records(
    path: Path | str, verify_crc: bool = False
) -> Iterator[bytes]:
    """Yield raw record payloads from a TFRecord file."""
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                return
            if len(header) < 8:
                raise IOError(f"{path}: truncated length header")
            (length,) = struct.unpack("<Q", header)
            (len_crc,) = struct.unpack("<I", f.read(4))
            if verify_crc and masked_crc32c(header) != len_crc:
                raise IOError(f"{path}: length CRC mismatch")
            data = f.read(length)
            if len(data) < length:
                raise IOError(f"{path}: truncated record")
            (data_crc,) = struct.unpack("<I", f.read(4))
            if verify_crc and masked_crc32c(data) != data_crc:
                raise IOError(f"{path}: data CRC mismatch")
            yield data


def count_records(path: Path | str) -> int:
    """Count records by seeking over payloads (no CRC, no parse) — cheap
    enough to size an epoch (--num_epochs) from the actual shards."""
    import os

    n = 0
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        pos = 0
        while pos < size:
            header = f.read(8)
            if len(header) < 8:
                raise IOError(f"{path}: truncated length header")
            (length,) = struct.unpack("<Q", header)
            pos += 8 + 4 + length + 4
            f.seek(pos)
            n += 1
    if pos > size:
        raise IOError(f"{path}: truncated record")
    return n


# ---------------------------------------------------------------------------
# Minimal protobuf wire codec for tf.train.Example
# ---------------------------------------------------------------------------


def _write_varint(out: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _tag(field: int, wire: int) -> int:
    return (field << 3) | wire


def _len_delim(field: int, payload: bytes) -> bytes:
    out = bytearray()
    _write_varint(out, _tag(field, 2))
    _write_varint(out, len(payload))
    out += payload
    return bytes(out)


FeatureValue = list  # list[bytes] | list[float] | list[int]


def build_example(features: dict[str, FeatureValue]) -> bytes:
    """Encode a feature dict as a serialized tf.train.Example.

    Value type is inferred from the first element: bytes -> bytes_list,
    float -> float_list, int -> int64_list.
    """
    feats = bytearray()
    for name, values in features.items():
        if not isinstance(values, (list, tuple)):
            values = [values]
        if not values:
            raise ValueError(f"feature {name!r} is empty")
        v0 = values[0]
        inner = bytearray()
        if isinstance(v0, (bytes, str)):
            payload = bytearray()
            for v in values:
                vb = v.encode() if isinstance(v, str) else v
                payload += _len_delim(1, vb)
            inner += _len_delim(1, bytes(payload))      # Feature.bytes_list
        elif isinstance(v0, float):
            packed = bytearray()
            _write_varint(packed, _tag(1, 2))           # FloatList.value packed
            body = struct.pack(f"<{len(values)}f", *values)
            _write_varint(packed, len(body))
            packed += body
            inner += _len_delim(2, bytes(packed))       # Feature.float_list
        elif isinstance(v0, int):
            packed = bytearray()
            _write_varint(packed, _tag(1, 2))           # Int64List.value packed
            body = bytearray()
            for v in values:
                _write_varint(body, v & 0xFFFFFFFFFFFFFFFF)
            _write_varint(packed, len(body))
            packed += body
            inner += _len_delim(3, bytes(packed))       # Feature.int64_list
        else:
            raise TypeError(f"feature {name!r}: unsupported {type(v0)}")
        entry = _len_delim(1, name.encode()) + _len_delim(2, bytes(inner))
        feats += _len_delim(1, entry)                   # Features.feature map
    return _len_delim(1, bytes(feats))                  # Example.features


def _parse_packed_or_repeated(buf, want_wire, unpack_one):
    """Parse values that may be packed (len-delim) or repeated scalar."""
    values, pos = [], 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 2:  # packed
            ln, pos = _read_varint(buf, pos)
            end = pos + ln
            while pos < end:
                v, pos = unpack_one(buf, pos)
                values.append(v)
        else:
            v, pos = unpack_one(buf, pos)
            values.append(v)
    return values


def _unpack_varint(buf, pos):
    v, pos = _read_varint(buf, pos)
    if v >= 1 << 63:  # two's-complement int64
        v -= 1 << 64
    return v, pos


def _unpack_f32(buf, pos):
    return struct.unpack_from("<f", buf, pos)[0], pos + 4


def _split_fields(buf: bytes) -> Iterator[tuple[int, int, bytes | int]]:
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            v, pos = _read_varint(buf, pos)
            yield field, wire, v
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            yield field, wire, buf[pos : pos + ln]
            pos += ln
        elif wire == 5:
            yield field, wire, buf[pos : pos + 4]
            pos += 4
        elif wire == 1:
            yield field, wire, buf[pos : pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")


def parse_example(data: bytes) -> dict[str, FeatureValue]:
    """Decode a serialized tf.train.Example into {name: values}."""
    out: dict[str, FeatureValue] = {}
    for field, wire, features_buf in _split_fields(data):
        if field != 1 or wire != 2:
            continue
        for f2, w2, entry in _split_fields(features_buf):
            if f2 != 1 or w2 != 2:
                continue
            name, feature_buf = None, b""
            for f3, w3, v3 in _split_fields(entry):
                if f3 == 1:
                    name = v3.decode()
                elif f3 == 2:
                    feature_buf = v3
            if name is None:
                continue
            values: FeatureValue = []
            for f4, w4, v4 in _split_fields(feature_buf):
                if f4 == 1:    # bytes_list
                    for f5, w5, v5 in _split_fields(v4):
                        if f5 == 1:
                            values.append(v5)
                elif f4 == 2:  # float_list
                    values = _parse_packed_or_repeated(v4, 5, _unpack_f32)
                elif f4 == 3:  # int64_list
                    values = _parse_packed_or_repeated(v4, 0, _unpack_varint)
            out[name] = values
    return out
