"""Memory-mapped pre-tokenized text corpus loader (round 3, VERDICT #7).

The reference's real-vs-synthetic axis (``run-tf-sing-ucx-openmpi.sh:
19,80-81`` — real ImageNet TFRecords vs ``--data_dir`` unset) only had an
image-side analog here; this module gives the text members the same
contract.  Wire format is the standard pre-tokenized flat binary (the
nanoGPT/Megatron convention): ``<data_dir>/<split>.bin`` holding a raw
little-endian uint16 (vocab <= 65536) or uint32 token stream, memory-
mapped so a multi-GB corpus costs no RSS and the OS page cache does the
caching.  TPU-first choices:

- **Zero-copy windows**: batches are gathered directly out of the memmap
  into the wire dtype; int32 widening happens once per batch on host
  (the uint8-images lesson: ship the narrow dtype, widen where cheap).
- **Per-worker sharding**: worker ``w`` of ``W`` owns the ``w``-th of
  ``W`` contiguous stripes of the token stream — disjoint data per
  process, the Horovod per-rank input sharding (SURVEY.md §3.1).
- **Determinism**: window starts are drawn from a counter-based rng
  keyed ``(seed, step)``, so the batch stream is reproducible and
  independent of consumer pacing.

Objectives match ``SyntheticTokens``'s batch contract exactly
(``(tokens, targets, weights)``): causal members get next-token targets
from a ``seq_len+1`` window; MLM members get BERT-style 15% masking with
the mask drawn from the same keyed rng.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Iterator

import numpy as np


def write_token_file(path: str | Path, tokens: np.ndarray,
                     vocab_size: int | None = None) -> Path:
    """Write a flat token stream in the wire format (uint16 when the
    vocab fits, else uint32) + a small sidecar recording the dtype."""
    path = Path(path)
    tokens = np.asarray(tokens)
    hi = int(vocab_size if vocab_size is not None
             else (tokens.max() + 1 if tokens.size else 1))
    dtype = np.uint16 if hi <= (1 << 16) else np.uint32
    path.parent.mkdir(parents=True, exist_ok=True)
    tokens.astype(dtype).tofile(path)
    meta = {"dtype": np.dtype(dtype).name, "num_tokens": int(tokens.size),
            "vocab_size": hi}
    path.with_suffix(path.suffix + ".meta.json").write_text(
        json.dumps(meta))
    return path


def _resolve(data_dir: str | Path, split: str) -> tuple[Path, np.dtype]:
    path = Path(data_dir) / f"{split}.bin"
    if not path.exists():
        raise FileNotFoundError(
            f"no {split}.bin token file under {data_dir} (write one with "
            f"data.tokens.write_token_file)")
    meta_path = path.with_suffix(path.suffix + ".meta.json")
    if meta_path.exists():
        dtype = np.dtype(json.loads(meta_path.read_text())["dtype"])
    else:
        dtype = np.dtype(np.uint16)        # the common convention
    return path, dtype


@dataclasses.dataclass
class TokenDataset:
    """Endless iterator of ``(tokens, targets, weights)`` global batches
    drawn from a memory-mapped pre-tokenized corpus."""

    data_dir: str | Path
    global_batch: int
    seq_len: int
    split: str = "train"
    causal_lm: bool = True
    mask_rate: float = 0.15            # MLM members (BERT's 15%)
    worker: int = 0
    num_workers: int = 1
    seed: int = 0
    vocab_size: int | None = None      # when set, reject out-of-range ids

    def __post_init__(self):
        path, dtype = _resolve(self.data_dir, self.split)
        data = np.memmap(path, dtype=dtype, mode="r")
        window = self.seq_len + 1 if self.causal_lm else self.seq_len
        shard = len(data) // self.num_workers
        lo = self.worker * shard
        self._data = data[lo:lo + shard]
        if len(self._data) < window:
            raise ValueError(
                f"{path}: worker shard has {len(self._data)} tokens < "
                f"window {window} (corpus too small for "
                f"{self.num_workers} workers at seq_len {self.seq_len})")
        self._window = window
        if self.vocab_size is not None:
            probe = np.asarray(self._data[: min(len(self._data), 1 << 20)])
            if probe.size and int(probe.max()) >= self.vocab_size:
                raise ValueError(
                    f"{path}: token id {int(probe.max())} >= vocab_size "
                    f"{self.vocab_size} — corpus/model vocab mismatch")

    def batch(self, step: int = 0) -> tuple[np.ndarray, ...]:
        rng = np.random.default_rng((self.seed, self.worker, step))
        starts = rng.integers(
            0, len(self._data) - self._window + 1,
            size=(self.global_batch,))
        win = np.stack([
            np.asarray(self._data[s:s + self._window]) for s in starts
        ]).astype(np.int32)
        if self.causal_lm:
            tokens, targets = win[:, :-1], win[:, 1:]
            weights = np.ones_like(tokens, np.float32)
            return tokens, targets, weights
        targets = win
        mask = rng.random(win.shape) < self.mask_rate
        tokens = np.where(mask, 0, targets).astype(np.int32)
        return tokens, targets, mask.astype(np.float32)

    def __iter__(self) -> Iterator[tuple[np.ndarray, ...]]:
        step = 0
        while True:
            yield self.batch(step)
            step += 1


# ---------------------------------------------------------------------
# packed / ragged sequence batching (round 13, input-service side)
#
# Variable-length documents padded to a per-batch max are the classic
# recompile generator: every new max length is a new XLA program.  The
# input service packs documents into ONE fixed bucket host-side —
# greedy first-fit in arrival order, long documents chunked — so the
# consumer only ever sees a single (batch, seq_len) shape.  Segment ids
# and in-segment positions ride along; loss weights zero out pad slots
# and the cross-document next-token positions.


def split_documents(tokens: np.ndarray, eod_id: int) -> list[np.ndarray]:
    """Split a flat token stream into documents on ``eod_id``.

    Each document KEEPS its trailing end-of-document token (the
    nanoGPT/Megatron convention); a trailing partial document (no eod
    yet) is kept too.  Empty documents (consecutive eods) are dropped.
    """
    tokens = np.asarray(tokens)
    ends = np.flatnonzero(tokens == eod_id)
    docs: list[np.ndarray] = []
    start = 0
    for e in ends:
        if e > start:       # e == start is a consecutive eod: empty doc
            docs.append(tokens[start:e + 1])
        start = e + 1
    if start < len(tokens):
        docs.append(tokens[start:])
    return docs


def pack_sequences(docs: list[np.ndarray], seq_len: int,
                   pad_id: int = 0) -> dict[str, np.ndarray]:
    """Pack documents into fixed ``seq_len`` rows (greedy first-fit in
    arrival order; documents longer than ``seq_len`` are chunked).

    Returns ``tokens`` [N, L] int32, ``segment_ids`` [N, L] int32
    (1-based per-row document index, 0 = padding), and ``positions``
    [N, L] int32 (0-based offset within the segment).  Deterministic:
    row layout depends only on the document sequence.
    """
    if seq_len < 1:
        raise ValueError(f"seq_len must be >= 1: {seq_len}")
    rows: list[list[np.ndarray]] = []
    space: list[int] = []           # free slots per row
    for doc in docs:
        doc = np.asarray(doc)
        for i in range(0, len(doc), seq_len):
            chunk = doc[i:i + seq_len]
            for r, free in enumerate(space):
                if len(chunk) <= free:
                    rows[r].append(chunk)
                    space[r] -= len(chunk)
                    break
            else:
                rows.append([chunk])
                space.append(seq_len - len(chunk))
    n = len(rows)
    tokens = np.full((n, seq_len), pad_id, np.int32)
    segment_ids = np.zeros((n, seq_len), np.int32)
    positions = np.zeros((n, seq_len), np.int32)
    for r, segs in enumerate(rows):
        off = 0
        for s, seg in enumerate(segs, start=1):
            tokens[r, off:off + len(seg)] = seg
            segment_ids[r, off:off + len(seg)] = s
            positions[r, off:off + len(seg)] = np.arange(len(seg))
            off += len(seg)
    return {"tokens": tokens, "segment_ids": segment_ids,
            "positions": positions}


@dataclasses.dataclass
class PackedTokenDataset:
    """Endless iterator of FIXED-SHAPE packed causal batches
    ``(tokens, targets, weights, segment_ids)`` from a memory-mapped
    corpus whose documents are delimited by ``eod_id``.

    Every batch is ``[global_batch, seq_len]`` — the one bucket the
    service publishes, so consumers never recompile.  Weights mask
    padding and the next-token positions that would cross a document
    boundary.  Deterministic per ``(seed, worker, step)`` like
    ``TokenDataset``.
    """

    data_dir: str | Path
    global_batch: int
    seq_len: int
    eod_id: int = 0
    split: str = "train"
    worker: int = 0
    num_workers: int = 1
    seed: int = 0

    def __post_init__(self):
        path, dtype = _resolve(self.data_dir, self.split)
        data = np.memmap(path, dtype=dtype, mode="r")
        shard = len(data) // self.num_workers
        lo = self.worker * shard
        self._data = data[lo:lo + shard]
        # draw window: enough raw stream to fill the bucket even after
        # packing losses (greedy first-fit wastes < one doc per row)
        self._draw = min(len(self._data),
                         2 * self.global_batch * (self.seq_len + 1))
        if len(self._data) < self.seq_len + 1:
            raise ValueError(
                f"{path}: worker shard has {len(self._data)} tokens < "
                f"window {self.seq_len + 1}")

    def batch(self, step: int = 0) -> tuple[np.ndarray, ...]:
        rng = np.random.default_rng((self.seed, self.worker, step))
        start = int(rng.integers(0, len(self._data) - self._draw + 1))
        window = np.asarray(self._data[start:start + self._draw])
        docs = split_documents(window, self.eod_id)
        packed = pack_sequences(docs, self.seq_len + 1)
        b, lw = self.global_batch, self.seq_len + 1
        toks = np.zeros((b, lw), np.int32)
        segs = np.zeros((b, lw), np.int32)
        n = min(b, len(packed["tokens"]))
        toks[:n] = packed["tokens"][:n]
        segs[:n] = packed["segment_ids"][:n]
        tokens, targets = toks[:, :-1], toks[:, 1:]
        seg_t, seg_n = segs[:, :-1], segs[:, 1:]
        # a target counts only when it continues the SAME document (and
        # neither side is padding)
        weights = ((seg_t != 0) & (seg_t == seg_n)).astype(np.float32)
        return (np.ascontiguousarray(tokens),
                np.ascontiguousarray(targets), weights,
                np.ascontiguousarray(seg_t))

    def __iter__(self) -> Iterator[tuple[np.ndarray, ...]]:
        step = 0
        while True:
            yield self.batch(step)
            step += 1


@dataclasses.dataclass
class PromptSampler:
    """Per-request prompt sampler for the serving lane
    (``tpu_hc_bench.serve``).

    Two sources behind one contract (``sample(rid, length) -> int32
    tokens``, deterministic per ``(seed, rid)`` and independent of
    consumer pacing — the ``TokenDataset`` counter-rng idiom):

    - **corpus** (``data_dir`` set): a window is drawn from the
      memory-mapped pre-tokenized stream and cut at the first
      end-of-document boundary via the packing machinery's
      ``split_documents`` — prompts end where real documents do, so
      sampled lengths have the ragged shape serving systems actually
      see (a returned prompt may be SHORTER than requested).
    - **synthetic** (``data_dir`` None): uniform ids over
      ``[1, vocab_size)`` at exactly the requested length (0 is
      reserved as the eod/pad id).
    """

    vocab_size: int
    data_dir: str | Path | None = None
    split: str = "train"
    eod_id: int = 0
    seed: int = 0

    def __post_init__(self):
        self._data = None
        if self.data_dir is not None:
            path, dtype = _resolve(self.data_dir, self.split)
            self._data = np.memmap(path, dtype=dtype, mode="r")
            if len(self._data) < 2:
                raise ValueError(f"{path}: corpus too small to sample "
                                 f"prompts from")

    def sample(self, rid: int, length: int) -> np.ndarray:
        """The prompt for request ``rid`` at (up to) ``length`` tokens."""
        if length < 1:
            raise ValueError(f"prompt length must be >= 1: {length}")
        rng = np.random.default_rng((self.seed, 11, rid))
        if self._data is None:
            return rng.integers(1, max(2, self.vocab_size),
                                size=(length,), dtype=np.int64
                                ).astype(np.int32)
        span = min(length, len(self._data))
        start = int(rng.integers(0, len(self._data) - span + 1))
        window = np.asarray(self._data[start:start + span])
        docs = split_documents(window, self.eod_id)
        prompt = docs[0] if docs else window
        # an eod-led window can yield a 1-token document; prompts are
        # >= 1 token by construction either way
        out = np.asarray(prompt, dtype=np.int64)
        out = np.clip(out, 0, self.vocab_size - 1)
        return out.astype(np.int32)


def main(argv=None) -> int:
    """Operator CLI: write a corpus in the wire format.

    python -m tpu_hc_bench.data.tokens out_dir --num_tokens 1000000
    python -m tpu_hc_bench.data.tokens out_dir --from_text corpus.txt

    ``--from_text`` byte-level-tokenizes a UTF-8 text file (vocab 256) —
    the zero-dependency way to get a REAL corpus for smoke runs; random
    mode generates a uniform stream for throughput work.  Pair with the
    driver: ``python -m tpu_hc_bench 1 0 8 ici --model gpt2
    --data_dir out_dir``.
    """
    import argparse

    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("out_dir")
    p.add_argument("--split", default="train")
    p.add_argument("--num_tokens", type=int, default=1_000_000)
    p.add_argument("--vocab_size", type=int, default=50257)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--from_text", default=None,
                   help="byte-level tokenize this UTF-8 file instead of "
                        "generating random tokens")
    args = p.parse_args(argv)
    if args.from_text:
        ignored = [f for f, d in (("--num_tokens", 1_000_000),
                                  ("--vocab_size", 50257), ("--seed", 0))
                   if getattr(args, f[2:]) != d]
        if ignored:
            p.error(f"{', '.join(ignored)} do(es) not apply with "
                    f"--from_text (byte-level: vocab 256, whole file)")
        toks = np.frombuffer(Path(args.from_text).read_bytes(), np.uint8)
        vocab = 256
    else:
        rng = np.random.default_rng(args.seed)
        toks = rng.integers(1, args.vocab_size, size=(args.num_tokens,))
        vocab = args.vocab_size
    path = write_token_file(Path(args.out_dir) / f"{args.split}.bin",
                            toks, vocab)
    print(f"{path}: {len(toks)} tokens, vocab {vocab}, "
          f"{path.stat().st_size} bytes")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
