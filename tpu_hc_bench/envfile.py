"""Generated environment registry — the TPU analog of ``/mnt/shared/setenv``.

The reference's entire configuration system is a file every installer appends
``export`` lines to (``install-scripts/install_gcc-8.2.sh:34-41``,
``install_ucx_ompi.sh:29-38``, ``install_conda_tf_hvd.sh:16-18``) and every
downstream script sources (``benchmark-scripts/run-tf-sing-ucx-openmpi.sh:14``).

This module keeps that contract: components register their environment
exports into one registry file (default ``~/.tpu_hc_bench/setenv``); launch
scripts ``source`` it.  Entries are idempotent (keyed by a section tag) so
re-running a setup step replaces rather than duplicates its block — an
improvement over the reference's append-only file, which grows on re-install.
"""

from __future__ import annotations

import os
import re
from pathlib import Path

DEFAULT_PATH = Path(os.environ.get(
    "TPU_HC_BENCH_SETENV", str(Path.home() / ".tpu_hc_bench" / "setenv")
))

_BEGIN = "# >>> tpu_hc_bench:{tag} >>>"
_END = "# <<< tpu_hc_bench:{tag} <<<"


def register(tag: str, exports: dict[str, str], path: Path | None = None) -> Path:
    """Write/replace a tagged export block in the registry file."""
    path = Path(path or DEFAULT_PATH)
    path.parent.mkdir(parents=True, exist_ok=True)
    begin, end = _BEGIN.format(tag=tag), _END.format(tag=tag)
    block = "\n".join(
        [begin] + [f"export {k}={_quote(v)}" for k, v in exports.items()] + [end]
    )
    text = path.read_text() if path.exists() else ""
    pattern = re.compile(
        re.escape(begin) + r".*?" + re.escape(end), flags=re.DOTALL
    )
    if pattern.search(text):
        text = pattern.sub(block, text)
    else:
        if text and not text.endswith("\n"):
            text += "\n"
        text += block + "\n"
    path.write_text(text)
    return path


def read(path: Path | None = None) -> dict[str, str]:
    """Parse all exports back out (for sanity reporting / tests)."""
    path = Path(path or DEFAULT_PATH)
    out: dict[str, str] = {}
    if not path.exists():
        return out
    for line in path.read_text().splitlines():
        m = re.match(r"export\s+([A-Za-z_][A-Za-z0-9_]*)=(.*)$", line.strip())
        if m:
            out[m.group(1)] = _unquote(m.group(2))
    return out


def _quote(v: str) -> str:
    return "'" + str(v).replace("'", "'\\''") + "'"


def _unquote(v: str) -> str:
    v = v.strip()
    if len(v) >= 2 and v[0] == v[-1] and v[0] in "'\"":
        return v[1:-1].replace("'\\''", "'")
    return v
