"""tf_cnn_benchmarks-compatible flag surface, translated for TPU.

The reference drives ``tf_cnn_benchmarks.py`` with a fixed flag set assembled
in ``benchmark-scripts/run-tf-sing-ucx-openmpi.sh:62-81`` (identical at
``run-tf-sing-libfabric-intelmpi.sh:63-82``).  That flag set is the de-facto
API of the reference framework, so this module reproduces it: every flag the
reference passes parses here, with TPU-meaningful semantics where a literal
interpretation would be wrong for the hardware:

- ``--device=cpu`` / ``--mkl=TRUE``: the reference's compute engine selection
  (Intel-MKL CPU kernels).  On TPU the engine is XLA:TPU; ``device`` accepts
  ``cpu|tpu`` and controls the JAX platform, ``mkl`` parses as a no-op.
- ``--data_format=NCHW``: optimal for MKL-DNN, pessimal for TPU (the MXU wants
  NHWC so the channel dim lands on the 128-lane minor axis).  We parse both
  and *translate* to NHWC by default, recording the translation in the
  resolved config (see ``BenchmarkConfig.resolve``).
- ``--num_intra_threads`` / ``--num_inter_threads`` / ``--kmp_blocktime`` /
  ``--kmp_affinity``: CPU thread-pool tuning (reference lines :67-70,76).
  Parsed and preserved for log parity, but no-ops on TPU — XLA owns
  scheduling inside a compiled computation.
- ``--variable_update=horovod --horovod_device=cpu
  --local_parameter_device=cpu`` (reference :77-79): the reference's
  data-parallel engine selection.  Here ``variable_update`` accepts
  ``horovod|psum|replicated|zero1`` and maps to gradient ``psum`` over the
  mesh's data axis (the TPU-native equivalent of Horovod's fused MPI
  allreduce); ``zero1`` is the ZeRO-1 optimizer-state-sharding arm
  (reduce-scatter + sharded update + all-gather, train/step.py).

Defaults mirror the constants hardcoded in the reference launcher
(``run-tf-sing-ucx-openmpi.sh:32-35``): 50 warmup batches, 100 timed batches,
model resnet50, display every 10 steps (``:71``), momentum optimizer
(``:74``), imagenet data (``:81``).
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Any, Sequence

# Experiment constants pinned by the reference launcher
# (run-tf-sing-ucx-openmpi.sh:32-35).
DEFAULT_WARMUP_BATCHES = 50
DEFAULT_NUM_BATCHES = 100
DEFAULT_MODEL = "resnet50"
DEFAULT_DISPLAY_EVERY = 10  # --display_every=10 (:71)

# Horovod fusion buffer: 128 MiB (HOROVOD_FUSION_THRESHOLD=134217728,
# run-tf-sing-ucx-openmpi.sh:105).  The XLA analog is the all-reduce
# combine threshold; see tpu_hc_bench.parallel.fabric.
DEFAULT_FUSION_THRESHOLD_BYTES = 134217728

# attention impls that shard (or degenerately carry) a sequence axis —
# selecting one at --sequence_parallel=1 routes through the degenerate-SP
# block in resolve(), which translates variable_update replicated->psum
SEQ_SHARDED_IMPLS = ("ring", "ulysses", "ulysses_flash")

# --- serving lane (round 16) ------------------------------------------
# Training-only knobs that have no meaning under `python -m tpu_hc_bench
# serve`: a serving run that silently accepted --gradient_accumulation_
# steps or --on_nonfinite=rewind would wear a banner describing machinery
# that never ran, so resolve() rejects any of these the operator
# explicitly set (flag-time, the same loudness contract as every other
# invalid combination).  Knobs shared by both lanes (model, seed, dtype,
# data_dir for the prompt corpus, compile_cache, metrics_dir, device,
# hbm_budget, config) are deliberately absent.
TRAIN_ONLY_FLAGS = (
    "batch_size", "num_warmup_batches", "num_batches", "num_epochs",
    "display_every", "optimizer", "forward_only", "eval",
    "init_learning_rate", "momentum", "data_format",
    "use_fp16",  # serving runs f32 reference decode for now (ROADMAP:
                 # quantized serving arms)
    "variable_update", "overlap_grad_comm", "fusion_threshold_bytes",
    "num_intra_threads", "num_inter_threads", "kmp_blocktime",
    "kmp_affinity", "datasets_num_private_threads",
    "datasets_repeat_cached_sample", "train_dir", "save_model_steps",
    "async_checkpoint", "prefetch_depth", "input_service",
    "service_decode_workers", "full_batch_identity", "on_nonfinite",
    "max_bad_steps", "resume", "step_timeout_s", "keep_checkpoints",
    "inject_fault", "profile_steps", "fabric_ceiling", "num_slices",
    "fused_conv", "fused_xent", "use_space_to_depth", "seq_len",
    "wire_dtype", "gradient_accumulation_steps", "accum_dtype",
    "model_parallel", "expert_parallel", "pipeline_parallel",
    "num_microbatches", "sequence_parallel", "gradient_checkpointing",
    "attention_impl", "moe_impl", "moe_capacity_factor", "moe_f_chunk",
    "scan_layers", "rnn_impl",
)

# The serving lane's own knobs — rejected with the mirror-image error
# when explicitly set on a TRAINING run, so neither lane ever silently
# ignores the other's flags.
SERVE_ONLY_FLAGS = (
    "arrival", "arrival_rate", "num_requests", "serve_buckets",
    "max_in_flight", "kv_page_size", "kv_pages", "max_prompt_len",
    "max_output_len", "batching", "decode_attention", "quant",
    "decode_block_pages", "slo_e2e_ms",
    # round 23: overload/failure survival — the serve lane's own
    # spellings (the train lane's inject_fault/resume/step_timeout_s
    # stay train-only; neither lane ever silently eats the other's)
    "deadline_ms", "shed", "kv_preempt", "serve_faults",
    "serve_journal", "serve_resume", "serve_step_timeout_s",
    # round 25: lazy KV reservation + shared-prefix cache
    "kv_reserve", "prefix_cache", "kv_growth_headroom",
)


def parse_serve_buckets(spec: str, max_in_flight: int) -> tuple[int, ...]:
    """Resolve ``--serve_buckets`` into the decode batch-bucket ladder.

    ``auto`` = the power-of-two ladder 1, 2, 4, ... up to
    ``max_in_flight`` (``max_in_flight`` itself appended when it is not
    a power of two), so every admissible in-flight count has a bucket
    within 2x.  An explicit spec is comma-separated positive ints
    (``"1,4,8"``); loud on malformed input.  The engine AOT-compiles
    one decode executable per bucket at warmup — the ladder IS the set
    of shapes that can ever run, so a request count above the top
    bucket is an admission-control clamp, never a new compile.
    """
    if max_in_flight < 1:
        raise ValueError(f"--max_in_flight must be >= 1: {max_in_flight}")
    if spec == "auto":
        ladder = []
        b = 1
        while b < max_in_flight:
            ladder.append(b)
            b *= 2
        ladder.append(max_in_flight)
        return tuple(ladder)
    try:
        vals = sorted({int(v) for v in spec.split(",") if v.strip()})
    except ValueError:
        raise ValueError(
            f"--serve_buckets must be 'auto' or comma-separated ints "
            f"(decode batch buckets): {spec!r}") from None
    if not vals or vals[0] < 1:
        raise ValueError(
            f"--serve_buckets needs at least one positive bucket: {spec!r}")
    return tuple(vals)


def parse_profile_steps(spec: str) -> tuple[int, int]:
    """Parse ``--profile_steps=a:b`` into an inclusive timed-step window.

    Loud on malformed input (resolve() calls this so a bad window dies at
    flag time, not after 50 warmup steps).  ``b`` may exceed the run
    length — the trace then simply stops when the run does.
    """
    parts = spec.split(":")
    try:
        if len(parts) != 2:
            raise ValueError
        a, b = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(
            f"--profile_steps must be 'a:b' (1-based timed-step bounds, "
            f"inclusive): {spec!r}") from None
    if a < 1 or b < a:
        raise ValueError(
            f"--profile_steps window must satisfy 1 <= a <= b: {spec!r}")
    return a, b


def _parse_bool(v: str | bool) -> bool:
    """tf_cnn_benchmarks accepts TRUE/False/true/... for boolean flags."""
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in ("true", "t", "1", "yes"):
        return True
    if s in ("false", "f", "0", "no"):
        return False
    raise argparse.ArgumentTypeError(f"not a boolean: {v!r}")


@dataclasses.dataclass
class BenchmarkConfig:
    """Resolved benchmark configuration.

    Field names follow the reference flag names (minus leading dashes) so a
    log line of the resolved config reads like the reference's echoed command
    (run-tf-sing-ucx-openmpi.sh:111).
    """

    # --- core experiment knobs (reference :32-35, :62-66) ---
    batch_size: int = 64                      # per-worker batch (README.md:70)
    num_warmup_batches: int = DEFAULT_WARMUP_BATCHES
    # None = unset (resolve() fills DEFAULT_NUM_BATCHES) so an explicit
    # --num_batches=100 still conflicts with --num_epochs
    num_batches: int | None = None
    num_epochs: float = 0.0                   # tf_cnn_benchmarks --num_epochs:
                                              # when set, num_batches is
                                              # derived from the dataset size
                                              # and the resolved global batch
                                              # (driver, needs the layout)
    model: str = DEFAULT_MODEL
    display_every: int = DEFAULT_DISPLAY_EVERY
    optimizer: str = "momentum"               # --optimizer=momentum (:74)
    forward_only: bool = False                # --forward_only=False (:75)
    eval: bool = False                        # tf_cnn_benchmarks --eval:
                                              # forward + top-1 accuracy
    init_learning_rate: float = 0.01          # tf_cnn_benchmarks flag; the
                                              # reference leaves the default
    momentum: float = 0.9                     # tf_cnn_benchmarks default

    # --- data (reference :80-81) ---
    data_dir: str | None = None               # None => synthetic data
    data_name: str = "imagenet"
    data_format: str = "NHWC"                 # reference passes NCHW (:73);
                                              # translated, see resolve()

    # --- compute engine selection (reference :76-77) ---
    device: str = "tpu"                       # reference: cpu; ours: tpu
    mkl: bool = False                         # --mkl=TRUE no-ops on TPU
    use_fp16: bool = False                    # fp32 parity default; bf16 is
                                              # the TPU fast path (see
                                              # compute_dtype)

    # --- distribution (reference :77-79) ---
    variable_update: str = "psum"             # horovod|psum|replicated
    horovod_device: str = "tpu"               # parsed for parity
    local_parameter_device: str = "tpu"

    # --- CPU thread tuning: parsed, preserved, no-op on TPU (:67-70,76) ---
    num_intra_threads: int = 0
    num_inter_threads: int = 2
    kmp_blocktime: int = 1
    kmp_affinity: str = "granularity=fine,noverbose,compact,1,0"
    # tf_cnn_benchmarks' input-pipeline private threadpool — here it is the
    # REAL width of the host JPEG decode pool (data/imagenet.py); 0 = auto
    datasets_num_private_threads: int = 0
    # tf_cnn_benchmarks --datasets_repeat_cached_sample: decode a small set
    # of real batches ONCE, keep them device-resident, and cycle them every
    # step.  Measures the DEVICE-side real-data step cost (uint8 wire cast +
    # normalize inside the compiled step) with the host decode/transfer wall
    # taken out.  DELIBERATE DEVIATION from the reference flag's mechanics:
    # tf_cnn's version (ds.take(1).cache().repeat()) repeats one cached
    # record through the LIVE host pipeline, still paying the per-step
    # host->device transfer; here the batches are fully device-resident and
    # the decode pool is shut down, so transfer cost is removed too —
    # a stricter isolation, but numbers are NOT comparable to reference
    # runs of the same flag (BASELINE.md round-4 real-data note).
    datasets_repeat_cached_sample: bool = False

    # --- TPU-native additions (no reference analog) ---
    fusion_threshold_bytes: int = DEFAULT_FUSION_THRESHOLD_BYTES
    overlap_grad_comm: str = "on"             # psum/zero1 arms: pack the
                                              # gradient fusion buckets in
                                              # backward-completion order
                                              # so XLA's async collectives
                                              # overlap the remaining
                                              # backward ("on", default);
                                              # "off" barriers the full
                                              # grad tree first — comm
                                              # strictly after the
                                              # complete backward (the
                                              # serialized A/B control)
    seed: int = 0
    num_classes: int = 1000                   # imagenet label space
    trace_dir: str | None = None              # jax.profiler trace output; the
                                              # structured upgrade of the
                                              # reference's I_MPI_DEBUG tracing
    profile_steps: str | None = None          # "a:b": profile timed steps
                                              # a..b into --trace_dir (window
                                              # bounds observed via the
                                              # timeline's completion markers);
                                              # unset = the legacy first-
                                              # sync-window trace
    metrics_dir: str | None = None            # per-run observability artifact:
                                              # metrics.jsonl + manifest.json
                                              # (obs.metrics; worker 0 writes)
                                              # + per-host heartbeat files
                                              # metrics.<k>.jsonl (obs.fleet;
                                              # every process writes its own)
    flight_recorder: str = "on"               # on|off: the always-on host
                                              # span recorder (obs.timeline)
                                              # — bounded in-memory ring on
                                              # every run; with --metrics_dir
                                              # each rank also persists
                                              # spans.<k>.jsonl and the
                                              # watchdog/OOM/preempt paths
                                              # drop timeline_dump.json.
                                              # "off" is the bare-benchmark
                                              # paranoia switch (measured
                                              # overhead is <1% of a
                                              # steady-state step)
    fabric_ceiling: str | None = None         # measured-fabric sweep JSON
                                              # (microbench.osu --json): the
                                              # run judges its achieved
                                              # collective bandwidth against
                                              # this ceiling (obs.efficiency)
    hbm_budget: str | None = None             # device-memory budget for the
                                              # pre-run AOT check
                                              # (obs.memory): bytes with an
                                              # optional KB/MB/GB suffix, or
                                              # "auto" = the live device's
                                              # measured bytes_limit.  The
                                              # step program's
                                              # memory_analysis() is
                                              # compared at run start and a
                                              # loud WARNING fires when it
                                              # exceeds the budget — before
                                              # the full run's compile is
                                              # paid for.  unset = off
    num_slices: int = 0                       # fabric=dcn multislice layout:
                                              # slices x hosts/slice x chips
                                              # (0 = one slice per host)
    fused_conv: bool = False                  # Pallas fused BN-relu-conv3x3
                                              # bottleneck segment (v1
                                              # resnets; ops/fused_conv.py)
    fused_xent: bool = False                  # Pallas blocked cross-entropy
                                              # for large-vocab (MLM) heads
    use_space_to_depth: bool = False          # ResNet stem as 4x4/s1 conv on
                                              # 2x2-packed input (MXU-friendly)
    seq_len: int | None = None                # text models: override the
                                              # registry sequence length
                                              # (long-context runs)
    wire_dtype: str = "uint8"                 # real-data host->device wire
                                              # format; uint8 = 4x less
                                              # traffic, normalize on device
    gradient_accumulation_steps: int = 1      # split each step's batch into
                                              # N microbatches (lax.scan),
                                              # average grads, ONE allreduce
                                              # + optimizer update — batch
                                              # scaling without remat's
                                              # recompute or PP's pipeline
    accum_dtype: str = "f32"                  # microbatch grad-accumulator
                                              # dtype: f32 (exact mean) |
                                              # bf16 (halves the accumulator
                                              # tree AND the allreduce
                                              # bytes — the HBM lever for
                                              # param-bound members whose
                                              # f32 tree OOMs: llama_1b,
                                              # gpt2_moe; ~3 significant
                                              # digits per grad)
    model_parallel: int = 1                   # tensor-parallel degree over
                                              # the mesh "model" axis
                                              # (Megatron-style GSPMD
                                              # shardings; transformers)
    expert_parallel: int = 1                  # expert-parallel degree: MoE
                                              # expert dim sharded over the
                                              # mesh "model" axis (GSPMD
                                              # all-to-all dispatch);
                                              # exclusive with model_parallel
    pipeline_parallel: int = 1                # pipeline stages over the mesh
                                              # "pipe" axis (GPipe
                                              # microbatching via ppermute;
                                              # GPT decoder family)
    num_microbatches: int = 0                 # GPipe microbatches per step
                                              # (0 -> 2x pipeline stages)
    sequence_parallel: int = 1                # sequence shards over the mesh
                                              # "seq" axis (ring /
                                              # ulysses[_flash] attention;
                                              # text models)
    virtual_devices: int | None = None        # debug: provision N virtual
                                              # CPU devices (multi-chip
                                              # paths without hardware)
    gradient_checkpointing: bool = False      # remat transformer layers:
                                              # trade FLOPs for activation
                                              # HBM (long-context headroom)
    attention_impl: str = "dense"             # transformer attention kernel:
                                              # dense|flash single-device
                                              # (flash = Pallas blocked
                                              # softmax); ring|ulysses|
                                              # ulysses_flash under
                                              # --sequence_parallel
    moe_impl: str = "einsum"                  # einsum|ragged: MoE dispatch
                                              # (einsum = GShard GSPMD/EP;
                                              # ragged = grouped-matmul
                                              # ragged_dot fast DP path)
    moe_capacity_factor: float = 1.25         # einsum slots/expert =
                                              # ceil(cf*k*S/E): the
                                              # token-drop pressure valve
                                              # for long-context MoE
    moe_f_chunk: int = 0                      # ragged MoE: FFN-dim tile of
                                              # the grouped matmuls (0 =
                                              # full width, measured best;
                                              # BASELINE.md MoE round 4)
    scan_layers: bool = False                 # decoders: lax.scan over
                                              # stacked layers (one
                                              # compiled body; the
                                              # program-size lever for
                                              # deep/HLO-heavy stacks)
    rnn_impl: str = "hoisted"                 # hoisted|bidi|flax: RNN
                                              # members' GRU form (hoisted =
                                              # input projections batched
                                              # out of the scan; bidi = both
                                              # BiGRU directions in one scan,
                                              # a recorded-null A/B arm;
                                              # flax = linen.RNN control)
    train_dir: str | None = None              # tf_cnn_benchmarks --train_dir:
                                              # save checkpoints here during
                                              # training; --eval restores the
                                              # latest from it
    save_model_steps: int = 0                 # save every N timed steps
                                              # (0 = final state only; the
                                              # steps analog of tf_cnn's
                                              # --save_model_secs)

    # --- latency hiding (round 10) ---
    async_checkpoint: bool = True             # overlap checkpoint writes with
                                              # the step loop: snapshot blocks
                                              # (small), the Orbax write +
                                              # commit runs in a background
                                              # thread (in-flight <= 1).
                                              # Single-process DP/TP/EP/SP
                                              # only; emergency saves,
                                              # io_error@ckpt injection,
                                              # multi-host, and PP saves stay
                                              # synchronous (driver)
    compile_cache: str | None = None          # persistent XLA compile cache
                                              # dir.  unset = auto: reuse an
                                              # already-configured jax cache,
                                              # else <train_dir>/compile_cache
                                              # on stacks where the cache is
                                              # safe; "off" disables; an
                                              # explicit dir is always honored
    prefetch_depth: int = 2                   # host->device input pipeline
                                              # lookahead (real-data runs):
                                              # batches kept in flight so
                                              # decode + DMA overlap the
                                              # running step; also the
                                              # double-buffer depth of the
                                              # host decode queue and the
                                              # input-service ring slots

    # --- host-level shared input service (round 13) ---
    input_service: str = "auto"               # on|off|auto: one decode pool
                                              # per host serving all local
                                              # workers over shared-memory
                                              # batch rings (data/service.py)
                                              # instead of a private pool
                                              # per process.  auto = on when
                                              # >1 worker shares the host;
                                              # off = the per-process
                                              # pipeline (the control arm)
    service_decode_workers: int = 0           # width of the HOST decode
                                              # pool under the service
                                              # (0 = auto: cpu_count-1 for
                                              # the whole host)

    # --- autotuner (round 14) ---
    config: str = "manual"                    # manual: flags mean what
                                              # they say (the reference
                                              # contract); auto: resolve()
                                              # loads this member's tuned
                                              # row from the registry
                                              # (artifacts/tuned/
                                              # <hardware_key>.json,
                                              # tpu_hc_bench.tune) and
                                              # applies its lever
                                              # overrides to every field
                                              # left at the default —
                                              # explicit flags win; no
                                              # row falls back LOUDLY to
                                              # BASELINE defaults
    full_batch_identity: bool = False         # multi-worker input: ship
                                              # each process the FULL
                                              # global batch and let
                                              # device_put keep the local
                                              # slice (the conservative
                                              # pre-round-14 arm, kept
                                              # for the bitwise A/B).
                                              # Default off: each process
                                              # builds the global array
                                              # from its LOCAL rows
                                              # (jax.make_array_from_
                                              # process_local_data) and
                                              # the input service serves
                                              # sliced rings — the W-fold
                                              # host-decode saving

    # --- resilience (round 8; no reference analog — SURVEY.md §5 notes
    # the reference just dies) ---
    on_nonfinite: str = "abort"               # non-finite loss/grad-norm
                                              # policy: abort (fail the run
                                              # loudly) | skip (drop the
                                              # update in-step, donation-
                                              # safe) | rewind (restore the
                                              # last checkpoint + skip a
                                              # window of batches)
    max_bad_steps: int = 10                   # consecutive-failure budget
                                              # for skip/rewind: a poisoned
                                              # run still terminates
    resume: str = "auto"                      # --train_dir restore policy:
                                              # auto (restore latest
                                              # complete checkpoint if any)
                                              # | never (fresh init) | must
                                              # (error if none — crash-loop
                                              # relaunches shouldn't
                                              # silently restart from step 0)
                                              # | elastic (must + the saved
                                              # topology sidecar may differ
                                              # from the live mesh: the
                                              # state is reassembled and
                                              # re-placed — zero1 opt
                                              # shards resplit to the new
                                              # world size — with a loud
                                              # one-line plan; genuinely
                                              # incompatible arm/layout
                                              # transitions refuse with an
                                              # actionable error)
    step_timeout_s: str | None = None         # hung-step watchdog: seconds,
                                              # "auto" (k x warmup mean step
                                              # time), unset/off = disabled
    keep_checkpoints: int = 0                 # retention GC: keep only the
                                              # newest N complete
                                              # checkpoints (0 = keep all)
    inject_fault: str | None = None           # deterministic fault
                                              # injection, e.g. nan_loss@40,
                                              # hang@80:30,sigterm@120,
                                              # io_error@ckpt
                                              # (resilience/inject.py)

    # --- serving lane (round 16; tpu_hc_bench.serve) ---
    workload: str = "train"                   # train|serve: which lane this
                                              # config drives.  Set by the
                                              # serve CLI (`python -m
                                              # tpu_hc_bench serve`), never a
                                              # user flag — the entry point
                                              # IS the workload selection.
                                              # resolve() rejects the other
                                              # lane's knobs loudly under
                                              # either value.
    arrival: str = "poisson"                  # synthetic request arrival
                                              # process: poisson (memoryless
                                              # open loop) | bursty (on/off
                                              # duty cycle) | diurnal
                                              # (sinusoidal rate — the
                                              # day/night traffic shape,
                                              # compressed)
    arrival_rate: float = 8.0                 # mean request arrival rate,
                                              # requests/second (the load
                                              # axis of the SLO report)
    num_requests: int = 64                    # requests in the closed-loop
                                              # run (the serving analog of
                                              # --num_batches)
    serve_buckets: str = "auto"               # decode batch-bucket ladder:
                                              # auto = powers of two up to
                                              # max_in_flight, or explicit
                                              # "1,2,8".  Every bucket is
                                              # AOT-compiled at warmup; the
                                              # ladder is the complete set
                                              # of shapes that can ever run
    max_in_flight: int = 8                    # continuous-batching admission
                                              # cap: requests decoding
                                              # concurrently (also the
                                              # static arm's batch size)
    kv_page_size: int = 16                    # tokens per KV-cache page
                                              # (vLLM-style paged KV: decode
                                              # members allocate cache in
                                              # fixed pages, never per-
                                              # sequence max-length slabs)
    kv_pages: int = 0                         # total pages in the pool
                                              # (0 = auto: enough for
                                              # max_in_flight sequences at
                                              # max_prompt_len +
                                              # max_output_len, + the
                                              # reserved trash page)
    max_prompt_len: int = 64                  # prompt-length ceiling; the
                                              # prefill bucket ladder pads
                                              # up to it
    max_output_len: int = 32                  # generation ceiling per
                                              # request (requests retire at
                                              # max_output_len tokens)
    batching: str = "continuous"              # continuous: admit/retire
                                              # per decode step (Orca-style)
                                              # | static: collect a full
                                              # batch, run it to completion,
                                              # only then admit again (the
                                              # A/B control arm)
    decode_attention: str = "gather"          # decode attention program
                                              # (round 18): gather = dense
                                              # page gather + softmax (the
                                              # parity reference) | paged =
                                              # Pallas flash-decode kernel
                                              # reading K/V directly
                                              # through the page tables
                                              # (ops.paged_attention)
    quant: str = "off"                        # serving quantization arm:
                                              # off | int8_w (per-channel
                                              # int8 weights, dequantized
                                              # AT the matmul) | int8_kv
                                              # (int8 KV pool + per-page
                                              # scales consumed inside the
                                              # paged kernel; requires
                                              # --decode_attention=paged)
    decode_block_pages: int = 0               # paged kernel block size:
                                              # KV pages streamed per grid
                                              # step (0 = auto: 1 page, the
                                              # page IS the block; tuned
                                              # like any other lever)
    slo_e2e_ms: float = 0.0                   # per-request e2e SLO target
                                              # (round 20): windowed
                                              # violation/burn-rate
                                              # tracking in the serve
                                              # summary distinguishes
                                              # sustained overload from a
                                              # transient burst (0 = off)
    deadline_ms: float = 0.0                  # per-request service
                                              # deadline (round 23): the
                                              # shed policies measure
                                              # "already dead" against it
                                              # (0 = fall back to
                                              # slo_e2e_ms)
    shed: str = "off"                         # load shedding: off |
                                              # admit (reject requests
                                              # whose deadline already
                                              # expired at admission) |
                                              # deadline (admit + predict
                                              # queue wait blowing the
                                              # deadline, and retire
                                              # already-expired residents
                                              # instead of decoding dead
                                              # tokens)
    kv_preempt: str = "off"                   # KV-pressure preemption:
                                              # when the pool cannot
                                              # serve an admit, preempt
                                              # the resident with most
                                              # pages per token of
                                              # progress, free its pages,
                                              # requeue it carrying its
                                              # generated prefix (off |
                                              # on)
    serve_faults: str | None = None           # deterministic serve-lane
                                              # fault injection:
                                              # hang@STEP:S,
                                              # nan_logits@RID,
                                              # sigterm@T,
                                              # pool_squeeze@T:PAGES
    serve_journal: str | None = None          # drain journal path
                                              # (default:
                                              # <metrics_dir>/
                                              # serve_journal.json)
    serve_resume: str | None = None           # replay every unfinished
                                              # request from a drain
                                              # journal exactly once
    serve_step_timeout_s: str | None = None   # scheduler-iteration
                                              # watchdog: no iteration
                                              # within this -> timeline/
                                              # memory dumps + exit 70
    kv_reserve: str = "worst"                 # KV reservation policy
                                              # (round 25): worst =
                                              # reserve every request's
                                              # worst-case page count at
                                              # admission (the r22-
                                              # measured 45%-waste
                                              # control) | lazy =
                                              # reserve ceil(prompt/
                                              # page) + kv_growth_
                                              # headroom and grow one
                                              # page on each crossed
                                              # boundary; a failed
                                              # growth falls back to
                                              # prefix-cache eviction,
                                              # then --kv_preempt
    prefix_cache: str = "off"                 # shared-prefix KV cache
                                              # (round 25): on = a
                                              # prefix trie keyed on
                                              # page-aligned prompt
                                              # chunks maps common
                                              # prefixes to shared,
                                              # refcounted physical
                                              # pages; cache-hit admits
                                              # skip the page WRITES
                                              # for shared slots and
                                              # the first append into a
                                              # shared page copies it
                                              # (COW).  Requires
                                              # --kv_reserve=lazy
    kv_growth_headroom: int = 1               # decode pages reserved
                                              # beyond the prompt at
                                              # lazy admission — the
                                              # slack that keeps the
                                              # first decode steps from
                                              # immediately growing

    # Populated by resolve():
    translations: dict[str, str] = dataclasses.field(default_factory=dict)
    # config provenance (resolve()): manual = hand-set flags, auto = a
    # tuned registry row was applied, baseline = --config=auto found no
    # row and fell back to the BASELINE defaults.  BENCH json and the
    # run manifest carry both fields so the perf trajectory can
    # distinguish tuned from hand-set runs.
    config_source: str = "manual"
    tuned_config: dict | None = None
    # Populated by parse_flags(): the flag names the operator actually
    # typed (the launcher's positional batch included).  --config=auto
    # consults this so an EXPLICIT --batch_size=64 pins the default
    # value against the tuned row; programmatic configs leave it None
    # and resolve_auto falls back to "non-default means set".
    explicit_flags: tuple | None = None

    @property
    def compute_dtype(self) -> str:
        """bfloat16 when fp16 requested (TPU has no fp16 MXU path), else f32."""
        return "bfloat16" if self.use_fp16 else "float32"

    def _explicitly_set(self, names: Sequence[str]) -> list[str]:
        """The subset of ``names`` the operator actually set: named in
        ``explicit_flags`` when the config came through ``parse_flags``
        (so an explicit flag typed at its default value still counts),
        else any field whose value differs from the dataclass default
        (the programmatic-construction fallback — the same two-tier
        rule ``tune.registry.resolve_auto`` uses for pinning)."""
        if self.explicit_flags is not None:
            return [n for n in names if n in self.explicit_flags]
        defaults = {f.name: f.default for f in dataclasses.fields(self)}
        return [n for n in names
                if n in defaults and getattr(self, n) != defaults[n]]

    def _resolve_serving(self, t: dict[str, str]) -> "BenchmarkConfig":
        """The ``workload="serve"`` half of resolve(): the serving lane
        shares the parser (every flag still parses) but owns its own
        validity matrix — a training-only knob silently ignored here
        would wear a banner describing machinery that never ran, so it
        dies at flag time instead."""
        bad = self._explicitly_set(TRAIN_ONLY_FLAGS)
        if bad:
            raise ValueError(
                "training-only flag(s) have no meaning under `python -m "
                "tpu_hc_bench serve`: "
                + ", ".join(f"--{b}" for b in bad)
                + " (the serving lane sizes work by --serve_buckets/"
                  "--max_in_flight/--max_prompt_len and owns its own "
                  "decode step; drop the flag or run the training lane)")
        # compute-engine translations shared with the training lane
        if self.mkl:
            t["mkl"] = "TRUE->no-op (XLA:TPU is the compute engine)"
            self.mkl = False
        if self.device == "cpu":
            t["device"] = "cpu->tpu (per-launcher target platform)"
            self.device = "tpu"
        if self.arrival not in ("poisson", "bursty", "diurnal"):
            raise ValueError(
                f"--arrival must be poisson|bursty|diurnal: {self.arrival!r}")
        if self.arrival_rate <= 0:
            raise ValueError(
                f"--arrival_rate must be > 0 req/s: {self.arrival_rate}")
        if self.num_requests < 1:
            raise ValueError(
                f"--num_requests must be >= 1: {self.num_requests}")
        if self.kv_page_size < 1:
            raise ValueError(
                f"--kv_page_size must be >= 1 token: {self.kv_page_size}")
        if self.kv_pages < 0:
            raise ValueError(
                f"--kv_pages must be >= 0 (0 = auto): {self.kv_pages}")
        if self.max_prompt_len < 1:
            raise ValueError(
                f"--max_prompt_len must be >= 1: {self.max_prompt_len}")
        if self.max_output_len < 1:
            raise ValueError(
                f"--max_output_len must be >= 1: {self.max_output_len}")
        if self.batching not in ("continuous", "static"):
            raise ValueError(
                f"--batching must be continuous|static: {self.batching!r}")
        if self.decode_attention not in ("gather", "paged"):
            raise ValueError(
                f"--decode_attention must be gather|paged: "
                f"{self.decode_attention!r}")
        if self.quant not in ("off", "int8_w", "int8_kv"):
            raise ValueError(
                f"--quant must be off|int8_w|int8_kv: {self.quant!r}")
        if self.quant == "int8_kv" and self.decode_attention != "paged":
            raise ValueError(
                "--quant=int8_kv stores per-page scales that are "
                "consumed INSIDE the paged decode kernel; set "
                "--decode_attention=paged (the gather reference has no "
                "scale-fused read path)")
        if self.decode_block_pages < 0:
            raise ValueError(
                f"--decode_block_pages must be >= 0 (0 = auto): "
                f"{self.decode_block_pages}")
        if self.decode_block_pages and self.decode_attention != "paged":
            raise ValueError(
                "--decode_block_pages sizes the paged kernel's page "
                "blocks; it has no meaning under "
                "--decode_attention=gather")
        if self.slo_e2e_ms < 0:
            raise ValueError(
                f"--slo_e2e_ms must be >= 0 ms (0 = no SLO tracking): "
                f"{self.slo_e2e_ms}")
        # round 23: the degradation/survival knobs
        if self.deadline_ms < 0:
            raise ValueError(
                f"--deadline_ms must be >= 0 ms (0 = use --slo_e2e_ms): "
                f"{self.deadline_ms}")
        if self.shed not in ("off", "admit", "deadline"):
            raise ValueError(
                f"--shed must be off|admit|deadline: {self.shed!r}")
        if self.shed != "off" and not (self.deadline_ms
                                       or self.slo_e2e_ms):
            raise ValueError(
                "--shed needs a deadline to shed against: set "
                "--deadline_ms (or --slo_e2e_ms, its fallback)")
        if self.kv_preempt not in ("off", "on"):
            raise ValueError(
                f"--kv_preempt must be off|on: {self.kv_preempt!r}")
        # round 25: lazy reservation + shared-prefix cache
        if self.kv_reserve not in ("worst", "lazy"):
            raise ValueError(
                f"--kv_reserve must be worst|lazy: {self.kv_reserve!r}")
        if self.prefix_cache not in ("off", "on"):
            raise ValueError(
                f"--prefix_cache must be off|on: {self.prefix_cache!r}")
        if self.prefix_cache == "on" and self.kv_reserve != "lazy":
            raise ValueError(
                "--prefix_cache=on shares pages a worst-case "
                "reservation would immediately duplicate; set "
                "--kv_reserve=lazy (sharing only saves pages when "
                "admission stops reserving the worst case)")
        if self.kv_growth_headroom < 0:
            raise ValueError(
                f"--kv_growth_headroom must be >= 0 pages: "
                f"{self.kv_growth_headroom}")
        if self.serve_faults:
            from tpu_hc_bench.serve.faults import parse_serve_plan

            parse_serve_plan(self.serve_faults)     # loud format check
        if self.serve_step_timeout_s is not None:
            from tpu_hc_bench.resilience.watchdog import resolve_timeout

            resolve_timeout(self.serve_step_timeout_s)  # loud check
        # loud format checks (raise on malformed spec; values re-read by
        # the engine)
        parse_serve_buckets(self.serve_buckets, self.max_in_flight)
        if self.hbm_budget is not None:
            from tpu_hc_bench.obs.memory import parse_hbm_budget

            parse_hbm_budget(self.hbm_budget)
        self.translations = t
        return self

    def resolve(self) -> "BenchmarkConfig":
        """Apply TPU translations of reference-literal flag values.

        Mirrors the judgment call in SURVEY.md §7 hard-parts (a): honor flag
        *semantics*, not literal values that would be wrong on TPU.
        """
        t: dict[str, str] = {}
        if self.config not in ("manual", "auto"):
            raise ValueError(
                f"--config must be manual|auto: {self.config!r}")
        if self.config == "auto":
            # the one deliberate exception to resolve()'s filesystem-
            # purity principle (--fabric_ceiling/--compile_cache defer
            # their reads to the driver): --config=auto IS a registry
            # read, and it must happen before the validations below so
            # an applied tuned row is checked like any hand-set flag.
            # Registry dir / hardware key honor TPU_HC_TUNE_REGISTRY /
            # TPU_HC_TUNE_HW env overrides (tune.registry).
            from tpu_hc_bench.tune import registry as tune_registry

            t["config"] = tune_registry.resolve_auto(self)
        if self.workload not in ("train", "serve"):
            raise ValueError(
                f"workload must be train|serve: {self.workload!r}")
        if self.flight_recorder not in ("on", "off"):
            # shared by both lanes, so validated before the serve branch
            raise ValueError(
                f"--flight_recorder must be on|off: "
                f"{self.flight_recorder!r}")
        if self.workload == "serve":
            # the serving lane (round 16): its own validity matrix, and
            # none of the training-lane translations/duration defaults
            # below apply
            return self._resolve_serving(t)
        extras = self._explicitly_set(SERVE_ONLY_FLAGS)
        if extras:
            raise ValueError(
                "serving-lane flag(s) have no meaning in the training "
                "lane: " + ", ".join(f"--{e}" for e in extras)
                + " — run `python -m tpu_hc_bench serve` for the "
                  "request-driven benchmark")
        if self.data_format.upper() == "NCHW":
            t["data_format"] = "NCHW->NHWC (MXU wants channels-minor)"
            self.data_format = "NHWC"
        if self.mkl:
            t["mkl"] = "TRUE->no-op (XLA:TPU is the compute engine)"
            self.mkl = False
        if self.device == "cpu":
            t["device"] = "cpu->tpu (per-launcher target platform)"
            self.device = "tpu"
        if self.variable_update == "horovod":
            t["variable_update"] = "horovod->psum (XLA allreduce over mesh)"
            self.variable_update = "psum"
        if self.variable_update == "zero1":
            # ZeRO-1 shards the optimizer state over the data axis; every
            # unsupported composition dies at flag time, not 50 warmup
            # steps in
            if self.model_parallel > 1 or self.expert_parallel > 1:
                raise ValueError(
                    "--variable_update=zero1 composes with plain data "
                    "parallelism only (TP/EP run on the GSPMD arm)")
            if self.pipeline_parallel > 1:
                raise ValueError(
                    "--variable_update=zero1 is not supported with "
                    "--pipeline_parallel (the GPipe arm owns its own "
                    "gradient path; no sharded-optimizer layout)")
            if (self.sequence_parallel > 1
                    or self.attention_impl in SEQ_SHARDED_IMPLS):
                raise ValueError(
                    "--variable_update=zero1 composes with plain data "
                    "parallelism only: the SP step reduces over "
                    "(data, seq) and the zero1 reduce-scatter layout is "
                    "data-axis only")
            if self.forward_only:
                raise ValueError(
                    "--variable_update=zero1 shards the OPTIMIZER state; "
                    "forward-only runs have none (use psum)")
        if self.horovod_device in ("cpu", "gpu"):
            t["horovod_device"] = f"{self.horovod_device}->tpu"
            self.horovod_device = "tpu"
        if self.local_parameter_device in ("cpu", "gpu"):
            t["local_parameter_device"] = f"{self.local_parameter_device}->tpu"
            self.local_parameter_device = "tpu"
        if self.num_intra_threads or self.kmp_blocktime != 1:
            t["thread_tuning"] = (
                "num_intra/inter_threads,kmp_* parsed but no-op on TPU"
            )
        if self.num_epochs and self.num_batches is not None:
            # tf_cnn_benchmarks semantics: the two duration flags conflict
            raise ValueError(
                "--num_batches and --num_epochs cannot both be set"
            )
        if self.num_epochs < 0:
            raise ValueError(f"--num_epochs must be >= 0: {self.num_epochs}")
        if self.num_batches is None and not self.num_epochs:
            self.num_batches = DEFAULT_NUM_BATCHES
        if self.profile_steps is not None:
            if not self.trace_dir:
                raise ValueError(
                    "--profile_steps selects WHICH timed steps to profile; "
                    "--trace_dir says where the trace goes — set both")
            if self.eval:
                # same loud-error principle as the other eval exclusions:
                # the window is defined over the timed TRAINING steps, and
                # accepting the flag under --eval would silently write no
                # trace
                raise ValueError(
                    "--profile_steps applies to the timed training loop; "
                    "it has no meaning under --eval")
            parse_profile_steps(self.profile_steps)  # loud format check
        # --fabric_ceiling is validated at RUN start (driver loads the
        # sweep before the banner): resolve() stays filesystem-pure so
        # configs parse on machines that don't hold the artifacts
        if self.model_parallel > 1 and self.expert_parallel > 1:
            raise ValueError(
                "--model_parallel and --expert_parallel are exclusive: both "
                "shard over the mesh 'model' axis"
            )
        if self.gradient_accumulation_steps < 1:
            raise ValueError(
                f"--gradient_accumulation_steps must be >= 1: "
                f"{self.gradient_accumulation_steps}")
        if self.gradient_accumulation_steps > 1:
            # accumulation lives in the explicit-psum DP/SP step (a
            # lax.scan over microbatches before the single fused
            # allreduce); the other arms reject loudly rather than run
            # with the flag silently ignored
            if self.pipeline_parallel > 1:
                raise ValueError(
                    "--gradient_accumulation_steps: pipeline parallelism "
                    "already microbatches (--num_microbatches)")
            if self.model_parallel > 1 or self.expert_parallel > 1:
                raise ValueError(
                    "--gradient_accumulation_steps is not supported on the "
                    "GSPMD TP/EP arm (supported: DP and DP x SP)")
            if (self.variable_update == "replicated"
                    and self.sequence_parallel <= 1
                    and self.attention_impl not in SEQ_SHARDED_IMPLS):
                # under SP — including the degenerate seq-1 axis the
                # seq-sharded attention impls select — replicated is
                # translated to psum further down (the SP blocks below),
                # and that combo is supported; only the true GSPMD arm
                # rejects
                raise ValueError(
                    "--gradient_accumulation_steps needs "
                    "--variable_update=psum or zero1 (the explicit "
                    "shard_map step)")
            if self.forward_only or self.eval:
                raise ValueError(
                    "--gradient_accumulation_steps is a training-step "
                    "knob; it has no meaning forward-only / under --eval")
        if self.accum_dtype not in ("f32", "bf16"):
            raise ValueError(
                f"--accum_dtype must be f32 or bf16: {self.accum_dtype!r}")
        if self.accum_dtype != "f32" and self.gradient_accumulation_steps == 1:
            raise ValueError(
                "--accum_dtype selects the microbatch grad-accumulator "
                "dtype; it has no meaning without "
                "--gradient_accumulation_steps > 1")
        # round 2: minor axes compose — supported hybrids are DPxPPxTP and
        # DPxSPxTP (model auto/GSPMD under a manual PP/SP shard_map); the
        # remaining pairings are rejected here and in run_benchmark
        if self.pipeline_parallel > 1 and self.sequence_parallel > 1:
            raise ValueError(
                "--pipeline_parallel x --sequence_parallel is not a "
                "supported composition (supported: DPxPPxTP, DPxSPxTP)"
            )
        if self.expert_parallel > 1 and (self.pipeline_parallel > 1
                                         or self.sequence_parallel > 1):
            raise ValueError(
                "--expert_parallel composes with data parallelism only"
            )
        if self.sequence_parallel > 1:
            if self.variable_update == "replicated":
                note = (
                    f"replicated->psum (sequence_parallel="
                    f"{self.sequence_parallel} runs the explicit shard_map "
                    f"step; gradients fuse-psum over both mesh axes)"
                )
                prior = t.get("variable_update")
                t["variable_update"] = f"{prior}; {note}" if prior else note
                self.variable_update = "psum"
            # SP needs a sequence-sharded attention impl; translate the
            # single-device names to their SP counterparts
            sp_map = {"dense": "ring", "flash": "ulysses_flash"}
            if self.attention_impl in sp_map:
                new = sp_map[self.attention_impl]
                t["attention_impl"] = (
                    f"{self.attention_impl}->{new} (sequence_parallel="
                    f"{self.sequence_parallel} shards the sequence axis)"
                )
                self.attention_impl = new
        elif self.attention_impl in SEQ_SHARDED_IMPLS:
            # DEGENERATE SP (round 3): the seq-sharded impls run on a
            # size-1 seq axis — world-1 collectives are no-ops, so this
            # measures the SP machinery's overhead on a single chip (the
            # performance-evidence run VERDICT #9 asks for).  The psum
            # step still reduces over (data, seq).  Plain DP only: the
            # PP/EP/TP compositions are keyed on sequence_parallel > 1
            # throughout, so a degenerate seq axis under them would
            # silently skip or misconfigure those paths.
            if (self.pipeline_parallel > 1 or self.expert_parallel > 1
                    or self.model_parallel > 1):
                raise ValueError(
                    f"--attention_impl={self.attention_impl} with "
                    "--sequence_parallel=1 (degenerate SP) composes with "
                    "plain data parallelism only; set "
                    "--sequence_parallel>1 for the SP hybrids")
            note = (f"sequence_parallel=1: degenerate seq axis (size 1) — "
                    f"{self.attention_impl} collectives are world-1 no-ops")
            t["sequence_parallel"] = note
            if self.variable_update == "replicated":
                note2 = ("replicated->psum (degenerate seq axis runs the "
                         "explicit (data, seq) shard_map step)")
                prior = t.get("variable_update")
                t["variable_update"] = (f"{prior}; {note2}" if prior
                                        else note2)
                self.variable_update = "psum"
        # --- resilience flag surface (round 8): every invalid combination
        # dies at flag time, not 50 warmup steps in ---
        if self.on_nonfinite not in ("abort", "skip", "rewind"):
            raise ValueError(
                f"--on_nonfinite must be abort|skip|rewind: "
                f"{self.on_nonfinite!r}")
        if self.on_nonfinite in ("skip", "rewind"):
            if self.forward_only or self.eval:
                raise ValueError(
                    "--on_nonfinite=skip/rewind guards the optimizer "
                    "update; forward-only/--eval runs have none (abort "
                    "still applies)")
            if self.pipeline_parallel > 1:
                raise ValueError(
                    "--on_nonfinite=skip/rewind is not supported on the "
                    "GPipe arm yet (the PP step owns its own update "
                    "loop); supported: DP / TP / EP / SP / multislice")
        if self.on_nonfinite == "rewind" and not self.train_dir:
            raise ValueError(
                "--on_nonfinite=rewind restores the last checkpoint — "
                "set --train_dir")
        if self.on_nonfinite == "rewind" and self.resume == "never":
            raise ValueError(
                "--on_nonfinite=rewind restores from --train_dir; "
                "--resume=never contradicts that (a rewind could "
                "resurrect the very checkpoints you asked to ignore)")
        if self.max_bad_steps < 1:
            raise ValueError(
                f"--max_bad_steps must be >= 1: {self.max_bad_steps}")
        if self.resume not in ("auto", "never", "must", "elastic"):
            raise ValueError(
                f"--resume must be auto|never|must|elastic: {self.resume!r}")
        if self.resume in ("must", "elastic") and not self.train_dir:
            raise ValueError(f"--resume={self.resume} needs --train_dir")
        if self.keep_checkpoints < 0:
            raise ValueError(
                f"--keep_checkpoints must be >= 0: {self.keep_checkpoints}")
        if self.prefetch_depth < 1:
            raise ValueError(
                f"--prefetch_depth must be >= 1 (1 = no lookahead): "
                f"{self.prefetch_depth}")
        # --- input service (round 13): config-resolvable exclusions
        # translate loudly here; world-shape ones (multi-host grouping)
        # are only known to the driver ---
        if self.input_service not in ("on", "off", "auto"):
            raise ValueError(
                f"--input_service must be on|off|auto: "
                f"{self.input_service!r}")
        if self.service_decode_workers < 0:
            raise ValueError(
                f"--service_decode_workers must be >= 0 (0 = auto): "
                f"{self.service_decode_workers}")
        if self.input_service == "on":
            is_text = False
            if self.data_dir is not None:
                from tpu_hc_bench.models import get_model_spec

                try:
                    is_text = get_model_spec(self.model).is_text
                except ValueError:
                    pass    # unknown model: let create_model raise later
            if self.data_dir is None:
                t["input_service"] = ("on->off (synthetic input has no "
                                      "host decode pipeline to share)")
                self.input_service = "off"
            elif is_text:
                # loud, not silent: the driver's service arm covers the
                # image TFRecord path; text members read a memmapped
                # corpus per-process (page-cache-shared, no decode) —
                # the packed-token service exists at the API level only
                # (data.service.make_packed_token_service)
                t["input_service"] = (
                    "on->off (text members read a memmapped corpus "
                    "per-process; the packed-token service is not "
                    "driver-wired yet — see "
                    "data.service.make_packed_token_service)")
                self.input_service = "off"
            elif self.datasets_repeat_cached_sample:
                t["input_service"] = (
                    "on->off (--datasets_repeat_cached_sample decodes a "
                    "handful of batches once and shuts the pipeline down "
                    "— nothing to serve)")
                self.input_service = "off"
            elif self.eval:
                t["input_service"] = (
                    "on->off (--eval reads the validation split "
                    "per-process; the service targets the sustained "
                    "training input plane)")
                self.input_service = "off"
        # --compile_cache stays filesystem-pure here (same principle as
        # --fabric_ceiling): the driver resolves auto/off and creates the
        # directory at run start
        if self.hbm_budget is not None:
            from tpu_hc_bench.obs.memory import parse_hbm_budget

            parse_hbm_budget(self.hbm_budget)   # loud format check;
            # "auto" resolves against the live device at run start
        if self.step_timeout_s is not None:
            from tpu_hc_bench.resilience.watchdog import resolve_timeout

            resolve_timeout(self.step_timeout_s)    # loud format check
        if self.inject_fault:
            from tpu_hc_bench.resilience.inject import parse_plan

            parse_plan(self.inject_fault)           # loud format check
        if self.moe_impl == "auto":
            from tpu_hc_bench.models import get_model_spec

            try:
                is_moe = get_model_spec(self.model).moe
            except ValueError:
                is_moe = False      # unknown model: let create_model raise
            if not is_moe:
                raise ValueError(
                    f"--moe_impl=auto only applies to MoE members, not "
                    f"{self.model}")
            # round 3: pick the dispatch by MEASUREMENT — einsum wins at
            # short/medium seq (49.2 vs 31.2 ex/s on gpt2_moe seq 1024,
            # BASELINE.md) and is the GSPMD path EP/TP require; ragged
            # grouped matmuls take over at long seq (the O(S) dispatch:
            # einsum needs the token-dropping capacity valve at seq 4096
            # and fails to compile beyond)
            long_seq = (self.seq_len or 0) >= 4096
            new = ("ragged" if (long_seq and self.expert_parallel == 1
                                and self.model_parallel == 1
                                and self.moe_capacity_factor == 1.25)
                   else "einsum")
            t["moe_impl"] = (f"auto->{new} (einsum short-seq/EP/TP, "
                             f"ragged at seq>=4096 single-shard)")
            self.moe_impl = new
        if self.moe_impl == "ragged" and self.moe_capacity_factor != 1.25:
            raise ValueError(
                "--moe_capacity_factor applies to the einsum dispatch only: "
                "the ragged grouped-matmul path has no capacity concept "
                "(zero token drops), so the flag would be silently ignored"
            )
        if self.moe_impl == "ragged" and (
                self.expert_parallel > 1 or self.model_parallel > 1):
            # TP also shards the expert tensors over the model axis
            # (tp_param_spec's moe/ rules), so both spellings are blocked
            raise ValueError(
                "--expert_parallel/--model_parallel require "
                "--moe_impl=einsum (ragged_dot grouped matmuls are "
                "single-shard; the GShard einsum dispatch is the "
                "GSPMD-shardable path)"
            )
        if self.pipeline_parallel > 1:
            note = (
                f"{self.variable_update}->n/a (pipeline_parallel="
                f"{self.pipeline_parallel} runs the dedicated GPipe "
                f"shard_map step with its own gradient psums)"
            )
            # append rather than overwrite: an earlier horovod->psum
            # record must stay in the audit trail
            prior = t.get("variable_update")
            t["variable_update"] = f"{prior}; {note}" if prior else note
        sharded = max(self.model_parallel, self.expert_parallel)
        # ...but NOT under the SP (or PP) hybrids: there the manual
        # shard_map step keeps running and the model axis rides auto/GSPMD
        # inside it, so variable_update stays on the psum path
        if (sharded > 1 and self.variable_update != "replicated"
                and self.sequence_parallel == 1
                and self.pipeline_parallel == 1):
            which = ("model_parallel" if self.model_parallel > 1
                     else "expert_parallel")
            t["variable_update"] = (
                f"{self.variable_update}->replicated ({which}={sharded} "
                f"runs on the GSPMD arm; the explicit fused-psum path and "
                f"fusion_threshold do not apply)"
            )
            self.variable_update = "replicated"
        if self.overlap_grad_comm not in ("on", "off"):
            raise ValueError(
                f"--overlap_grad_comm must be on|off: "
                f"{self.overlap_grad_comm!r}")
        if (self.overlap_grad_comm == "off"
                and self.variable_update == "replicated"
                and self.pipeline_parallel == 1):
            # the GSPMD arm's collectives are scheduled by XLA; the flag
            # only shapes the explicit psum/zero1 programs — record the
            # no-op instead of silently accepting it
            t["overlap_grad_comm"] = (
                "off->n/a (GSPMD schedules its own collectives; the flag "
                "applies to the psum/zero1 arms)")
        self.translations = t
        return self

    def summary_lines(self) -> list[str]:
        """Config header in the spirit of run-tf-sing-ucx-openmpi.sh:52-58."""
        if self.workload == "serve":
            buckets = ",".join(
                str(b) for b in parse_serve_buckets(self.serve_buckets,
                                                    self.max_in_flight))
            lines = [
                f"model={self.model} workload=serve "
                f"batching={self.batching} dtype={self.compute_dtype}",
                f"arrival={self.arrival} rate={self.arrival_rate}/s "
                f"requests={self.num_requests} "
                f"prompt<={self.max_prompt_len} output<={self.max_output_len}",
                f"buckets={buckets} max_in_flight={self.max_in_flight} "
                f"kv_page_size={self.kv_page_size} "
                f"kv_pages={self.kv_pages or 'auto'}",
                f"decode_attention={self.decode_attention} "
                f"quant={self.quant}"
                + (f" decode_block_pages={self.decode_block_pages}"
                   if self.decode_block_pages else ""),
            ]
            if self.kv_reserve != "worst" or self.prefix_cache != "off":
                lines.append(
                    f"kv_reserve={self.kv_reserve} "
                    f"prefix_cache={self.prefix_cache} "
                    f"growth_headroom={self.kv_growth_headroom}")
            if (self.shed != "off" or self.kv_preempt != "off"
                    or self.serve_faults or self.serve_resume
                    or self.serve_step_timeout_s):
                lines.append(
                    f"shed={self.shed} kv_preempt={self.kv_preempt}"
                    + (f" deadline_ms={self.deadline_ms:g}"
                       if self.deadline_ms else "")
                    + (f" faults={self.serve_faults}"
                       if self.serve_faults else "")
                    + (f" resume={self.serve_resume}"
                       if self.serve_resume else "")
                    + (f" watchdog={self.serve_step_timeout_s}s"
                       if self.serve_step_timeout_s else ""))
            for k, v in self.translations.items():
                lines.append(f"translated: {k}: {v}")
            return lines
        lines = [
            f"model={self.model} batch_size/worker={self.batch_size} "
            f"optimizer={self.optimizer} dtype={self.compute_dtype}",
            f"warmup={self.num_warmup_batches} timed={self.num_batches} "
            f"display_every={self.display_every} forward_only={self.forward_only}",
            f"data={'synthetic' if self.data_dir is None else self.data_dir}"
            + (" [repeat_cached_sample]"
               if self.datasets_repeat_cached_sample else "")
            + f" ({self.data_name}, {self.data_format})"
            + f" prefetch_depth={self.prefetch_depth}"
            + (f" input_service={self.input_service}"
               if self.data_dir is not None else ""),
            f"variable_update={self.variable_update} "
            f"fusion_threshold={self.fusion_threshold_bytes}B"
            + (f" overlap_grad_comm={self.overlap_grad_comm}"
               if self.variable_update in ("psum", "zero1") else "")
            + (f" model_parallel={self.model_parallel}"
               if self.model_parallel > 1 else "")
            + (f" expert_parallel={self.expert_parallel}"
               if self.expert_parallel > 1 else "")
            + (f" pipeline_parallel={self.pipeline_parallel}"
               f" num_microbatches={self.num_microbatches or 'auto'}"
               if self.pipeline_parallel > 1 else "")
            + (f" sequence_parallel={self.sequence_parallel}"
               if self.sequence_parallel > 1 else "")
            + (f" gradient_accumulation_steps="
               f"{self.gradient_accumulation_steps}"
               if self.gradient_accumulation_steps > 1 else "")
            + (f" accum_dtype={self.accum_dtype}"
               if self.accum_dtype != "f32" else ""),
        ]
        for k, v in self.translations.items():
            lines.append(f"translated: {k}: {v}")
        return lines


def build_parser() -> argparse.ArgumentParser:
    """Argument parser covering the full reference flag surface (§2d)."""
    p = argparse.ArgumentParser(
        prog="tpu_hc_bench",
        description="TPU-native tf_cnn_benchmarks-compatible benchmark driver",
    )
    d = BenchmarkConfig()
    p.add_argument("--batch_size", type=int, default=d.batch_size)
    p.add_argument("--num_warmup_batches", type=int, default=d.num_warmup_batches)
    p.add_argument("--num_batches", type=int, default=None)
    p.add_argument("--num_epochs", type=float, default=d.num_epochs)
    p.add_argument("--model", type=str, default=d.model)
    p.add_argument("--display_every", type=int, default=d.display_every)
    p.add_argument("--optimizer", type=str, default=d.optimizer,
                   choices=["momentum", "sgd", "adam", "adamw", "rmsprop"])
    p.add_argument("--forward_only", type=_parse_bool, default=d.forward_only)
    p.add_argument("--eval", type=_parse_bool, default=False)
    p.add_argument("--init_learning_rate", type=float, default=d.init_learning_rate)
    p.add_argument("--momentum", type=float, default=d.momentum)
    p.add_argument("--data_dir", type=str, default=None)
    p.add_argument("--data_name", type=str, default=d.data_name)
    p.add_argument("--data_format", type=str, default="NHWC",
                   choices=["NCHW", "NHWC", "nchw", "nhwc"])
    p.add_argument("--device", type=str, default=d.device,
                   choices=["cpu", "tpu"])
    p.add_argument("--mkl", type=_parse_bool, default=False)
    p.add_argument("--use_fp16", type=_parse_bool, default=False)
    p.add_argument("--variable_update", type=str, default="psum",
                   choices=["horovod", "psum", "replicated", "zero1"])
    p.add_argument("--overlap_grad_comm", type=str, default=d.overlap_grad_comm,
                   choices=["on", "off"])
    p.add_argument("--horovod_device", type=str, default=d.horovod_device)
    p.add_argument("--local_parameter_device", type=str,
                   default=d.local_parameter_device)
    p.add_argument("--num_intra_threads", type=int, default=d.num_intra_threads)
    p.add_argument("--num_inter_threads", type=int, default=d.num_inter_threads)
    p.add_argument("--kmp_blocktime", type=int, default=d.kmp_blocktime)
    p.add_argument("--kmp_affinity", type=str, default=d.kmp_affinity)
    p.add_argument("--datasets_num_private_threads", type=int,
                   default=d.datasets_num_private_threads)
    p.add_argument("--datasets_repeat_cached_sample", type=_parse_bool,
                   default=d.datasets_repeat_cached_sample)
    p.add_argument("--train_dir", type=str, default=None)
    p.add_argument("--save_model_steps", type=int, default=d.save_model_steps)
    p.add_argument("--async_checkpoint", type=_parse_bool,
                   default=d.async_checkpoint)
    p.add_argument("--compile_cache", type=str, default=d.compile_cache,
                   metavar="DIR|off")
    p.add_argument("--prefetch_depth", type=int, default=d.prefetch_depth)
    p.add_argument("--input_service", type=str, default=d.input_service,
                   choices=["on", "off", "auto"])
    p.add_argument("--service_decode_workers", type=int,
                   default=d.service_decode_workers)
    p.add_argument("--config", type=str, default=d.config,
                   choices=["manual", "auto"])
    p.add_argument("--full_batch_identity", type=_parse_bool,
                   default=d.full_batch_identity)
    p.add_argument("--on_nonfinite", type=str, default=d.on_nonfinite,
                   choices=["abort", "skip", "rewind"])
    p.add_argument("--max_bad_steps", type=int, default=d.max_bad_steps)
    p.add_argument("--resume", type=str, default=d.resume,
                   choices=["auto", "never", "must", "elastic"])
    p.add_argument("--step_timeout_s", type=str, default=d.step_timeout_s)
    p.add_argument("--keep_checkpoints", type=int,
                   default=d.keep_checkpoints)
    p.add_argument("--inject_fault", type=str, default=d.inject_fault,
                   metavar="CLASS@STEP[,...]")
    p.add_argument("--moe_capacity_factor", type=float,
                   default=d.moe_capacity_factor)
    p.add_argument("--fusion_threshold_bytes", type=int,
                   default=d.fusion_threshold_bytes)
    p.add_argument("--seed", type=int, default=d.seed)
    p.add_argument("--num_classes", type=int, default=d.num_classes)
    p.add_argument("--trace_dir", type=str, default=None)
    p.add_argument("--profile_steps", type=str, default=None,
                   metavar="A:B")
    p.add_argument("--metrics_dir", type=str, default=None)
    p.add_argument("--flight_recorder", type=str,
                   default=d.flight_recorder, choices=["on", "off"])
    p.add_argument("--fabric_ceiling", type=str, default=None,
                   metavar="SWEEP_JSON")
    p.add_argument("--hbm_budget", type=str, default=None,
                   metavar="BYTES|auto")
    p.add_argument("--num_slices", type=int, default=d.num_slices)
    p.add_argument("--fused_conv", type=_parse_bool, default=d.fused_conv)
    p.add_argument("--fused_xent", type=_parse_bool, default=False)
    p.add_argument("--use_space_to_depth", type=_parse_bool,
                   default=d.use_space_to_depth)
    p.add_argument("--seq_len", type=int, default=d.seq_len)
    p.add_argument("--wire_dtype", type=str, default=d.wire_dtype,
                   choices=["float32", "uint8"])
    p.add_argument("--gradient_accumulation_steps", type=int,
                   default=d.gradient_accumulation_steps)
    p.add_argument("--accum_dtype", type=str, default=d.accum_dtype,
                   choices=["f32", "bf16"])
    p.add_argument("--model_parallel", type=int, default=d.model_parallel)
    p.add_argument("--expert_parallel", type=int, default=d.expert_parallel)
    p.add_argument("--pipeline_parallel", type=int,
                   default=d.pipeline_parallel)
    p.add_argument("--num_microbatches", type=int, default=d.num_microbatches)
    p.add_argument("--sequence_parallel", type=int,
                   default=d.sequence_parallel)
    p.add_argument("--virtual_devices", type=int, default=d.virtual_devices)
    p.add_argument("--gradient_checkpointing", type=_parse_bool,
                   default=d.gradient_checkpointing)
    p.add_argument("--attention_impl", type=str, default=d.attention_impl,
                   choices=["dense", "flash", "ring", "ulysses",
                            "ulysses_flash"])
    p.add_argument("--moe_impl", type=str, default=d.moe_impl,
                   choices=["auto", "einsum", "ragged"])
    p.add_argument("--rnn_impl", type=str, default=d.rnn_impl,
                   choices=["hoisted", "bidi", "flax"])
    p.add_argument("--scan_layers", type=_parse_bool, default=d.scan_layers)
    p.add_argument("--moe_f_chunk", type=int, default=d.moe_f_chunk)
    # --- serving lane (round 16): parse everywhere, validated by
    # resolve() under workload="serve" only (and rejected loudly when
    # explicitly set on a training run) ---
    p.add_argument("--arrival", type=str, default=d.arrival,
                   choices=["poisson", "bursty", "diurnal"])
    p.add_argument("--arrival_rate", type=float, default=d.arrival_rate)
    p.add_argument("--num_requests", type=int, default=d.num_requests)
    p.add_argument("--serve_buckets", type=str, default=d.serve_buckets,
                   metavar="auto|B1,B2,...")
    p.add_argument("--max_in_flight", type=int, default=d.max_in_flight)
    p.add_argument("--kv_page_size", type=int, default=d.kv_page_size)
    p.add_argument("--kv_pages", type=int, default=d.kv_pages)
    p.add_argument("--max_prompt_len", type=int, default=d.max_prompt_len)
    p.add_argument("--max_output_len", type=int, default=d.max_output_len)
    p.add_argument("--batching", type=str, default=d.batching,
                   choices=["continuous", "static"])
    p.add_argument("--decode_attention", type=str,
                   default=d.decode_attention,
                   choices=["gather", "paged"])
    p.add_argument("--quant", type=str, default=d.quant,
                   choices=["off", "int8_w", "int8_kv"])
    p.add_argument("--decode_block_pages", type=int,
                   default=d.decode_block_pages)
    p.add_argument("--slo_e2e_ms", type=float, default=d.slo_e2e_ms)
    # --- round 23: overload/failure survival knobs ---
    p.add_argument("--deadline_ms", type=float, default=d.deadline_ms)
    p.add_argument("--shed", type=str, default=d.shed,
                   choices=["off", "admit", "deadline"])
    p.add_argument("--kv_preempt", type=str, default=d.kv_preempt,
                   choices=["off", "on"])
    p.add_argument("--serve_faults", type=str, default=None,
                   metavar="hang@N:S,nan_logits@RID,sigterm@T,"
                           "pool_squeeze@T:PAGES")
    p.add_argument("--serve_journal", type=str, default=None,
                   metavar="PATH")
    p.add_argument("--serve_resume", type=str, default=None,
                   metavar="JOURNAL")
    p.add_argument("--serve_step_timeout_s", type=str, default=None,
                   metavar="SECONDS")
    # --- round 25: lazy KV reservation + shared-prefix cache ---
    p.add_argument("--kv_reserve", type=str, default=d.kv_reserve,
                   choices=["worst", "lazy"])
    p.add_argument("--prefix_cache", type=str, default=d.prefix_cache,
                   choices=["off", "on"])
    p.add_argument("--kv_growth_headroom", type=int,
                   default=d.kv_growth_headroom)
    return p


def parse_flags(argv: Sequence[str] | None = None,
                workload: str = "train") -> BenchmarkConfig:
    """Parse a tf_cnn_benchmarks-style argv into a resolved BenchmarkConfig.

    ``workload`` is set by the entry point, not a flag: the serve CLI
    (`python -m tpu_hc_bench serve`) passes ``"serve"`` so resolve()
    runs the serving lane's validity matrix (and the tuned-config
    registry keys its lookup on the ``<model>@serve`` row).
    """
    if argv is None:
        import sys

        argv = sys.argv[1:]
    ns = build_parser().parse_args(argv)
    fields = {f.name for f in dataclasses.fields(BenchmarkConfig)}
    kwargs: dict[str, Any] = {
        k: v for k, v in vars(ns).items() if k in fields
    }
    kwargs["data_format"] = kwargs["data_format"].upper()
    cfg = BenchmarkConfig(**kwargs)
    cfg.workload = workload
    # record what the operator actually typed BEFORE resolve():
    # --config=auto must honor an explicit flag even when its value
    # equals the dataclass default
    cfg.explicit_flags = tuple(sorted(
        {a[2:].split("=", 1)[0] for a in argv if a.startswith("--")}
        & fields))
    return cfg.resolve()
