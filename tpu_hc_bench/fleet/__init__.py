"""Fleet orchestrator: many training jobs, one device pool.

The production story on the training side ("heavy traffic from
millions of users" — ROADMAP): N zoo members packed onto one shared
pool of chips, kept at high fleet-wide goodput while jobs are
continuously killed and resized by spot churn and priority arrivals.
Nothing here invents new machinery — the subsystem is a composition of
contracts the single-job layers already pin:

- the launcher's **exit-code contract** (0/1/70/75 —
  ``resilience.EXIT_CLASSES``) classifies every death;
- **graceful preemption** (``resilience.preempt``): the supervisor's
  SIGTERM rides the same emergency-checkpoint path as a spot notice;
- **elastic resume** (``--resume=elastic``, round 12): a preempted job
  relaunches at whatever world the scheduler can grant, not just the
  world it lost;
- the **measured HBM model** (``tune/prune.hbm_model_for``) refuses
  admissions that would OOM, measured anchors first;
- **heartbeats + incarnation counters** (``obs/fleet``) give the
  supervisor liveness, and the **flight recorder** (``obs/timeline``)
  gives the report per-job span timelines.

Modules: ``pool`` (chips + HBM admission, the JobSpec contract),
``scheduler`` (pure priority/gang/grow policy), ``supervisor``
(process lifecycle + the control loop), ``churn`` (deterministic
seeded kill/shrink/arrival schedules), ``report`` (the fleet goodput
ledger and the soak verdict artifact).  CLI::

    python -m tpu_hc_bench.fleet run --demo --chips 8 --out /tmp/fleet
    python -m tpu_hc_bench.fleet status /tmp/fleet
    python -m tpu_hc_bench.fleet report /tmp/fleet --control /tmp/ctl
"""

from tpu_hc_bench.fleet.churn import ChurnEvent, parse_churn, seeded_churn
from tpu_hc_bench.fleet.pool import DevicePool, HbmVerdict, JobSpec
from tpu_hc_bench.fleet.report import fleet_ledger, write_verdict
from tpu_hc_bench.fleet.scheduler import Decision, plan
from tpu_hc_bench.fleet.supervisor import (
    FleetController,
    LocalBackend,
    Supervisor,
)

__all__ = [
    "ChurnEvent", "parse_churn", "seeded_churn",
    "DevicePool", "HbmVerdict", "JobSpec",
    "fleet_ledger", "write_verdict",
    "Decision", "plan",
    "FleetController", "LocalBackend", "Supervisor",
]
