"""``python -m tpu_hc_bench.fleet run|status|report`` — the fleet CLI.

``run`` drives a real fleet on this host (jobs are launcher
subprocesses on virtual CPU devices, or real chips where they exist),
``status`` renders a snapshot of a live or finished fleet dir, and
``report`` folds the journal into the fleet goodput ledger — with
``--control`` + ``--artifact`` it writes the soak verdict record the
regression gate consumes.  Also reachable as
``python -m tpu_hc_bench fleet ...`` (launcher subcommand).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from tpu_hc_bench.fleet import churn as churn_mod
from tpu_hc_bench.fleet import report as report_mod
from tpu_hc_bench.fleet.pool import DevicePool, JobSpec
from tpu_hc_bench.fleet.supervisor import FleetController, LocalBackend

# the built-in --demo fleet: three zoo members that fit the CPU
# container, one of them a delayed higher-priority arrival — the
# smallest spec that exercises admit, priority, shrink, and regrow
DEMO_JOBS = [
    {"name": "trivial-a", "model": "trivial", "batch_size": 2,
     "world_pref": 4, "world_min": 2, "priority": 0, "batches": 60,
     "flags": ["--num_classes=10", "--init_learning_rate=0.05"]},
    {"name": "lenet-b", "model": "lenet", "batch_size": 2,
     "world_pref": 4, "world_min": 2, "priority": 0, "batches": 60,
     "flags": ["--num_classes=10", "--init_learning_rate=0.05"]},
    {"name": "trivial-hi", "model": "trivial", "batch_size": 2,
     "world_pref": 4, "world_min": 2, "priority": 1, "arrival_s": 12.0,
     "batches": 40,
     "flags": ["--num_classes=10", "--init_learning_rate=0.05"]},
]


def load_specs(path: str | None, demo: bool) -> list[JobSpec]:
    if demo or not path:
        rows = DEMO_JOBS
    else:
        with open(path) as f:
            data = json.load(f)
        rows = data["jobs"] if isinstance(data, dict) else data
    return [JobSpec.from_dict(r) for r in rows]


def _cmd_run(args, out) -> int:
    specs = load_specs(args.spec, args.demo)
    events = []
    if args.churn:
        events = churn_mod.parse_churn(args.churn)
    elif args.churn_seed is not None:
        events = churn_mod.seeded_churn(
            args.churn_seed, [s.name for s in specs],
            horizon_s=args.churn_horizon, kills=args.churn_kills,
            shrinks=args.churn_shrinks)
        print(f"seeded churn ({args.churn_seed}): "
              f"{churn_mod.format_churn(events)}", file=out)
    pool = DevicePool(args.chips)
    ctl = FleetController(
        pool, specs, args.out,
        backend=LocalBackend(
            cache_dir=os.path.join(args.out, "compile_cache")),
        churn=events,
        tick_s=args.tick_s, settle_s=args.settle_s,
        kill_grace_s=args.kill_grace_s,
        dead_after_s=args.dead_after_s,
        startup_grace_s=args.startup_grace_s,
        deadline_s=args.deadline_s,
        print_fn=lambda s: print(s, file=out),
    )
    result = ctl.run()
    for ln in report_mod.report_lines(args.out, timelines=False):
        print(ln, file=out)
    print(f"fleet: {result['status']}  jobs {result['jobs']}", file=out)
    if result["orphans"]:
        print(f"ERROR: orphaned pids after the run: "
              f"{result['orphans']}", file=out)
        return 1
    ok = (result["status"] == "done"
          and all(s in ("done", "refused")
                  for s in result["jobs"].values()))
    return 0 if ok else 1


def _cmd_status(args, out) -> int:
    from tpu_hc_bench.obs import fleet as obs_fleet

    path = os.path.join(args.dir, "fleet_state.json")
    try:
        with open(path) as f:
            state = json.load(f)
    except (OSError, ValueError) as e:
        print(f"error: no fleet state at {path}: {e}", file=out)
        return 2
    print(f"fleet {args.dir}: {state.get('chips')} chip(s), "
          f"{state.get('free')} free, t={state.get('t_s', 0):.1f}s, "
          f"{state.get('status')}", file=out)
    for name, j in sorted((state.get("jobs") or {}).items()):
        line = (f"  {name:<12} {j.get('status', '?'):<8} "
                f"world {j.get('world', 0)}  "
                f"inc {j.get('incarnations', 0)}  "
                f"prio {j.get('priority', 0)}")
        if j.get("status") in ("running", "stopping"):
            beats = obs_fleet.read_heartbeats(
                os.path.join(j.get("run_dir", ""), "m"))
            recs = [r for rs in beats.values() for r in rs]
            live = obs_fleet.classify_liveness(
                recs, expect_incarnation=j.get("expect_incarnation"))
            age = live["age_s"]
            line += (f"  {live['status']}"
                     + (f" (step {live['step']}, beat {age:.0f}s ago)"
                        if age is not None else " (no heartbeat yet)"))
        elif j.get("exit_class"):
            line += f"  [{j['exit_class']}]"
        print(line, file=out)
    return 0


def _cmd_report(args, out) -> int:
    ledger = report_mod.fleet_ledger(args.dir)
    if ledger is None:
        print(f"error: no fleet journal under {args.dir}", file=out)
        return 2
    for ln in report_mod.report_lines(args.dir, ledger,
                                      timelines=not args.no_timelines):
        print(ln, file=out)
    rc = 0
    if args.control:
        control = report_mod.fleet_ledger(args.control)
        if control is None:
            print(f"error: no fleet journal under {args.control}",
                  file=out)
            return 2
        frac = (ledger["fleet_goodput"] / control["fleet_goodput"]
                if control["fleet_goodput"] > 0 else 0.0)
        ok = ledger["fleet_goodput"] >= args.bound * \
            control["fleet_goodput"]
        print(f"churn vs control: {ledger['fleet_goodput']:.1%} vs "
              f"{control['fleet_goodput']:.1%} ({frac:.0%} of control; "
              f"bound {args.bound:.0%}) -> "
              f"{'ok' if ok else 'REGRESSION'}", file=out)
        rc = 0 if ok else 1
    if args.artifact:
        rec = report_mod.write_verdict(
            args.dir, args.artifact, control_dir=args.control,
            bound_frac=args.bound)
        print(f"verdict: {args.artifact} "
              f"(fleet_goodput {rec['value']:.4f})", file=out)
    return rc


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_hc_bench.fleet",
        description="multi-job fleet orchestrator over one device pool")
    sub = ap.add_subparsers(dest="cmd", required=True)

    r = sub.add_parser("run", help="run a fleet of jobs on this host")
    r.add_argument("--spec", help="job-spec JSON (list of job dicts, "
                   "or {'jobs': [...]}; see README)")
    r.add_argument("--demo", action="store_true",
                   help="use the built-in 3-member demo fleet")
    r.add_argument("--out", required=True, help="fleet output dir")
    r.add_argument("--chips", type=int, default=8)
    r.add_argument("--churn", help="explicit schedule: "
                   "'kill@8:jobA,shrink@14:jobB,arrive@6:jobC'")
    r.add_argument("--churn-seed", type=int, default=None,
                   help="seeded deterministic churn (replayable)")
    r.add_argument("--churn-kills", type=int, default=1)
    r.add_argument("--churn-shrinks", type=int, default=1)
    r.add_argument("--churn-horizon", type=float, default=60.0)
    r.add_argument("--tick_s", type=float, default=0.5)
    r.add_argument("--settle_s", type=float, default=5.0)
    r.add_argument("--kill_grace_s", type=float, default=30.0)
    r.add_argument("--dead_after_s", type=float, default=60.0)
    r.add_argument("--startup_grace_s", type=float, default=45.0,
                   help="liveness holds off this long after a launch "
                   "(plus dead_after_s before the first beat — compile "
                   "time is not a hang)")
    r.add_argument("--deadline_s", type=float, default=1800.0)

    s = sub.add_parser("status", help="snapshot of a fleet dir "
                       "(liveness from heartbeats)")
    s.add_argument("dir")

    p = sub.add_parser("report", help="fleet goodput ledger "
                       "(+ verdict artifact with --control/--artifact)")
    p.add_argument("dir")
    p.add_argument("--control", help="no-churn control fleet dir")
    p.add_argument("--bound", type=float, default=0.5,
                   help="churn goodput must be >= bound x control")
    p.add_argument("--artifact", help="write the BENCH-shaped verdict "
                   "JSON here")
    p.add_argument("--no-timelines", action="store_true")
    return ap


def main(argv: list[str] | None = None, out=None) -> int:
    out = out or sys.stdout
    args = build_parser().parse_args(argv)
    t0 = time.time()
    try:
        if args.cmd == "run":
            rc = _cmd_run(args, out)
        elif args.cmd == "status":
            rc = _cmd_status(args, out)
        else:
            rc = _cmd_report(args, out)
    except (ValueError, OSError) as e:
        print(f"error: {e}", file=out)
        return 2
    if args.cmd == "run":
        print(f"({time.time() - t0:.1f}s)", file=out)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
