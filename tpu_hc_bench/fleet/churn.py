"""Deterministic spot-churn injection for fleet soaks.

A soak that cannot be replayed cannot be debugged: the whole point of
killing and resizing jobs continuously is to catch a scheduler bug, and
the repro must be one command away.  So churn here is a *schedule*, not
a coin flip per tick — either spelled explicitly::

    kill@8:jobA, shrink@14:jobB, arrive@6:jobC

(``<op>@<t_seconds>:<job>``) or generated from a seed
(``seeded_churn``) with the same counter-keyed RNG discipline the data
pipeline uses: the schedule is a pure function of (seed, jobs,
horizon), independent of wall-clock jitter, so two soaks with the same
seed inject the same events at the same fleet-relative times.

Event semantics (applied by the fleet controller):

- ``kill``   — the spot preemption: SIGTERM to the job's process group
  (the in-job ``resilience.preempt`` handler writes the emergency
  checkpoint and exits 75); the job requeues and resumes elastically
  at whatever world the pool then affords.
- ``shrink`` — capacity pressure: preempt with an explicit target of
  half the job's current world (floored at ``world_min``).
- ``arrive`` — delayed priority arrival: the named job only enters the
  queue at this time (overrides its spec ``arrival_s``).
"""

from __future__ import annotations

import dataclasses
import random

__all__ = ["ChurnEvent", "parse_churn", "format_churn", "seeded_churn",
           "OPS"]

OPS = ("kill", "shrink", "arrive")


@dataclasses.dataclass(frozen=True, order=True)
class ChurnEvent:
    t_s: float
    op: str
    job: str

    def __post_init__(self):
        if self.op not in OPS:
            raise ValueError(f"unknown churn op {self.op!r} "
                             f"(known: {', '.join(OPS)})")
        if self.t_s < 0:
            raise ValueError(f"churn time must be >= 0: {self.t_s}")


def parse_churn(spec: str) -> list[ChurnEvent]:
    """``kill@8:jobA, shrink@14:jobB`` -> sorted events.  Loud on any
    malformed entry — a silently-dropped kill event turns a failing
    soak green."""
    events: list[ChurnEvent] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            op, rest = part.split("@", 1)
            t, job = rest.split(":", 1)
            events.append(ChurnEvent(float(t), op.strip(), job.strip()))
        except ValueError as e:
            raise ValueError(
                f"malformed churn entry {part!r} (want "
                f"<op>@<t_seconds>:<job>, op in {'/'.join(OPS)}): {e}"
            ) from None
    return sorted(events)


def format_churn(events: list[ChurnEvent]) -> str:
    return ",".join(f"{e.op}@{e.t_s:g}:{e.job}" for e in sorted(events))


def seeded_churn(seed: int, jobs: list[str], horizon_s: float,
                 kills: int = 1, shrinks: int = 1,
                 min_gap_s: float = 2.0) -> list[ChurnEvent]:
    """A replayable random schedule: ``kills`` kill events and
    ``shrinks`` shrink events spread over the middle 60% of the horizon
    (the soak's steady state — events in the first/last 20% race
    startup and drain, which are churny already), round-robin over the
    job names, at least ``min_gap_s`` apart.  Same (seed, jobs,
    horizon) -> same schedule, always."""
    if not jobs:
        return []
    rng = random.Random(seed)
    lo, hi = 0.2 * horizon_s, 0.8 * horizon_s
    events: list[ChurnEvent] = []
    times: list[float] = []
    ops = ["kill"] * kills + ["shrink"] * shrinks
    for i, op in enumerate(ops):
        for _ in range(64):     # bounded rejection sampling on the gap
            t = round(rng.uniform(lo, hi), 1)
            if all(abs(t - u) >= min_gap_s for u in times):
                break
        times.append(t)
        events.append(ChurnEvent(t, op, jobs[i % len(jobs)]))
    return sorted(events)
