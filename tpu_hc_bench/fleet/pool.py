"""The device pool and the job-spec contract.

One physical pool (``chips`` on this "slice" — virtual CPU devices in
the container, real chips on hardware), many jobs.  Two admission
questions are answered here, both *before* a process is spawned:

- **Chips** — gang semantics: a job holds ``world`` chips or none.
  Reservations are plain bookkeeping (``reserve``/``release``) with a
  hard overcommit invariant; *which* world a job gets is the
  scheduler's decision, the pool only says whether it fits.

- **HBM** — a job whose per-chip microbatch cannot fit a chip's memory
  will OOM 50 warmup steps in, burning its gang's chip-seconds for
  nothing.  ``hbm_admission`` reuses the autotuner's known-OOM model
  (``tune/prune.hbm_model_for``): measured anchors from prior run
  journals win, the seeded best-known-config guess is the fallback,
  and every verdict carries its provenance (``measured|seeded``) so a
  refusal can say *why* it believed the job would not fit.  The
  launcher's ``--batch_size`` is per-worker (README), so the per-chip
  microbatch — batch / accum — is world-independent and the check runs
  once per spec, not per candidate world.

The job spec is the fleet's unit of work: a zoo member plus the gang
geometry (preferred and minimum world), a priority, an arrival time,
and the run length.  ``JobSpec.from_dict``/``to_dict`` define the
``fleet run --spec jobs.json`` file format documented in the README.
"""

from __future__ import annotations

import dataclasses

__all__ = ["JobSpec", "DevicePool", "HbmVerdict"]


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One training job in the fleet.

    ``batch_size`` is the launcher's per-worker batch (global batch
    scales with the world the scheduler grants).  ``world_pref`` is the
    gang size the job wants; ``world_min`` the smallest world it is
    worth running at — the scheduler shrinks between the two, never
    below.  Higher ``priority`` preempts lower.  ``arrival_s`` is when
    the job enters the queue (fleet-relative seconds — the churn
    schedule's priority-arrival events use it).  ``flags`` are extra
    driver flags passed through verbatim.
    """

    name: str
    model: str
    batch_size: int
    world_pref: int
    world_min: int = 1
    priority: int = 0
    arrival_s: float = 0.0
    batches: int = 60
    warmup: int = 2
    accum: int = 1
    save_every: int = 2
    flags: tuple[str, ...] = ()

    def __post_init__(self):
        if not self.name or "/" in self.name:
            raise ValueError(f"job name must be a plain token: "
                             f"{self.name!r}")
        if self.world_min < 1 or self.world_pref < self.world_min:
            raise ValueError(
                f"{self.name}: need 1 <= world_min <= world_pref, got "
                f"min={self.world_min} pref={self.world_pref}")
        if self.batch_size < 1 or self.accum < 1:
            raise ValueError(f"{self.name}: batch/accum must be >= 1")

    @property
    def microbatch(self) -> int:
        """The per-chip activation-memory unit (batch / accum) — the
        quantity the HBM admission check anchors on."""
        return max(1, self.batch_size // self.accum)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["flags"] = list(self.flags)
        return d

    @staticmethod
    def from_dict(d: dict) -> "JobSpec":
        known = {f.name for f in dataclasses.fields(JobSpec)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"job spec {d.get('name', '?')!r}: unknown field(s) "
                f"{sorted(unknown)} (known: {sorted(known)})")
        d = dict(d)
        d["flags"] = tuple(d.get("flags") or ())
        return JobSpec(**d)


@dataclasses.dataclass(frozen=True)
class HbmVerdict:
    fits: bool
    reason: str | None      # refusal reason (None when it fits)
    source: str             # measured | seeded | unknown


class DevicePool:
    """Chip reservations for one shared pool, gang-or-nothing.

    ``measured_rows`` (tune-journal measurement rows joined with their
    overrides — ``tune.prune.measured_rows_from_journal``) feed the HBM
    model its measured anchors; without them the seeded best-known
    configs are the fallback, and members outside the seed table admit
    with ``source="unknown"`` (no memory knowledge beats refusing every
    unknown member).
    """

    def __init__(self, chips: int,
                 measured_rows: list[dict] | None = None):
        if chips < 1:
            raise ValueError(f"pool needs >= 1 chip, got {chips}")
        self.chips = chips
        self.measured_rows = list(measured_rows or [])
        self.held: dict[str, int] = {}
        self._hbm_cache: dict[tuple, HbmVerdict] = {}

    @property
    def free(self) -> int:
        return self.chips - sum(self.held.values())

    def can_reserve(self, world: int) -> bool:
        return 1 <= world <= self.free

    def reserve(self, name: str, world: int) -> None:
        if name in self.held:
            raise ValueError(f"{name} already holds "
                             f"{self.held[name]} chip(s)")
        if not self.can_reserve(world):
            raise ValueError(
                f"cannot reserve {world} chip(s) for {name}: "
                f"{self.free} of {self.chips} free")
        self.held[name] = world

    def release(self, name: str) -> int:
        return self.held.pop(name, 0)

    def hbm_admission(self, spec: JobSpec) -> HbmVerdict:
        """Would one chip hold this job's microbatch?  Measured-anchors-
        first through ``tune.prune.hbm_model_for`` — the ONE provenance
        rule — with the verdict cached per (model, batch, accum).

        The pool holds one row list for the whole fleet, so rows are
        filtered to THIS spec's model here (each ``tune/runner`` record
        carries its ``model``); a row without the field is dropped —
        a lenet memory profile must never anchor a bert admission.
        """
        key = (spec.model, spec.batch_size, spec.accum)
        hit = self._hbm_cache.get(key)
        if hit is not None:
            return hit
        from tpu_hc_bench.tune.prune import hbm_model_for
        from tpu_hc_bench.tune.space import Candidate

        rows = [r for r in self.measured_rows
                if r.get("model") == spec.model]
        model = hbm_model_for(spec.model, rows or None)
        if model is None:
            verdict = HbmVerdict(True, None, "unknown")
        else:
            overrides = {"batch_size": spec.batch_size}
            if spec.accum > 1:
                overrides["gradient_accumulation_steps"] = spec.accum
            reason = model.check(
                Candidate.make(spec.model, overrides))
            verdict = HbmVerdict(reason is None, reason, model.source)
        self._hbm_cache[key] = verdict
        return verdict
