"""Fleet-wide goodput ledger + the soak verdict artifact.

The accounting question a fleet scheduler must answer about itself:
of all the chip-seconds the pool owned, how many went to *productive
training steps of admitted jobs*?  The journal (``fleet_events.jsonl``)
carries everything needed: every incarnation's ``exit`` event records
its wall seconds, the world it held, and its goodput fraction (from the
job's own metrics stream — the summary record for completed runs, the
partial ledger fold for preempted ones).  So

    fleet_goodput = Σ_incarnations goodput × world × wall_s
                    ─────────────────────────────────────────
                    pool_chips × fleet_wall_s

— per-job goodput-weighted chip-seconds over pool chip-seconds.  The
denominator charges the fleet for idle chips, scheduling gaps, startup
compiles, and every relaunch's restart tax, which is exactly what a
churn-vs-control comparison must not hide.

``render`` also merges each job's flight-recorder timeline
(``spans.<k>.jsonl`` via ``obs.timeline``) into per-job span-fold lines
and — with ``trace=True`` — a per-job Chrome trace, so "what was job X
doing while job Y was admitted" is one artifact away.

``write_verdict`` emits the BENCH-record-shaped JSON the regression
gate consumes (``obs regress``: ``fleet_goodput`` regresses DOWN), with
the churn number as the headline value and the no-churn control riding
``extra`` — the committed soak artifact's format
(``artifacts/bench_fleet_soak_r19.json``).
"""

from __future__ import annotations

import json
import os

__all__ = ["read_events", "fleet_ledger", "report_lines",
           "write_verdict"]


def read_events(out_dir: str) -> list[dict]:
    """The fleet journal, corrupt lines skipped (a journal interrupted
    by the very death it records must still render)."""
    from tpu_hc_bench.obs.metrics import read_jsonl

    return read_jsonl(os.path.join(out_dir, "fleet_events.jsonl"))


def fleet_ledger(out_dir: str) -> dict | None:
    """Fold the journal into the fleet account.  None without a
    ``fleet_start`` (not a fleet dir)."""
    events = read_events(out_dir)
    start = next((e for e in events if e["kind"] == "fleet_start"), None)
    if start is None:
        return None
    end = next((e for e in reversed(events)
                if e["kind"] == "fleet_end"), None)
    chips = int(start.get("chips", 0) or 0)
    wall_s = (float(end["wall_s"]) if end
              else max((e.get("t", 0.0) for e in events), default=0.0))
    jobs: dict[str, dict] = {}
    counts = {"kills": 0, "shrinks": 0, "grows": 0,
              "preempts": 0, "elastic_resumes": 0, "deaths": 0}
    for e in events:
        kind = e["kind"]
        name = e.get("job")
        if name is not None:
            j = jobs.setdefault(name, {
                "chip_s": 0.0, "productive_chip_s": 0.0,
                "incarnations": 0, "status": None, "worlds": [],
                "exit_classes": []})
        if kind == "launch":
            j["incarnations"] += 1
            j["worlds"].append(e.get("world"))
            if e.get("resume") == "elastic":
                counts["elastic_resumes"] += 1
        elif kind == "exit":
            w = float(e.get("world", 0) or 0)
            dur = float(e.get("wall_s", 0.0) or 0.0)
            gp = e.get("goodput")
            j["chip_s"] += w * dur
            if isinstance(gp, (int, float)):
                j["productive_chip_s"] += float(gp) * w * dur
            j["exit_classes"].append(e.get("exit_class"))
        elif kind in ("done", "failed", "refuse"):
            j["status"] = kind if kind != "refuse" else "refused"
        elif kind == "preempt_sent":
            counts["preempts"] += 1
            reason = e.get("reason", "")
            if reason == "churn-kill":
                counts["kills"] += 1
            elif reason in ("churn-shrink", "shrink"):
                counts["shrinks"] += 1
            elif reason == "grow":
                counts["grows"] += 1
        elif kind == "dead":
            counts["deaths"] += 1
    pool_chip_s = chips * wall_s
    productive = sum(j["productive_chip_s"] for j in jobs.values())
    used = sum(j["chip_s"] for j in jobs.values())
    return {
        "chips": chips,
        "wall_s": round(wall_s, 3),
        "pool_chip_s": round(pool_chip_s, 3),
        "used_chip_s": round(used, 3),
        "productive_chip_s": round(productive, 3),
        "fleet_goodput": (round(productive / pool_chip_s, 4)
                          if pool_chip_s > 0 else 0.0),
        "utilization": (round(used / pool_chip_s, 4)
                        if pool_chip_s > 0 else 0.0),
        "jobs": jobs,
        "counts": counts,
        "status": (end or {}).get("status"),
    }


def report_lines(out_dir: str, ledger: dict | None = None,
                 timelines: bool = True) -> list[str]:
    """The ``fleet report`` text: the fleet account, one line per job,
    and each job's span-timeline fold (``obs.timeline``)."""
    ledger = ledger if ledger is not None else fleet_ledger(out_dir)
    if ledger is None:
        return [f"error: no fleet journal at {out_dir}/fleet_events.jsonl"]
    c = ledger["counts"]
    lines = [
        f"fleet: {ledger['chips']} chip(s) x {ledger['wall_s']:.1f}s = "
        f"{ledger['pool_chip_s']:.0f} chip-s",
        f"  goodput {ledger['fleet_goodput']:.1%}  (utilization "
        f"{ledger['utilization']:.1%}; productive "
        f"{ledger['productive_chip_s']:.0f} chip-s)",
        f"  churn: {c['kills']} kill(s), {c['shrinks']} shrink(s), "
        f"{c['grows']} grow(s), {c['preempts']} preempt signal(s), "
        f"{c['elastic_resumes']} elastic resume(s), "
        f"{c['deaths']} liveness death(s)",
    ]
    for name, j in sorted(ledger["jobs"].items()):
        worlds = "->".join(str(w) for w in j["worlds"]) or "-"
        gp = (j["productive_chip_s"] / j["chip_s"]
              if j["chip_s"] > 0 else 0.0)
        lines.append(
            f"  {name}: {j['status'] or '?'}  worlds {worlds}  "
            f"{j['incarnations']} incarnation(s)  "
            f"{j['chip_s']:.0f} chip-s  goodput {gp:.1%}")
    if timelines:
        from tpu_hc_bench.obs import timeline as timeline_mod

        for name in sorted(ledger["jobs"]):
            mdir = os.path.join(out_dir, "jobs", name, "m")
            for ln in timeline_mod.timeline_lines(mdir):
                lines.append(f"  {name} {ln.strip()}")
    return lines


def write_verdict(out_dir: str, path: str,
                  control_dir: str | None = None,
                  bound_frac: float = 0.5,
                  device_kind: str | None = None,
                  extra: dict | None = None) -> dict:
    """The soak verdict as one BENCH-shaped record: headline value =
    fleet goodput under churn, ``extra.fleet_goodput_nochurn`` = the
    control, ``within_bound`` = churn >= bound_frac x control.  Shaped
    for ``obs regress`` (metric/unit/extra/manifest — fleet_goodput is
    a direction-aware DOWN metric there)."""
    ledger = fleet_ledger(out_dir)
    if ledger is None:
        raise ValueError(f"no fleet journal under {out_dir}")
    control = fleet_ledger(control_dir) if control_dir else None
    if device_kind is None:
        device_kind = _device_kind(out_dir) or "unknown"
    c = ledger["counts"]
    rec = {
        "metric": "fleet_goodput",
        "value": ledger["fleet_goodput"],
        "unit": "fraction",
        "extra": {
            "fleet_goodput": ledger["fleet_goodput"],
            "fleet_goodput_nochurn": (control or {}).get("fleet_goodput"),
            "bound_frac": bound_frac,
            "within_bound": (
                None if control is None else
                ledger["fleet_goodput"]
                >= bound_frac * control["fleet_goodput"]),
            "chips": ledger["chips"],
            "wall_s": ledger["wall_s"],
            "wall_s_nochurn": (control or {}).get("wall_s"),
            "jobs": sorted(ledger["jobs"]),
            "kills": c["kills"], "shrinks": c["shrinks"],
            "grows": c["grows"],
            "elastic_resumes": c["elastic_resumes"],
            **(extra or {}),
        },
        "manifest": {"device_kind": device_kind, "process_count": 1},
    }
    from tpu_hc_bench.tune.search import commit_json

    commit_json(path, rec)
    return rec


def _device_kind(out_dir: str) -> str | None:
    """The device kind from any job's metrics manifest (they all ran
    on the one pool)."""
    jobs_dir = os.path.join(out_dir, "jobs")
    try:
        names = sorted(os.listdir(jobs_dir))
    except OSError:
        return None
    for name in names:
        path = os.path.join(jobs_dir, name, "m", "manifest.json")
        try:
            with open(path) as f:
                kind = json.load(f).get("device_kind")
            if kind:
                return str(kind)
        except (OSError, ValueError):
            continue
    return None
