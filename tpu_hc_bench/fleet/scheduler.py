"""Priority + gang scheduling policy — pure decisions, no processes.

The scheduler is a function from fleet state to a decision list; the
controller (``fleet/supervisor.py``) applies decisions by launching and
signalling processes.  Keeping the policy side-effect-free is what
makes it testable in virtual time with a stub backend (the default test
lane) and replayable from a journal.

Policy, in priority order:

- **Gang**: a job runs with its whole granted world or not at all —
  there is no partial admission (a half-gang would deadlock the mesh
  collectives).  A pending job is admitted at the LARGEST world its
  ladder (``world_pref``, halving down to ``world_min``) fits in the
  free chips; a requeued job's ladder is capped by its requeue target
  (a shrink decision survives the relaunch).
- **Priority**: when a higher-priority job cannot fit, lower-priority
  running jobs make room — first by SHRINKING victims to their
  ``world_min`` (cheapest: the victim keeps running, smaller), then by
  PREEMPTING them outright (they requeue and elastically resume when
  chips free up).  Victims are chosen lowest-priority-first,
  youngest-first (the job that has run longest has the most sunk
  chip-seconds — evicting it wastes the most).
- **Grow**: when chips are free and nothing is pending, the
  highest-priority running job below its ``world_pref`` is regrown —
  one job per tick, and only after ``settle_s`` since its last
  transition, because a grow is itself a preempt+elastic-resume (a
  relaunch at the bigger world) and back-to-back regrows would thrash
  the very goodput they chase.

Shrink/grow/preempt all ride ONE mechanism — SIGTERM, emergency
checkpoint, exit 75, relaunch via ``--resume=elastic`` at the new
world — so every decision kind exercises the same resilience path the
single-job tests already pin.
"""

from __future__ import annotations

import dataclasses

from tpu_hc_bench.fleet.pool import JobSpec

__all__ = ["Decision", "RunView", "PendView", "plan",
           "ADMIT", "PREEMPT", "SHRINK", "GROW", "RESERVE"]

ADMIT = "admit"
PREEMPT = "preempt"
SHRINK = "shrink"
GROW = "grow"
RESERVE = "reserve"     # cap a pending job's next admission world


@dataclasses.dataclass(frozen=True)
class Decision:
    kind: str           # admit | preempt | shrink | grow
    job: str
    world: int = 0      # admit: granted world; shrink/grow: target
    reason: str = ""


@dataclasses.dataclass(frozen=True)
class RunView:
    """The scheduler's view of one running job."""
    spec: JobSpec
    world: int
    since_s: float      # fleet-relative time of its last transition
    stopping: bool = False   # a preempt/shrink/grow signal is in flight


@dataclasses.dataclass(frozen=True)
class PendView:
    """One queued job (arrived, not running)."""
    spec: JobSpec
    target_world: int | None = None   # requeue hint (shrink/grow carry)
    resumable: bool = False           # has a checkpoint on disk


def world_ladder(spec: JobSpec, cap: int | None = None) -> list[int]:
    """Candidate worlds, largest first: ``world_pref`` halving down to
    ``world_min`` (``cap`` bounds the top — a requeue target)."""
    top = spec.world_pref if cap is None else min(spec.world_pref, cap)
    top = max(top, spec.world_min)
    out: list[int] = []
    w = top
    while w > spec.world_min:
        out.append(w)
        w //= 2
    out.append(spec.world_min)
    # halving can undershoot min (e.g. 6 -> 3 with min 4): dedup + floor
    return sorted({max(w, spec.world_min) for w in out}, reverse=True)


def _fit(spec: JobSpec, free: int, cap: int | None) -> int | None:
    for w in world_ladder(spec, cap):
        if w <= free:
            return w
    return None


def plan(now_s: float, free: int,
         running: list[RunView], pending: list[PendView],
         settle_s: float = 5.0) -> list[Decision]:
    """One scheduling round.  Deterministic: equal inputs, equal
    decisions; ties broken by (priority, arrival order as given)."""
    decisions: list[Decision] = []
    # jobs already being stopped will free chips on a later tick; their
    # chips are NOT free yet (no admission against them) but they ARE
    # incoming — making more room for a job that is already being made
    # room for would thrash every victim in priority order
    victims_available = [r for r in running if not r.stopping]
    incoming = sum(r.world for r in running if r.stopping)
    queue = sorted(pending,
                   key=lambda p: (-p.spec.priority, p.spec.arrival_s))
    for p in queue:
        w = _fit(p.spec, free, p.target_world)
        if w is not None:
            decisions.append(Decision(ADMIT, p.spec.name, w,
                                      reason="fits"))
            free -= w
            continue
        # not fitting at world_min: can lower-priority jobs make room?
        victims = sorted(
            (r for r in victims_available
             if r.spec.priority < p.spec.priority),
            key=lambda r: (r.spec.priority, -r.since_s))
        need = max(p.spec.world_min - free - incoming, 0)
        if need == 0:
            # chips are already on their way back; wait, don't re-evict
            incoming = max(0, incoming - p.spec.world_min)
            continue
        # pass 1 — shrinks only (victims keep running, smaller)
        shrinkable = [(r, r.world - r.spec.world_min)
                      for r in victims if r.world > r.spec.world_min]
        if sum(gain for _, gain in shrinkable) >= need:
            got = 0
            for r, gain in shrinkable:
                if got >= need:
                    break
                decisions.append(Decision(
                    SHRINK, r.spec.name, r.spec.world_min,
                    reason=f"make room for {p.spec.name} "
                           f"(priority {p.spec.priority})"))
                victims_available.remove(r)
                got += gain
            # the pending job admits on a later tick, once the shrunken
            # victims have released their chips — CAPPED at the world
            # this shrink pass budgeted for it.  Uncapped, it would
            # grab its full ladder top from the freed chips and starve
            # the very victims that were promised "keep running,
            # smaller" (the shrink would degrade into a preemption).
            decisions.append(Decision(
                RESERVE, p.spec.name, p.spec.world_min,
                reason="shrink pass budgeted exactly world_min"))
            continue
        # pass 2 — preempt whole gangs, lowest priority first
        got = 0
        chosen: list[RunView] = []
        for r in victims:
            if got >= need:
                break
            chosen.append(r)
            got += r.world
        if got >= need:
            for r in chosen:
                decisions.append(Decision(
                    PREEMPT, r.spec.name,
                    reason=f"make room for {p.spec.name} "
                           f"(priority {p.spec.priority})"))
                victims_available.remove(r)
        # else: not enough even preempting everything junior — the job
        # waits (an oversized spec is refused at submission, not here)
    if not queue and free > 0:
        # regrow ONE settled job toward its preference, seniors first
        for r in sorted(victims_available,
                        key=lambda r: (-r.spec.priority,
                                       r.spec.arrival_s)):
            if r.world >= r.spec.world_pref:
                continue
            if now_s - r.since_s < settle_s:
                continue
            # the job's own chips come back to the pool during the
            # regrow relaunch, so it can claim world + free
            w = _fit(r.spec, free + r.world, None)
            if w is not None and w > r.world:
                decisions.append(Decision(
                    GROW, r.spec.name, w,
                    reason=f"{free} chip(s) free, pref "
                           f"{r.spec.world_pref}"))
                break
    return decisions
