"""Per-job process lifecycle + the fleet control loop.

Three layers, separated so the default test lane never spawns a
process:

- **Backend** (``LocalBackend`` / a test stub): launches one job
  incarnation and harvests its results.  The local backend rides the
  ONE shared runner (``tune.runner.build_cmd``/``launch_one`` — the
  same argv translation and process-group discipline the tuner and
  sweep use), points every incarnation at the job's shared
  ``train_dir`` (checkpoint lineage) and ``metrics_dir`` (heartbeat
  incarnation counters keep counting across relaunches —
  ``obs.fleet.FleetWriter`` appends), and tees stdout to a
  per-incarnation ``job-<k>.log``.

- **Supervisor**: job states and transitions.  Exits are classified by
  the launcher contract (``resilience.classify_exit``): 0 completes the
  job, 75 requeues it (the emergency checkpoint is on disk; the next
  launch resumes ``--resume=elastic`` at whatever world the scheduler
  grants), and 1/70/crash/signal mark it failed.  Liveness rides the
  heartbeat files through ``obs.fleet.classify_liveness`` — a RUNNING
  job whose newest beat (at the supervisor's expected incarnation) goes
  silent past ``dead_after_s`` is force-killed (whole process group)
  and requeued like a preemption, minus the emergency checkpoint it
  never wrote (it resumes from its last periodic save).

- **FleetController**: the tick loop.  Each tick: reap exits, apply
  due churn events, check liveness, escalate overdue stops, ask the
  scheduler (``fleet.scheduler.plan``) for decisions, apply them, and
  journal everything into ``fleet_events.jsonl`` (append-only, the
  report's source of truth) + ``fleet_state.json`` (committed
  tmp→rename, the ``fleet status`` snapshot).  The clock and sleep are
  injectable, so the default-lane tests drive the whole loop in
  virtual time against a stub backend.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import signal
import subprocess
import time
from typing import Callable, Protocol

from tpu_hc_bench.fleet import scheduler as sched_mod
from tpu_hc_bench.fleet.churn import ChurnEvent
from tpu_hc_bench.fleet.pool import DevicePool, JobSpec
from tpu_hc_bench.resilience import classify_exit

__all__ = ["JobHandle", "Backend", "LocalBackend", "JobState",
           "Supervisor", "FleetController",
           "WAITING", "PENDING", "RUNNING", "STOPPING", "DONE",
           "FAILED", "REFUSED"]

WAITING = "waiting"       # not yet arrived
PENDING = "pending"       # queued for chips
RUNNING = "running"
STOPPING = "stopping"     # preempt signal sent, waiting for exit
DONE = "done"
FAILED = "failed"
REFUSED = "refused"       # admission refused (HBM / oversized gang)


class JobHandle(Protocol):
    pid: int

    def poll(self) -> int | None: ...
    def send_preempt(self) -> None: ...
    def force_kill(self) -> None: ...


class Backend(Protocol):
    def launch(self, spec: JobSpec, world: int, resume: str,
               run_dir: str, incarnation: int) -> JobHandle: ...
    def harvest(self, spec: JobSpec, run_dir: str,
                exit_code: int) -> dict: ...


class _LocalHandle:
    """One live job incarnation: a Popen in its own process group plus
    the log file its output tees into."""

    def __init__(self, proc: subprocess.Popen, log_f):
        self.proc = proc
        self.pid = proc.pid
        self._log_f = log_f

    def poll(self) -> int | None:
        rc = self.proc.poll()
        if rc is not None and self._log_f is not None:
            try:
                self._log_f.close()
            except OSError:
                pass
            self._log_f = None
        return rc

    def send_preempt(self) -> None:
        # SIGTERM to the WHOLE group, no escalation here: the in-job
        # preempt handler needs its grace window to write the emergency
        # checkpoint; the controller escalates on its own deadline
        from tpu_hc_bench.tune import runner as runner_mod

        runner_mod.kill_process_tree(self.proc, sig=signal.SIGTERM,
                                     escalate=False)

    def force_kill(self) -> None:
        from tpu_hc_bench.tune import runner as runner_mod

        runner_mod.kill_process_tree(self.proc, sig=signal.SIGKILL)


class LocalBackend:
    """Real subprocess jobs on this host's device pool (virtual CPU
    devices in the container — each job gets ``--virtual_devices=
    <world>``, its granted gang).  ``base_env`` extends os.environ for
    every job (the soak pins ``JAX_PLATFORMS=cpu``).  ``cache_dir``
    is a fleet-shared ``--compile_cache``: a relaunch at a world any
    fleet job has compiled before pays a cache load, not a recompile —
    the PR-5 persistent cache is what keeps the restart tax of
    preempt/shrink/grow from eating the goodput the scheduler wins."""

    def __init__(self, base_env: dict | None = None,
                 cache_dir: str | None = None):
        self.base_env = dict(base_env or {})
        self.cache_dir = cache_dir

    def launch(self, spec: JobSpec, world: int, resume: str,
               run_dir: str, incarnation: int) -> _LocalHandle:
        from tpu_hc_bench.tune import runner as runner_mod

        os.makedirs(run_dir, exist_ok=True)
        flags = [
            f"--virtual_devices={world}",
            f"--train_dir={os.path.join(run_dir, 'ck')}",
            f"--metrics_dir={os.path.join(run_dir, 'm')}",
            f"--resume={resume}",
            f"--display_every={spec.save_every}",
            f"--save_model_steps={spec.save_every}",
            *spec.flags,
        ]
        if self.cache_dir:
            from tpu_hc_bench._compat import CAPABILITIES

            if CAPABILITIES["persistent_compilation_cache"]:
                flags.append(f"--compile_cache={self.cache_dir}")
        # f32 end to end: the soak's bitwise fingerprint proof needs
        # deterministic params; members that want fp16 say so in flags
        cmd = runner_mod.build_cmd(
            spec.model, spec.batch_size, flags, warmup=spec.warmup,
            batches=spec.batches, use_fp16=False)
        env = dict(os.environ)
        env.update(self.base_env)
        log_path = os.path.join(run_dir, f"job-{incarnation}.log")
        log_f = open(log_path, "w")
        proc = runner_mod.launch_one(cmd, env=env, stdout=log_f)
        return _LocalHandle(proc, log_f)

    def harvest(self, spec: JobSpec, run_dir: str,
                exit_code: int) -> dict:
        """This incarnation's goodput account from its metrics stream:
        the final ``summary`` record when the run completed, else the
        partial ledger fold (a preempted incarnation still worked).
        Never raises — a job that died before writing anything harvests
        an empty record."""
        from tpu_hc_bench.obs import goodput as goodput_mod
        from tpu_hc_bench.obs.metrics import read_jsonl

        rec: dict = {}
        records = read_jsonl(os.path.join(run_dir, "m",
                                          "metrics.jsonl"))
        if not records:
            return rec
        summary = next((r for r in reversed(records)
                        if r.get("kind") == "summary"), None)
        if summary is not None:
            gp = summary.get("goodput")
            if isinstance(gp, (int, float)) and gp == gp:
                rec["goodput"] = round(float(gp), 4)
            if summary.get("images_per_sec_per_chip") is not None:
                rec["per_chip"] = summary["images_per_sec_per_chip"]
        if "goodput" not in rec:
            ledger = goodput_mod.build_ledger(records)
            if ledger is not None:
                rec["goodput"] = round(ledger.goodput, 4)
                rec["partial"] = True
        return rec


@dataclasses.dataclass
class JobState:
    spec: JobSpec
    status: str = WAITING
    world: int = 0
    handle: JobHandle | None = None
    incarnations: int = 0           # launches so far
    run_dir: str = ""
    since_s: float = 0.0            # last transition (fleet-relative)
    stop_sent_s: float | None = None
    stop_reason: str = ""
    target_world: int | None = None     # requeue hint (shrink/grow)
    expect_incarnation: int = 0     # what THIS life's heartbeats stamp
    exit_class: str | None = None
    chip_seconds: float = 0.0           # Σ world x incarnation wall
    productive_chip_seconds: float = 0.0    # goodput-weighted
    pgids: list[int] = dataclasses.field(default_factory=list)

    @property
    def resumable(self) -> bool:
        """A committed checkpoint exists (the ``step_N.complete``
        sentinel — the same contract restore believes)."""
        return bool(glob.glob(
            os.path.join(self.run_dir, "ck", "step_*.complete")))


class Supervisor:
    """Job-state transitions over a Backend.  Pure bookkeeping plus
    signals — scheduling decisions arrive from outside."""

    def __init__(self, backend: Backend, jobs_dir: str,
                 event_fn: Callable[..., None],
                 max_relaunches: int = 8):
        self.backend = backend
        self.jobs_dir = jobs_dir
        self.jobs: dict[str, JobState] = {}
        self._event = event_fn
        self.max_relaunches = max_relaunches

    def add(self, spec: JobSpec) -> JobState:
        if spec.name in self.jobs:
            raise ValueError(f"duplicate job name {spec.name!r}")
        st = JobState(spec=spec,
                      run_dir=os.path.join(self.jobs_dir, spec.name))
        self.jobs[spec.name] = st
        return st

    def launch(self, name: str, world: int, now_s: float) -> None:
        from tpu_hc_bench.obs import fleet as obs_fleet

        st = self.jobs[name]
        resume = "elastic" if st.resumable else "auto"
        # the incarnation THIS life's heartbeats will stamp, derived
        # from the same file tail the writer reads — a launch counter
        # would drift ahead forever the first time a life dies before
        # its first beat, and liveness would cap the job at STALE
        st.expect_incarnation = obs_fleet.next_incarnation(
            obs_fleet.heartbeat_path(os.path.join(st.run_dir, "m"), 0))
        handle = self.backend.launch(st.spec, world, resume,
                                     st.run_dir, st.incarnations)
        st.handle = handle
        st.world = world
        st.status = RUNNING
        st.since_s = now_s
        st.stop_sent_s = None
        st.incarnations += 1
        st.pgids.append(handle.pid)
        self._event("launch", job=name, world=world, resume=resume,
                    incarnation=st.incarnations - 1, pid=handle.pid)

    def preempt(self, name: str, now_s: float, reason: str,
                target_world: int | None = None) -> None:
        st = self.jobs[name]
        if st.status != RUNNING or st.handle is None:
            return
        st.handle.send_preempt()
        st.status = STOPPING
        # since_s stays at LAUNCH time: reap() charges the incarnation
        # its whole running wall — resetting it here would bill a
        # 100s-old preempted job 3 stop-grace seconds of chip time and
        # silently understate every churn run's fleet goodput
        st.stop_sent_s = now_s
        st.stop_reason = reason
        st.target_world = target_world
        self._event("preempt_sent", job=name, reason=reason,
                    target_world=target_world)

    def reap(self, now_s: float) -> list[tuple[JobState, int]]:
        """Collect exited jobs; classify, harvest, and transition them.
        Returns the (state, exit_code) pairs reaped this round.

        Transitions: a clean exit completes the job; an exit-75
        preemption — or ANY death of a job we were deliberately
        stopping (the escalation SIGKILL, the liveness kill) — requeues
        it for an elastic relaunch; everything else (watchdog,
        zero-throughput, crash, stray signal) fails it.  A job that
        keeps dying stops requeueing after ``max_relaunches`` — a
        crash-looping job must not hold its queue slot forever.
        """
        out: list[tuple[JobState, int]] = []
        for st in self.jobs.values():
            if st.status not in (RUNNING, STOPPING) or st.handle is None:
                continue
            code = st.handle.poll()
            if code is None:
                continue
            out.append((st, code))
            cls = classify_exit(code)
            intentional = st.status == STOPPING
            harvest = self.backend.harvest(st.spec, st.run_dir, code)
            gp = harvest.get("goodput")
            inc_wall = max(0.0, now_s - st.since_s)
            st.chip_seconds += st.world * inc_wall
            if isinstance(gp, (int, float)):
                st.productive_chip_seconds += gp * st.world * inc_wall
            self._event("exit", job=st.spec.name, code=code,
                        exit_class=cls, world=st.world,
                        wall_s=round(inc_wall, 3), **harvest)
            st.handle = None
            st.world = 0
            st.exit_class = cls
            st.since_s = now_s
            if cls is None:
                st.status = DONE
                self._event("done", job=st.spec.name)
            elif cls == "preempted" or intentional:
                if st.incarnations >= self.max_relaunches:
                    st.status = FAILED
                    self._event("failed", job=st.spec.name,
                                exit_class="relaunch-budget")
                else:
                    st.status = PENDING
                    self._event("requeue", job=st.spec.name,
                                target_world=st.target_world,
                                resumable=st.resumable)
            else:
                st.status = FAILED
                self._event("failed", job=st.spec.name, exit_class=cls)
        return out

    def check_liveness(self, now_s: float, wall_now: float,
                       dead_after_s: float,
                       startup_grace_s: float) -> None:
        """Force-kill RUNNING jobs whose heartbeats went silent (the
        hang the watchdog inside the job should have caught — this is
        the outer belt when the whole process wedged).

        A life that has not produced its FIRST beat yet (imports, jax
        init, compile, warmup — on real hardware minutes, and the
        heartbeat only starts at the first sync window) is judged from
        its LAUNCH time with the widest window,
        ``startup_grace_s + dead_after_s``: without that, a healthy job
        still compiling would be SIGKILLed into a relaunch loop that
        repeats the same startup until the relaunch budget fails it.
        """
        from tpu_hc_bench.obs import fleet as obs_fleet

        for st in self.jobs.values():
            if st.status != RUNNING or st.handle is None:
                continue
            if now_s - st.since_s < startup_grace_s:
                continue
            # bounded tail reads — this runs every tick, and heartbeat
            # files grow O(run)
            beats = obs_fleet.latest_heartbeats(
                os.path.join(st.run_dir, "m"))
            verdict = obs_fleet.classify_liveness(
                list(beats.values()), now=wall_now,
                dead_after_s=dead_after_s,
                expect_incarnation=st.expect_incarnation)
            if verdict["status"] != obs_fleet.DEAD:
                continue
            inc = verdict["incarnation"]
            if (inc is None or inc < st.expect_incarnation) \
                    and now_s - st.since_s \
                    < startup_grace_s + dead_after_s:
                continue    # this life has not beaten yet: still in
                            # its startup window, judged from launch
            self._event("dead", job=st.spec.name,
                        age_s=verdict["age_s"],
                        incarnation=verdict["incarnation"])
            st.handle.force_kill()
            # reap() will see the SIGKILL exit; mark the intent so the
            # job requeues instead of failing on signal-9
            st.status = STOPPING
            st.stop_sent_s = now_s
            st.stop_reason = "liveness"
            st.target_world = None

    def escalate_stops(self, now_s: float, kill_grace_s: float) -> None:
        for st in self.jobs.values():
            if st.status != STOPPING or st.handle is None:
                continue
            if st.stop_sent_s is not None \
                    and now_s - st.stop_sent_s > kill_grace_s:
                self._event("force_kill", job=st.spec.name,
                            reason=st.stop_reason)
                st.handle.force_kill()
                st.stop_sent_s = now_s  # don't re-kill every tick

    def orphan_pids(self) -> list[int]:
        """PIDs still alive in ANY incarnation's process group — every
        launch was a session leader (``runner.launch_one``), so its
        pgid == its pid, and a /proc scan over those pgids finds every
        grandchild a kill might have orphaned.  The soak's zero-orphan
        invariant asserts this is empty after the run."""
        pgids = {pg for st in self.jobs.values() for pg in st.pgids}
        alive: list[int] = []
        for pid_dir in glob.glob("/proc/[0-9]*"):
            try:
                pid = int(os.path.basename(pid_dir))
            except ValueError:
                continue
            try:
                if os.getpgid(pid) in pgids:
                    alive.append(pid)
            except (ProcessLookupError, OSError):
                continue
        return alive


class FleetController:
    """The tick loop: churn -> reap -> liveness -> schedule -> apply."""

    def __init__(
        self,
        pool: DevicePool,
        specs: list[JobSpec],
        out_dir: str,
        backend: Backend | None = None,
        churn: list[ChurnEvent] | None = None,
        now_fn: Callable[[], float] = time.monotonic,
        wall_fn: Callable[[], float] = time.time,
        sleep_fn: Callable[[float], None] = time.sleep,
        tick_s: float = 0.5,
        settle_s: float = 5.0,
        kill_grace_s: float = 30.0,
        dead_after_s: float = 60.0,
        startup_grace_s: float = 45.0,
        deadline_s: float = 3600.0,
        print_fn: Callable[[str], None] = print,
    ):
        self.pool = pool
        self.out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self.backend = backend if backend is not None else LocalBackend(
            cache_dir=os.path.join(out_dir, "compile_cache"))
        self.churn = sorted(churn or [])
        self._churn_applied = [False] * len(self.churn)
        self.now_fn = now_fn
        self.wall_fn = wall_fn
        self.sleep_fn = sleep_fn
        self.tick_s = tick_s
        self.settle_s = settle_s
        self.kill_grace_s = kill_grace_s
        self.dead_after_s = dead_after_s
        self.startup_grace_s = startup_grace_s
        self.deadline_s = deadline_s
        self.print_fn = print_fn
        self._events_path = os.path.join(out_dir, "fleet_events.jsonl")
        self._events_f = open(self._events_path, "a")
        self.t0 = self.now_fn()
        self._started_unix = self.wall_fn()
        # health-signal advisory inputs (round 24): per-job byte offset
        # into <run_dir>/m/signals.jsonl so each tick tails only the
        # new events
        self._signal_offsets: dict[str, int] = {}
        self.supervisor = Supervisor(
            self.backend, os.path.join(out_dir, "jobs"), self._event)
        # arrival times: an arrive@t churn event overrides the spec
        arrive_at = {e.job: e.t_s for e in self.churn
                     if e.op == "arrive"}
        self._arrivals: dict[str, float] = {}
        for spec in specs:
            st = self.supervisor.add(spec)
            self._arrivals[spec.name] = arrive_at.get(
                spec.name, spec.arrival_s)
            # HBM admission runs ONCE, at submission: a job that cannot
            # fit a chip is refused before it ever burns a gang
            verdict = self.pool.hbm_admission(spec)
            if not verdict.fits:
                st.status = REFUSED
                st.exit_class = "hbm-refused"
                self._event("refuse", job=spec.name,
                            reason=verdict.reason,
                            hbm_source=verdict.source)
            elif spec.world_min > self.pool.chips:
                st.status = REFUSED
                st.exit_class = "oversized-gang"
                self._event("refuse", job=spec.name,
                            reason=f"world_min {spec.world_min} exceeds "
                                   f"the pool ({self.pool.chips} chips)")
        self._event("fleet_start", chips=self.pool.chips,
                    jobs=[s.name for s in specs],
                    churn=[dataclasses.asdict(e) for e in self.churn])

    # -- journaling ----------------------------------------------------

    def rel(self, now_s: float | None = None) -> float:
        return (self.now_fn() if now_s is None else now_s) - self.t0

    def _event(self, kind: str, **fields) -> None:
        rec = {"t": round(self.rel(), 3), "kind": kind, **fields}
        try:
            self._events_f.write(json.dumps(rec, default=str) + "\n")
            self._events_f.flush()
        except OSError:
            pass        # the journal is telemetry, never fatal
        if kind not in ("fleet_start",):
            self.print_fn(
                f"[{rec['t']:8.2f}s] {kind:<13} "
                + " ".join(f"{k}={v}" for k, v in fields.items()
                           if v is not None))

    def _commit_state(self) -> None:
        from tpu_hc_bench.tune.search import commit_json

        jobs = {}
        for name, st in self.supervisor.jobs.items():
            jobs[name] = {
                "status": st.status, "world": st.world,
                "incarnations": st.incarnations,
                "expect_incarnation": st.expect_incarnation,
                "priority": st.spec.priority,
                "world_pref": st.spec.world_pref,
                "world_min": st.spec.world_min,
                "model": st.spec.model,
                "run_dir": st.run_dir,
                "exit_class": st.exit_class,
                "chip_seconds": round(st.chip_seconds, 3),
                "productive_chip_seconds":
                    round(st.productive_chip_seconds, 3),
            }
        commit_json(os.path.join(self.out_dir, "fleet_state.json"), {
            "chips": self.pool.chips,
            "free": self.pool.free,
            "t_s": round(self.rel(), 3),
            "started_unix": self._started_unix,
            "status": ("done" if self.finished() else "running"),
            "jobs": jobs,
        })

    # -- the loop ------------------------------------------------------

    def finished(self) -> bool:
        return all(st.status in (DONE, FAILED, REFUSED)
                   for st in self.supervisor.jobs.values())

    def tick(self) -> None:
        now = self.now_fn()
        rel = self.rel(now)
        sup = self.supervisor
        # 1. arrivals
        for name, st in sup.jobs.items():
            if st.status == WAITING and rel >= self._arrivals[name]:
                st.status = PENDING
                st.since_s = now
                self._event("arrive", job=name,
                            priority=st.spec.priority)
        # 2. reap exits, release chips
        for st, _code in sup.reap(now):
            self.pool.release(st.spec.name)
        # 3. churn events due
        for i, ev in enumerate(self.churn):
            if self._churn_applied[i] or rel < ev.t_s:
                continue
            self._churn_applied[i] = True
            if ev.op == "arrive":
                continue        # folded into arrivals above
            st = sup.jobs.get(ev.job)
            if st is None or st.status != RUNNING:
                self._event("churn_noop", op=ev.op, job=ev.job,
                            status=getattr(st, "status", "unknown"))
                continue
            if ev.op == "kill":
                sup.preempt(ev.job, now, reason="churn-kill")
            elif ev.op == "shrink":
                target = max(st.spec.world_min, st.world // 2)
                sup.preempt(ev.job, now, reason="churn-shrink",
                            target_world=target)
        # 4. liveness + stop escalation
        sup.check_liveness(now, self.wall_fn(), self.dead_after_s,
                           self.startup_grace_s)
        sup.escalate_stops(now, self.kill_grace_s)
        # 5. schedule
        running = [
            sched_mod.RunView(spec=st.spec, world=st.world,
                              since_s=st.since_s - self.t0,
                              stopping=(st.status == STOPPING))
            for st in sup.jobs.values()
            if st.status in (RUNNING, STOPPING)
        ]
        pending = [
            sched_mod.PendView(spec=st.spec,
                               target_world=st.target_world,
                               resumable=st.resumable)
            for st in sup.jobs.values() if st.status == PENDING
        ]
        for d in sched_mod.plan(rel, self.pool.free, running, pending,
                                settle_s=self.settle_s):
            if d.kind == sched_mod.ADMIT:
                self.pool.reserve(d.job, d.world)
                st = sup.jobs[d.job]
                st.target_world = None
                self._event("admit", job=d.job, world=d.world,
                            reason=d.reason)
                sup.launch(d.job, d.world, now)
            elif d.kind == sched_mod.RESERVE:
                # the shrink pass budgeted this pending job's next
                # admission — without the cap it would take its full
                # ladder top from the victims' freed chips
                sup.jobs[d.job].target_world = d.world
            elif d.kind == sched_mod.PREEMPT:
                sup.preempt(d.job, now, reason=d.reason)
            elif d.kind == sched_mod.SHRINK:
                self._event("shrink", job=d.job, world=d.world,
                            reason=d.reason)
                sup.preempt(d.job, now, reason="shrink",
                            target_world=d.world)
            elif d.kind == sched_mod.GROW:
                self._event("grow", job=d.job, world=d.world,
                            reason=d.reason)
                sup.preempt(d.job, now, reason="grow",
                            target_world=d.world)
        # 6. health signals (round 24): tail each running job's
        # signals.jsonl into the fleet journal.  ADVISORY ONLY — the
        # journal records what the ROADMAP autoscaler would do; no
        # scheduling lever moves off a signal yet.
        self._scan_signals()
        self._commit_state()

    def _scan_signals(self) -> None:
        from tpu_hc_bench.obs import signals as signals_mod

        for name, st in self.supervisor.jobs.items():
            if st.status != RUNNING:
                continue
            path = signals_mod.signals_path(
                os.path.join(st.run_dir, "m"))
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            off = self._signal_offsets.get(name, 0)
            if size <= off:
                continue
            try:
                with open(path) as f:
                    f.seek(off)
                    chunk = f.read()
            except OSError:
                continue
            # only whole lines advance the offset — a mid-write tail
            # is re-read next tick, never half-parsed
            consumed = chunk.rfind("\n") + 1
            self._signal_offsets[name] = off + consumed
            for line in chunk[:consumed].splitlines():
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                self._event("signal", job=name,
                            signal=ev.get("signal"),
                            state=ev.get("state"),
                            t_sig=ev.get("t"),
                            measure=ev.get("measure"))
                if ev.get("state") == "fire":
                    try:
                        advice = signals_mod.advice_for(ev["signal"])
                    except (KeyError, ValueError):
                        continue
                    self._event("signal_advice", job=name,
                                signal=ev.get("signal"), advice=advice,
                                actuation="log-only")

    def _kill_all_live(self) -> None:
        for st in self.supervisor.jobs.values():
            if st.status in (RUNNING, STOPPING) \
                    and st.handle is not None:
                st.handle.force_kill()

    def run(self) -> dict:
        """Loop until every job settles (or the deadline).  Returns the
        final per-job summary (also committed as fleet_state.json).

        A crash anywhere in the loop (a failed launch, a full disk)
        must not leave live job subprocesses running unsupervised — the
        ``finally`` force-kills every live process group before the
        exception propagates, the same zero-orphan contract the clean
        path proves.
        """
        self._commit_state()
        status = "done"
        try:
            while not self.finished():
                if self.rel() > self.deadline_s:
                    status = "deadline"
                    self._event("deadline", t_limit_s=self.deadline_s)
                    break
                self.tick()
                if self.finished():
                    break
                self.sleep_fn(self.tick_s)
        except BaseException:
            status = "crash"
            self._event("fleet_crash")
            raise
        finally:
            if status != "done":
                self._kill_all_live()
            # drain: killed jobs need a beat for the SIGKILL to land
            # before the final reap settles them in the journal
            for _ in range(50):
                live = [st for st in self.supervisor.jobs.values()
                        if st.handle is not None
                        and st.status in (RUNNING, STOPPING)]
                if not live:
                    break
                for st, _code in self.supervisor.reap(self.now_fn()):
                    self.pool.release(st.spec.name)
                if any(st.handle is not None for st in live):
                    self._kill_all_live()
                    self.sleep_fn(0.1)
        wall = self.rel()
        self._event("fleet_end", wall_s=round(wall, 3), status=status)
        self._commit_state()
        try:
            self._events_f.close()
        except OSError:
            pass
        orphans = self.supervisor.orphan_pids()
        return {
            "status": status, "wall_s": round(wall, 3),
            "orphans": orphans,
            "jobs": {n: st.status
                     for n, st in self.supervisor.jobs.items()},
        }
