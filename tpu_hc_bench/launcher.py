"""Positional launcher CLI — the reference's run-script contract.

The reference's user-facing entry is
``./run-tf-sing-ucx-openmpi.sh <NUM_NODES> <WORKERS_PER_SOCKET> <batch_size>
<fabric(ib,sock)>`` (``run-tf-sing-ucx-openmpi.sh:4,27-30``; README.md:62-73).
This module preserves that 4-arg positional signature::

    python -m tpu_hc_bench NUM_HOSTS WORKERS_PER_HOST BATCH_SIZE FABRIC [--tf_flags...]

with ``FABRIC in {ib, sock, ici, dcn, host}`` (reference names accepted) and
any tf_cnn_benchmarks-style ``--flag`` after the positionals overriding the
defaults the reference hardcodes (model, warmup, batches...).  Where mpirun
fanned ranks out over the hostfile (:99-109), here every TPU-VM host runs
this same command and ``jax.distributed`` coordinates (SPMD launch model);
on a single host it just runs.

Exit-code contract (``tpu_hc_bench.resilience``; README "Fault
tolerance" table) — distinct codes so schedulers/wrappers can react
without parsing logs:

- ``0``  clean success (nonzero throughput measured)
- ``1``  run completed but measured zero throughput
- ``70`` watchdog abort — no step completed within ``--step_timeout_s``
  (thread stacks were dumped to stderr; the process self-terminates
  with this code from the watchdog thread)
- ``75`` preempted — SIGTERM/SIGINT honored, emergency checkpoint
  written when ``--train_dir`` is set; relaunch with ``--resume=auto``
  to continue
"""

from __future__ import annotations

import sys
from pathlib import Path

from tpu_hc_bench import envfile, flags, resilience
from tpu_hc_bench.parallel import distributed, fabric as fabric_mod
from tpu_hc_bench.topology import discover_layout
from tpu_hc_bench.train import driver


def parse_positionals(argv: list[str]):
    """Split `[NUM_HOSTS WORKERS BATCH FABRIC] [--flags...]` like the
    reference's `$1 $2 $3 $4` parse (:27-30)."""
    pos = []
    rest = list(argv)
    while rest and not rest[0].startswith("-") and len(pos) < 4:
        pos.append(rest.pop(0))
    if len(pos) not in (0, 4):
        raise SystemExit(
            "usage: python -m tpu_hc_bench [NUM_HOSTS WORKERS_PER_HOST "
            "BATCH_SIZE FABRIC(ib|sock|ici|dcn|host)] [--tf_cnn_flags...]\n"
            "       python -m tpu_hc_bench serve [--serve_flags...]  "
            "(request-driven serving benchmark)\n"
            "       python -m tpu_hc_bench fleet run|status|report ...  "
            "(multi-job fleet orchestrator)"
        )
    return pos, rest


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "fleet":
        # the fleet orchestrator (round 19): many jobs, one device pool
        # — `python -m tpu_hc_bench fleet run|status|report ...`
        # (tpu_hc_bench.fleet); each job is itself a launcher
        # subprocess under the positional contract below
        from tpu_hc_bench.fleet import __main__ as fleet_cli

        return fleet_cli.main(argv[1:])
    if argv and argv[0] == "serve":
        # the serving lane (round 16): `python -m tpu_hc_bench serve
        # [--tf_flags...]` — request-driven benchmark with continuous
        # batching over AOT bucket shapes (tpu_hc_bench.serve).  The
        # subcommand replaces the positional NUM_HOSTS/WORKERS/BATCH/
        # FABRIC contract: serving sizes its own work (--serve_buckets/
        # --max_in_flight) and runs single-process for now.
        from tpu_hc_bench.serve import cli as serve_cli

        return serve_cli.main(argv[1:])
    pos, rest = parse_positionals(argv)
    if pos:
        num_hosts, workers_per_host = int(pos[0]), int(pos[1])
        rest = ["--batch_size", pos[2]] + rest
        fabric_name = pos[3]
    else:
        num_hosts, workers_per_host, fabric_name = None, 0, "ici"
    cfg = flags.parse_flags(rest)

    import os

    if os.environ.get("JAX_PLATFORMS"):
        # On boxes with a tunneled-device plugin the JAX_PLATFORMS env var
        # can lose to the plugin's registration priority; re-assert it
        # through the config (which always wins) so the documented
        # `JAX_PLATFORMS=cpu python -m tpu_hc_bench ...` contract holds.
        # Must land before the first backend query (discover_layout).
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if cfg.virtual_devices:
        # must land before the first backend query (discover_layout);
        # this jaxlib ignores --xla_force_host_platform_device_count
        import jax

        jax.config.update("jax_num_cpu_devices", cfg.virtual_devices)

    if num_hosts is not None and num_hosts > 1:
        distributed.initialize()

    layout = discover_layout(
        num_hosts=num_hosts, workers_per_host=workers_per_host
    )
    fab = fabric_mod.resolve_fabric(fabric_name)

    # persist the resolved fabric config to the env registry (setenv role)
    fcfg = fabric_mod.FabricConfig(fab, cfg.fusion_threshold_bytes)
    try:
        envfile.register("launcher", fcfg.env_exports())
    except OSError:
        pass  # read-only home dirs shouldn't kill a benchmark run

    # tee-style log file per the reference's naming convention (:9-12)
    data = "synthetic" if cfg.data_dir is None else "real"
    log_path = Path.home() / "logs" / driver.log_name(
        layout.num_hosts, cfg.batch_size, data, fab.value
    )
    lines: list[str] = []

    def tee(msg: str):
        print(msg, flush=True)
        lines.append(msg)

    # full-command echo, as the reference does at :111
    tee(f"command: python -m tpu_hc_bench {' '.join(argv)}")
    rc = resilience.EXIT_OK
    try:
        result = driver.run_benchmark(
            cfg, layout=layout, fabric_name=fabric_name, print_fn=tee
        )
        if result.total_images_per_sec <= 0:
            rc = resilience.EXIT_ZERO_THROUGHPUT
        if cfg.metrics_dir:
            # the operator's next command, spelled out (goodput/MFU/
            # straggler/ceiling lines all render from the artifacts)
            tee("summarize: python -m tpu_hc_bench.obs summarize "
                + cfg.metrics_dir
                + (f" --fabric_ceiling {cfg.fabric_ceiling}"
                   if cfg.fabric_ceiling else ""))
    except resilience.PreemptedError as e:
        # graceful preemption: the emergency checkpoint is on disk (when
        # --train_dir is set) — exit EXIT_PREEMPTED so the relauncher
        # knows `--resume=auto` will continue, not restart
        tee(str(e))
        rc = resilience.EXIT_PREEMPTED
    finally:
        # the tee log is part of the contract even for preempted runs
        try:
            log_path.parent.mkdir(parents=True, exist_ok=True)
            log_path.write_text("\n".join(lines) + "\n")
            print(f"log: {log_path}")
        except OSError:
            pass
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
