"""ICI micro-benchmarks: the OSU MPI benchmark suite, TPU-native.

Replaces OSU micro-benchmarks 5.6.1 (built by the reference at
``install-scripts/install_osu_bench.sh:13-17`` and shipped in the ``-osu``
container, ``tf-hvd-gcc-ompi-ucx-mlnx-osu.def:25-26``) with latency and
bandwidth sweeps of the XLA collectives that carry the training traffic:
psum (osu_allreduce), all_gather (osu_allgather), psum_scatter
(osu_reduce_scatter), and ppermute ring (osu_latency/osu_bw point-to-point
analog).
"""

from tpu_hc_bench.microbench.osu import (  # noqa: F401
    OSU_OPS,
    SweepResult,
    run_sweep,
)
