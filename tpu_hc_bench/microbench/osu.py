"""OSU-equivalent collective latency/bandwidth sweeps over the device mesh.

Protocol follows OSU's shape: for each message size (powers of two over a
configurable range), run ``warmup`` untimed iterations then ``iters`` timed
iterations, report mean time per op and derived bandwidth.  Iterations are
chained *inside* one compiled computation (``lax.fori_loop`` with a data
dependency between steps) so Python dispatch overhead is excluded — the TPU
counterpart of OSU's tight C loop around ``MPI_Allreduce``.

Bandwidth columns:
- ``algbw``  = message_bytes / time — what the caller observes.
- ``busbw``  = algbw * 2*(n-1)/n for allreduce (ring traffic factor),
  algbw * (n-1)/n for all_gather / reduce_scatter, algbw for ppermute —
  the fabric-utilization number comparable across world sizes (same
  convention as nccl-tests / OSU derived metrics).

Usage (the reference runs OSU via ``mpirun … singularity exec`` by hand,
SURVEY.md §3.5; here it is a first-class CLI)::

    python -m tpu_hc_bench.microbench.osu --op allreduce --max_bytes 16777216
"""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_hc_bench.topology import DATA_AXIS, discover_layout, build_mesh
from tpu_hc_bench.utils.sync import drain


@dataclasses.dataclass(frozen=True)
class SweepResult:
    op: str
    world_size: int
    message_bytes: int
    mean_us: float
    algbw_gbps: float   # GB/s (1e9 bytes)
    busbw_gbps: float


def _busbw_factor(op: str, n: int) -> float:
    if n <= 1:
        return 1.0
    if op == "allreduce":
        return 2.0 * (n - 1) / n
    if op in ("all_gather", "reduce_scatter", "all_to_all"):
        return (n - 1) / n
    return 1.0  # ppermute: each link carries the full message once


def _collective(op: str, axis: str) -> Callable[[jax.Array], jax.Array]:
    if op == "allreduce":
        # divide by world size so chained iterations stay finite; pcast
        # re-marks the (now replicated) result as axis-varying so it can
        # feed the next loop iteration's carry under shard_map
        return lambda x: jax.lax.pcast(
            jax.lax.psum(x, axis) / jax.lax.axis_size(axis), axis, to="varying"
        )
    if op == "all_gather":
        # gather then take own shard back so shape is loop-invariant
        def f(x):
            g = jax.lax.all_gather(x, axis, axis=0, tiled=True)
            n = jax.lax.axis_size(axis)
            i = jax.lax.axis_index(axis)
            return jax.lax.dynamic_slice_in_dim(g, i * x.shape[0], x.shape[0], 0)
        return f
    if op == "reduce_scatter":
        def f(x):
            s = jax.lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
            return jnp.tile(s / jax.lax.axis_size(axis), jax.lax.axis_size(axis))
        return f
    if op == "ppermute":
        def f(x):
            n = jax.lax.axis_size(axis)
            perm = [(i, (i + 1) % n) for i in range(n)]
            return jax.lax.ppermute(x, axis, perm)
        return f
    if op == "all_to_all":
        # osu_alltoall analog — the building block of expert/sequence
        # parallelism layouts; shape-preserving tiled exchange
        return lambda x: jax.lax.all_to_all(
            x, axis, split_axis=0, concat_axis=0, tiled=True
        )
    raise ValueError(f"unknown op {op!r}")


OSU_OPS = ("allreduce", "all_gather", "reduce_scatter", "ppermute",
           "all_to_all")


def _build_timed_fn(mesh: Mesh, op: str, iters: int):
    """Jitted fn running `iters` chained collectives on a per-device shard."""
    coll = _collective(op, DATA_AXIS)

    def body(x):
        # each iteration consumes the previous result, so the chain of
        # collectives cannot be CSE'd or reordered by XLA
        return jax.lax.fori_loop(0, iters, lambda _, c: coll(c), x)

    shard = jax.shard_map(
        body, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS)
    )
    return jax.jit(shard)


def run_sweep(
    op: str = "allreduce",
    min_bytes: int = 1024,
    max_bytes: int = 64 * 1024 * 1024,
    warmup: int = 5,
    iters: int = 20,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
) -> list[SweepResult]:
    """Sweep one collective over message sizes; returns per-size results.

    ``message_bytes`` is the per-device payload handed to the collective
    (matching OSU, where -m sets the per-rank message size).
    """
    if mesh is None:
        mesh = build_mesh(discover_layout())
    n = mesh.devices.size
    itemsize = jnp.dtype(dtype).itemsize
    results = []
    size = min_bytes
    while size <= max_bytes:
        elems_per_dev = max(1, size // itemsize)
        fn = _build_timed_fn(mesh, op, iters)
        sharding = NamedSharding(mesh, P(DATA_AXIS))
        x = jax.device_put(
            jnp.ones((elems_per_dev * n,), dtype), sharding
        )
        # warmup (includes compile); drain, not block_until_ready — the
        # latter is advisory on tunneled platforms (utils.sync)
        w = _build_timed_fn(mesh, op, warmup)
        drain(w(x))
        drain(fn(x))  # compile the timed fn
        t0 = time.perf_counter()
        drain(fn(x))
        dt = time.perf_counter() - t0
        per_op = dt / iters
        msg_bytes = elems_per_dev * itemsize
        algbw = msg_bytes / per_op / 1e9 if per_op > 0 else float("inf")
        results.append(
            SweepResult(
                op=op,
                world_size=n,
                message_bytes=msg_bytes,
                mean_us=per_op * 1e6,
                algbw_gbps=algbw,
                busbw_gbps=algbw * _busbw_factor(op, n),
            )
        )
        size *= 2
    return results


def format_table(results: list[SweepResult]) -> str:
    """OSU-style output table."""
    if not results:
        return "(no results)"
    r0 = results[0]
    lines = [
        f"# TPU ICI micro-benchmark: {r0.op} "
        f"(world={r0.world_size}, OSU-equivalent)",
        f"# {'bytes':>12} {'latency_us':>12} {'algbw_GB/s':>12} {'busbw_GB/s':>12}",
    ]
    for r in results:
        lines.append(
            f"  {r.message_bytes:>12} {r.mean_us:>12.2f} "
            f"{r.algbw_gbps:>12.3f} {r.busbw_gbps:>12.3f}"
        )
    return "\n".join(lines)


def sweep_json(results_by_op: dict[str, list[SweepResult]]) -> dict:
    """The JSON export schema ``obs.efficiency.load_fabric_ceiling``
    consumes: one sweep-row list per op plus the fabric identity the
    ceiling is only valid for (world size, device kind)."""
    from tpu_hc_bench.utils import hw

    world = next(
        (rs[0].world_size for rs in results_by_op.values() if rs), 0)
    try:
        kind = hw.device_kind()
    except Exception:
        kind = "unknown"
    return {
        "schema": 1,
        "created_unix": time.time(),
        "world_size": world,
        "device_kind": kind,
        "sweeps": {
            op: [dataclasses.asdict(r) for r in rows]
            for op, rows in results_by_op.items()
        },
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--op", choices=list(OSU_OPS) + ["all"], default="allreduce")
    p.add_argument("--min_bytes", type=int, default=1024)
    p.add_argument("--max_bytes", type=int, default=64 * 1024 * 1024)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--json", metavar="PATH", default=None,
                   help="save the sweep as a fabric-ceiling file for "
                        "--fabric_ceiling / obs summarize")
    args = p.parse_args(argv)
    ops = OSU_OPS if args.op == "all" else [args.op]
    by_op: dict[str, list[SweepResult]] = {}
    for op in ops:
        res = run_sweep(
            op=op, min_bytes=args.min_bytes, max_bytes=args.max_bytes,
            warmup=args.warmup, iters=args.iters,
        )
        by_op[op] = res
        print(format_table(res))
    if args.json:
        import json

        with open(args.json, "w") as f:
            json.dump(sweep_json(by_op), f, indent=2)
            f.write("\n")
        print(f"# sweep saved: {args.json} (pass as --fabric_ceiling)")


if __name__ == "__main__":
    main()
