"""Model zoo + registry — the tf_cnn_benchmarks ``--model=`` dispatch.

The reference drives tf_cnn_benchmarks' model zoo through a single
``--model`` flag (pinned to resnet50 at ``run-tf-sing-ucx-openmpi.sh:34,66``;
BASELINE.json additionally names inception3, vgg16, and BERT-base MLM).
This registry reproduces that dispatch for the TPU-native zoo, including
tf_cnn_benchmarks' ``trivial`` model (flatten + one dense layer) used as a
pipeline smoke test.

``flops_per_example`` is the *forward-pass* FLOP count at the canonical
input shape, used for MFU accounting (train step ~= 3x forward).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp


class TrivialModel(nn.Module):
    """tf_cnn_benchmarks' `trivial`: flatten -> dense(num_classes)."""

    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train
        x = x.astype(self.dtype).reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    name: str
    create: Callable[..., nn.Module]   # (num_classes, dtype) -> Module
    input_shape: tuple[int, ...]       # per-example, NHWC for images;
                                       # (seq_len,) token ids for text
    flops_per_example: float           # forward FLOPs at input_shape
    is_text: bool = False
    default_image_size: int = 224
    supports_s2d: bool = False         # stem accepts space_to_depth=True
    vocab_size: int = 30522            # text models: synthetic-data label space
    causal_lm: bool = False            # text models: next-token objective
    moe: bool = False                  # factory accepts moe_impl
    attention: bool = False            # image transformer (ViT): factory
                                       # accepts attention_impl/remat
    fused_conv: bool = False           # factory accepts fused_conv (the
                                       # Pallas bottleneck segment, v1
                                       # bottleneck resnets only)
    integer_input: bool = False        # [B, ...] int32 id inputs
                                       # (NCF: SyntheticIds feeds it)
    ctc: bool = False                  # CTC objective over spectrogram
                                       # frames (deepspeech2:
                                       # SyntheticSpeech feeds it)


def _registry() -> dict[str, ModelSpec]:
    from tpu_hc_bench.models import (
        alexnet, bert, cifar_resnet, deepspeech, densenet, googlenet, gpt,
        inception, llama, mobilenet, nasnet, ncf, resnet, small_cnns, vgg,
        vit,
    )

    specs = [
        ModelSpec("trivial", TrivialModel, (224, 224, 3), 2 * 150528 * 1000),
        ModelSpec("alexnet", alexnet.alexnet, (224, 224, 3), 1.43e9),
        ModelSpec("googlenet", googlenet.googlenet, (224, 224, 3), 3.0e9),
        # forward FLOPs below are 2*MACs of the conv/FC layers at the
        # canonical shape (same convention as the resnet figures)
        ModelSpec("lenet", small_cnns.lenet, (28, 28, 3), 2.46e7,
                  default_image_size=28),
        ModelSpec("overfeat", small_cnns.overfeat, (231, 231, 3), 7.53e9,
                  default_image_size=231),
        ModelSpec("mobilenet", mobilenet.mobilenet, (224, 224, 3), 1.16e9),
        # NASNet-A: 2*MACs — mobile 564M, large 23.8B multiply-adds
        ModelSpec("nasnet", nasnet.nasnet, (224, 224, 3), 1.13e9),
        ModelSpec("nasnetlarge", nasnet.nasnetlarge, (331, 331, 3), 4.76e10,
                  default_image_size=331),
        ModelSpec("densenet40_k12", densenet.densenet40_k12, (32, 32, 3),
                  5.08e8, default_image_size=32),
        ModelSpec("densenet100_k12", densenet.densenet100_k12, (32, 32, 3),
                  1.88e9, default_image_size=32),
        # ResNet fwd GFLOPs at 224^2 (2*MACs): v1.5 figures
        ModelSpec("resnet18", resnet.resnet18, (224, 224, 3), 3.64e9,
                  supports_s2d=True),
        ModelSpec("resnet34", resnet.resnet34, (224, 224, 3), 7.34e9,
                  supports_s2d=True),
        ModelSpec("resnet50", resnet.resnet50, (224, 224, 3), 8.2e9,
                  supports_s2d=True, fused_conv=True),
        ModelSpec("resnet101", resnet.resnet101, (224, 224, 3), 15.7e9,
                  supports_s2d=True, fused_conv=True),
        ModelSpec("resnet152", resnet.resnet152, (224, 224, 3), 23.1e9,
                  supports_s2d=True, fused_conv=True),
        # v2 (full preactivation) — same conv stack, same 2*MAC figures
        ModelSpec("resnet50_v2", resnet.resnet50_v2, (224, 224, 3), 8.2e9,
                  supports_s2d=True),
        ModelSpec("resnet101_v2", resnet.resnet101_v2, (224, 224, 3), 15.7e9,
                  supports_s2d=True),
        ModelSpec("resnet152_v2", resnet.resnet152_v2, (224, 224, 3), 23.1e9,
                  supports_s2d=True),
        # CIFAR 6n+2 family (He 2015 §4.2), 32x32
        ModelSpec("resnet20_cifar", cifar_resnet.resnet20_cifar, (32, 32, 3),
                  8.2e7, default_image_size=32),
        ModelSpec("resnet32_cifar", cifar_resnet.resnet32_cifar, (32, 32, 3),
                  1.4e8, default_image_size=32),
        ModelSpec("resnet44_cifar", cifar_resnet.resnet44_cifar, (32, 32, 3),
                  1.9e8, default_image_size=32),
        ModelSpec("resnet56_cifar", cifar_resnet.resnet56_cifar, (32, 32, 3),
                  2.5e8, default_image_size=32),
        ModelSpec("resnet110_cifar", cifar_resnet.resnet110_cifar, (32, 32, 3),
                  5.1e8, default_image_size=32),
        ModelSpec("vgg11", vgg.vgg11, (224, 224, 3), 15.2e9),
        ModelSpec("vgg16", vgg.vgg16, (224, 224, 3), 30.9e9),
        ModelSpec("vgg19", vgg.vgg19, (224, 224, 3), 39.3e9),
        # ViT-B/16: 17.6G multiply-adds at 224^2 (the figure papers quote)
        # -> 35.2e9 under this registry's 2*MACs convention
        ModelSpec("vit_b16", vit.vit_b16, (224, 224, 3), 35.2e9,
                  attention=True),
        # ViT-L/16: ~61.6G multiply-adds at 224^2 -> 2*MACs
        ModelSpec("vit_l16", vit.vit_l16, (224, 224, 3), 123.2e9,
                  attention=True),
        # 2*MACs at 32^2/patch-8: 17 tokens x 4 layers + patchify + head
        ModelSpec("vit_tiny", vit.vit_tiny, (32, 32, 3), 5.3e6,
                  default_image_size=32, attention=True),
        ModelSpec("inception3", inception.inception_v3, (299, 299, 3), 11.4e9,
                  default_image_size=299),
        ModelSpec("inception4", inception.inception_v4, (299, 299, 3), 24.5e9,
                  default_image_size=299),
        # DeepSpeech2 (tf_cnn's speech member): 2 strided convs + 5x800
        # summed BiGRU + CTC; fwd FLOPs ~= 2*MACs at [300, 161] frames
        ModelSpec("deepspeech2", deepspeech.deepspeech2, (300, 161),
                  1.0e10, ctc=True),
        ModelSpec("deepspeech2_tiny", deepspeech.deepspeech2_tiny,
                  (64, 32), 2.0e7, ctc=True),
        # NCF/NeuMF (tf_cnn's recommendation member, MLPerf ml-20m
        # shape): fwd FLOPs ~= 2*MACs of the MLP tower + fused head
        # (embedding gathers are bandwidth, not MACs)
        ModelSpec("ncf", ncf.ncf, (2,), 2.8e5, integer_input=True),
        ModelSpec("ncf_tiny", ncf.ncf_tiny, (2,), 5.0e3,
                  integer_input=True),
        ModelSpec("bert_base", bert.bert_base_mlm, (128,), 2 * 110e6 * 128,
                  is_text=True),
        ModelSpec("bert_large", bert.bert_large_mlm, (128,), 2 * 335e6 * 128,
                  is_text=True),
        # ~4.5M params, seq 64: CPU-smoke/test variant of the MLM path
        ModelSpec("bert_tiny", bert.bert_tiny_mlm, (64,), 2 * 4.5e6 * 64,
                  is_text=True, vocab_size=1024),
        # decoder family (causal LM; beyond-reference — see models/gpt.py)
        ModelSpec("gpt2", gpt.gpt2, (1024,), 2 * 124e6 * 1024,
                  is_text=True, vocab_size=gpt.GPT2_VOCAB, causal_lm=True),
        ModelSpec("gpt2_medium", gpt.gpt2_medium, (1024,), 2 * 355e6 * 1024,
                  is_text=True, vocab_size=gpt.GPT2_VOCAB, causal_lm=True),
        # sparse MoE decoder: FLOPs figure counts *active* params per token
        # (top-2 of 8 experts ~= 2x FFN of the dense 124M trunk)
        ModelSpec("gpt2_moe", gpt.gpt2_moe, (1024,), 2 * 180e6 * 1024,
                  is_text=True, vocab_size=gpt.GPT2_VOCAB, causal_lm=True,
                  moe=True),
        ModelSpec("moe_tiny", gpt.moe_tiny, (64,), 2 * 3e6 * 64,
                  is_text=True, vocab_size=1024, causal_lm=True, moe=True),
        # modern decoder family: RMSNorm + RoPE + SwiGLU + GQA
        ModelSpec("llama_1b", llama.llama_1b, (2048,), 2 * 1.1e9 * 2048,
                  is_text=True, vocab_size=32000, causal_lm=True),
        # ~0.8M params: embed 131k + untied head 131k + 4 layers x ~136k
        ModelSpec("llama_tiny", llama.llama_tiny, (64,), 2 * 0.8e6 * 64,
                  is_text=True, vocab_size=1024, causal_lm=True),
    ]
    return {s.name: s for s in specs}


_ALIASES = {
    "resnet50_v1.5": "resnet50",
    "inception_v3": "inception3",
    "bert": "bert_base",
    "bert-base": "bert_base",
    "lenet5": "lenet",
    "densenet": "densenet40_k12",
    "mobilenet_v1": "mobilenet",
    "inception_v4": "inception4",
    # tf_cnn_benchmarks names the CIFAR family bare resnet<depth>
    "resnet20": "resnet20_cifar",
    "resnet32": "resnet32_cifar",
    "resnet44": "resnet44_cifar",
    "resnet56": "resnet56_cifar",
    "resnet110": "resnet110_cifar",
}


def get_model_spec(name: str) -> ModelSpec:
    reg = _registry()
    key = _ALIASES.get(name.lower(), name.lower())
    if key not in reg:
        raise ValueError(f"unknown model {name!r}; have {sorted(reg)}")
    return reg[key]


def list_models() -> list[str]:
    return sorted(_registry())


def create_model(name: str, num_classes: int = 1000, dtype=jnp.float32,
                 attention_impl: str = "dense", space_to_depth: bool = False,
                 seq_len: int | None = None,
                 gradient_checkpointing: bool = False,
                 moe_impl: str = "einsum", seq_axis: str | None = None,
                 moe_capacity_factor: float = 1.25,
                 fused_conv: bool = False, rnn_impl: str = "hoisted",
                 scan_layers: bool = False, moe_f_chunk: int = 0):
    spec = get_model_spec(name)
    kwargs: dict[str, Any] = {"num_classes": num_classes, "dtype": dtype}
    if getattr(spec, "ctc", False):
        # RNN members: hoisted (input projections batched out of the
        # scan, the round-4 default) vs flax (linen.RNN A/B control)
        kwargs["rnn_impl"] = rnn_impl
    elif rnn_impl != "hoisted":
        raise ValueError(f"--rnn_impl only applies to RNN members, not {name}")
    if spec.moe:
        kwargs["moe_impl"] = moe_impl
        kwargs["moe_capacity_factor"] = moe_capacity_factor
        kwargs["moe_f_chunk"] = moe_f_chunk
    elif moe_impl != "einsum":
        raise ValueError(f"--moe_impl only applies to MoE members, not {name}")
    elif moe_capacity_factor != 1.25:
        raise ValueError(
            f"--moe_capacity_factor only applies to MoE members, not {name}")
    if seq_axis is not None and not spec.is_text:
        raise ValueError(f"--sequence_parallel only applies to text models, "
                         f"not {name}")
    if spec.attention or spec.is_text:  # transformers: kernel + remat knobs
        kwargs["attention_impl"] = attention_impl
        kwargs["remat"] = gradient_checkpointing
    if scan_layers:
        import inspect

        if "scan_layers" not in inspect.signature(spec.create).parameters:
            raise ValueError(
                f"--scan_layers is not supported for {name} (decoder "
                "families only: gpt2*/moe*/llama*)")
        kwargs["scan_layers"] = True
    if spec.is_text:
        kwargs["seq_axis"] = seq_axis
        if seq_len is not None:
            # long-context override: rescale the linear-in-seq FLOP figure
            # (conservative — ignores the quadratic attention term); the
            # factory grows its position table only if seq_len demands it
            kwargs["max_len"] = seq_len
            spec = dataclasses.replace(
                spec, input_shape=(seq_len,),
                flops_per_example=spec.flops_per_example
                * seq_len / spec.input_shape[0],
            )
    else:
        if gradient_checkpointing and not spec.attention:
            raise ValueError(
                "--gradient_checkpointing currently applies to transformer "
                f"members only, not {name}")
        if seq_len is not None:
            raise ValueError(
                f"--seq_len only applies to text models, not {name}")
    if spec.supports_s2d:
        kwargs["space_to_depth"] = space_to_depth
    elif space_to_depth:
        raise ValueError(f"--use_space_to_depth: {name} has no s2d stem")
    if spec.fused_conv:
        kwargs["fused_conv"] = fused_conv
    elif fused_conv:
        raise ValueError(
            f"--fused_conv applies to the v1 bottleneck resnets, not {name}")
    return spec.create(**kwargs), spec
