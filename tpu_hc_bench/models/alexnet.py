"""AlexNet in Flax (tf_cnn_benchmarks model zoo member `alexnet`).

Single-tower AlexNet as tf_cnn_benchmarks drives it (Krizhevsky 2014
one-GPU variant): five convs, three max-pools, two 4096-wide FC layers.
The FCs are the bulk of the ~61M parameters and are pure MXU matmuls.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class AlexNet(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        # pad 2 so a 224 input reproduces the canonical 227-input (Caffe)
        # spatial pipeline: 55 -> 27 -> 13 -> 6, giving the 9216-wide fc6
        x = nn.Conv(64, (11, 11), strides=(4, 4), padding=((2, 2), (2, 2)),
                    dtype=self.dtype, name="conv1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(192, (5, 5), padding="SAME", dtype=self.dtype,
                    name="conv2")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(384, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv3")(x)
        x = nn.relu(x)
        x = nn.Conv(256, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv4")(x)
        x = nn.relu(x)
        x = nn.Conv(256, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv5")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc6")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc7")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc8")(x)
        return x.astype(jnp.float32)


def alexnet(num_classes=1000, dtype=jnp.float32):
    return AlexNet(num_classes=num_classes, dtype=dtype)
