"""BERT-base masked-LM in Flax (BASELINE.json config 4: "BERT-base MLM
pretraining (Horovod -> JAX GSPMD data-parallel)").

Fresh TPU-first encoder: pre-computed position/segment embeddings, 12
post-LN transformer layers (BERT-base: hidden 768, 12 heads, FFN 3072,
vocab 30522), and an MLM head with tied input embeddings.  Attention and
FFN matmuls are MXU-shaped; the whole step jits under the same DP mesh as
the CNN zoo.  ``__call__`` takes token ids and returns per-position vocab
logits; masking/weighting lives in the loss (train.step.mlm_loss).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

BERT_BASE_VOCAB = 30522
BERT_BASE_HIDDEN = 768
BERT_BASE_LAYERS = 12
BERT_BASE_HEADS = 12
BERT_BASE_FFN = 3072
BERT_MAX_LEN = 512


class TransformerLayer(nn.Module):
    hidden: int
    heads: int
    ffn: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None, train: bool = True):
        # post-LN (original BERT): sublayer -> dropout -> add -> LN
        attn = nn.MultiHeadDotProductAttention(
            num_heads=self.heads,
            qkv_features=self.hidden,
            dtype=self.dtype,
            deterministic=not train,
            dropout_rate=0.1,
        )(x, x, mask=mask)
        attn = nn.Dropout(0.1, deterministic=not train)(attn)
        x = nn.LayerNorm(dtype=self.dtype)(x + attn)
        y = nn.Dense(self.ffn, dtype=self.dtype)(x)
        y = nn.gelu(y)
        y = nn.Dense(self.hidden, dtype=self.dtype)(y)
        y = nn.Dropout(0.1, deterministic=not train)(y)
        return nn.LayerNorm(dtype=self.dtype)(x + y)


class BertMLM(nn.Module):
    vocab_size: int = BERT_BASE_VOCAB
    hidden: int = BERT_BASE_HIDDEN
    num_layers: int = BERT_BASE_LAYERS
    heads: int = BERT_BASE_HEADS
    ffn: int = BERT_BASE_FFN
    max_len: int = BERT_MAX_LEN
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, token_ids, train: bool = True):
        b, s = token_ids.shape
        embed = nn.Embed(
            self.vocab_size, self.hidden, dtype=self.dtype, name="tok_embed"
        )
        x = embed(token_ids)
        pos = nn.Embed(self.max_len, self.hidden, dtype=self.dtype,
                       name="pos_embed")(jnp.arange(s)[None, :])
        x = nn.LayerNorm(dtype=self.dtype)(x + pos)
        x = nn.Dropout(0.1, deterministic=not train)(x)
        for i in range(self.num_layers):
            x = TransformerLayer(
                self.hidden, self.heads, self.ffn, dtype=self.dtype,
                name=f"layer_{i}",
            )(x, train=train)
        # MLM head: dense+gelu+LN, then tied-embedding projection
        x = nn.Dense(self.hidden, dtype=self.dtype, name="mlm_dense")(x)
        x = nn.gelu(x)
        x = nn.LayerNorm(dtype=self.dtype, name="mlm_ln")(x)
        logits = embed.attend(x.astype(jnp.float32))
        bias = self.param("mlm_bias", nn.initializers.zeros, (self.vocab_size,))
        return logits + bias


def bert_base_mlm(num_classes: int = 0, dtype=jnp.float32):
    """Registry adapter; num_classes is ignored (vocab is the label space)."""
    del num_classes
    return BertMLM(dtype=dtype)


def bert_tiny_mlm(dtype=jnp.float32):
    """4-layer/128-hidden variant for tests and CPU smoke runs."""
    return BertMLM(
        vocab_size=1024, hidden=128, num_layers=4, heads=4, ffn=512,
        max_len=128, dtype=dtype,
    )
