"""BERT-base masked-LM in Flax (BASELINE.json config 4: "BERT-base MLM
pretraining (Horovod -> JAX GSPMD data-parallel)").

Fresh TPU-first encoder: pre-computed position/segment embeddings, 12
post-LN transformer layers (BERT-base: hidden 768, 12 heads, FFN 3072,
vocab 30522), and an MLM head with tied input embeddings.  Attention and
FFN matmuls are MXU-shaped; the whole step jits under the same DP mesh as
the CNN zoo.  ``__call__`` takes token ids and returns per-position vocab
logits; masking/weighting lives in the loss (train.step.mlm_loss).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

BERT_BASE_VOCAB = 30522
BERT_BASE_HIDDEN = 768
BERT_BASE_LAYERS = 12
BERT_BASE_HEADS = 12
BERT_BASE_FFN = 3072
BERT_MAX_LEN = 512


class MultiHeadAttention(nn.Module):
    """Self-attention whose inner product routes through the framework's
    attention dispatch (``parallel.sequence.local_attention``), so one
    param layout serves every impl: ``dense`` (XLA), ``flash`` (Pallas
    blocked-softmax kernel), and — inside a shard_map with a bound seq
    axis — ``ring``/``ulysses`` sequence parallelism.

    Unlike ``nn.MultiHeadDotProductAttention`` there is no dropout on the
    attention probabilities (a flash kernel never materializes them); the
    residual-path dropout in ``TransformerLayer`` is retained.
    """

    hidden: int
    heads: int
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    seq_axis: str | None = None
    causal: bool = False               # decoder (GPT) members set this

    @nn.compact
    def __call__(self, x):
        d = self.hidden // self.heads
        qkv = nn.DenseGeneral((3, self.heads, d), dtype=self.dtype,
                              name="qkv")(x)
        q, k, v = (qkv[:, :, a] for a in range(3))
        from tpu_hc_bench.parallel.sequence import local_attention

        out = local_attention(q, k, v, impl=self.attention_impl,
                              axis_name=self.seq_axis, causal=self.causal)
        return nn.DenseGeneral(self.hidden, axis=(-2, -1), dtype=self.dtype,
                               name="out")(out)


class TransformerLayer(nn.Module):
    hidden: int
    heads: int
    ffn: int
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    seq_axis: str | None = None

    @nn.compact
    def __call__(self, x, mask=None, train: bool = True):
        if mask is not None:
            raise NotImplementedError(
                "attention masks are not supported: the MLM protocol uses "
                "fixed-length sequences (masking lives in the loss); pass "
                "mask=None"
            )
        # post-LN (original BERT): sublayer -> dropout -> add -> LN
        # NOTE deliberate deviation from nn.MultiHeadDotProductAttention:
        # no dropout on attention probabilities for ANY impl (a flash
        # kernel never materializes them); residual dropout is kept.
        attn = MultiHeadAttention(
            self.hidden, self.heads, dtype=self.dtype,
            attention_impl=self.attention_impl, seq_axis=self.seq_axis,
        )(x)
        attn = nn.Dropout(0.1, deterministic=not train)(attn)
        x = nn.LayerNorm(dtype=self.dtype)(x + attn)
        y = nn.Dense(self.ffn, dtype=self.dtype)(x)
        y = nn.gelu(y)
        y = nn.Dense(self.hidden, dtype=self.dtype)(y)
        y = nn.Dropout(0.1, deterministic=not train)(y)
        return nn.LayerNorm(dtype=self.dtype)(x + y)


def global_position_ids(s: int, seq_axis: str | None, max_len: int):
    """Position ids for a (possibly sequence-sharded) block of length s.

    Under sequence parallelism each shard holds s/n tokens; global position
    = shard offset + local offset.  Validates the global length against the
    position table (nn.Embed silently clamps out-of-range indices).
    """
    pos_ids = jnp.arange(s)
    if seq_axis is None:
        return pos_ids
    import jax

    global_s = s * jax.lax.axis_size(seq_axis)
    if global_s > max_len:
        raise ValueError(
            f"global sequence {global_s} exceeds max_len {max_len} "
            f"(nn.Embed would silently clamp)"
        )
    return pos_ids + jax.lax.axis_index(seq_axis) * s


class BertMLM(nn.Module):
    vocab_size: int = BERT_BASE_VOCAB
    hidden: int = BERT_BASE_HIDDEN
    num_layers: int = BERT_BASE_LAYERS
    heads: int = BERT_BASE_HEADS
    ffn: int = BERT_BASE_FFN
    max_len: int = BERT_MAX_LEN
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    seq_axis: str | None = None
    remat: bool = False                # recompute each layer in backward:
                                       # store only per-layer boundaries
                                       # (O(L) boundaries, no layer
                                       # interiors) -> long-context HBM
                                       # headroom at ~1/3 extra FLOPs

    @nn.compact
    def __call__(self, token_ids, train: bool = True):
        b, s = token_ids.shape
        embed = nn.Embed(
            self.vocab_size, self.hidden, dtype=self.dtype, name="tok_embed"
        )
        x = embed(token_ids)
        pos_ids = global_position_ids(s, self.seq_axis, self.max_len)
        pos = nn.Embed(self.max_len, self.hidden, dtype=self.dtype,
                       name="pos_embed")(pos_ids[None, :])
        x = nn.LayerNorm(dtype=self.dtype)(x + pos)
        x = nn.Dropout(0.1, deterministic=not train)(x)
        # static_argnums counts bound-method args with self=0:
        # (self, x, mask, train) -> mask and train are static
        layer_cls = (nn.remat(TransformerLayer, static_argnums=(2, 3))
                     if self.remat else TransformerLayer)
        for i in range(self.num_layers):
            x = layer_cls(
                self.hidden, self.heads, self.ffn, dtype=self.dtype,
                attention_impl=self.attention_impl, seq_axis=self.seq_axis,
                name=f"layer_{i}",
            )(x, None, train)
        # MLM head: dense+gelu+LN, then tied-embedding projection.  The
        # [hidden, vocab] matmul runs with operands in the compute dtype
        # and f32 accumulation (preferred_element_type) — the MXU's native
        # mode; a true-f32 matmul here is emulated in multiple bf16 passes
        # and dominates the head cost at 30k vocab.
        x = nn.Dense(self.hidden, dtype=self.dtype, name="mlm_dense")(x)
        x = nn.gelu(x)
        x = nn.LayerNorm(dtype=self.dtype, name="mlm_ln")(x)
        logits = jnp.einsum(
            "bsh,vh->bsv", x.astype(self.dtype),
            embed.embedding.astype(self.dtype),
            preferred_element_type=jnp.float32,
        )
        bias = self.param("mlm_bias", nn.initializers.zeros, (self.vocab_size,))
        return logits + bias


def bert_base_mlm(num_classes: int = 0, dtype=jnp.float32,
                  attention_impl: str = "dense", max_len: int | None = None,
                  remat: bool = False, seq_axis: str | None = None):
    """Registry adapter; num_classes is ignored (vocab is the label space).

    ``max_len`` only ever *grows* the position table past the canonical 512
    (long-context runs); shorter sequences keep the published shape."""
    del num_classes
    return BertMLM(dtype=dtype, attention_impl=attention_impl,
                   max_len=max(BERT_MAX_LEN, max_len or 0), remat=remat,
                   seq_axis=seq_axis)


def bert_large_mlm(num_classes: int = 0, dtype=jnp.float32,
                   attention_impl: str = "dense", max_len: int | None = None,
                   remat: bool = False, seq_axis: str | None = None):
    """BERT-large (24L/1024H/16 heads/4096 FFN, ~335M params)."""
    del num_classes
    return BertMLM(
        hidden=1024, num_layers=24, heads=16, ffn=4096,
        max_len=max(BERT_MAX_LEN, max_len or 0),
        dtype=dtype, attention_impl=attention_impl, remat=remat,
        seq_axis=seq_axis,
    )


def bert_tiny_mlm(num_classes: int = 0, dtype=jnp.float32,
                  attention_impl: str = "dense", max_len: int | None = None,
                  remat: bool = False, seq_axis: str | None = None):
    """4-layer/128-hidden variant for tests and CPU smoke runs."""
    del num_classes
    return BertMLM(
        vocab_size=1024, hidden=128, num_layers=4, heads=4, ffn=512,
        max_len=max(128, max_len or 0), dtype=dtype,
        attention_impl=attention_impl, remat=remat, seq_axis=seq_axis,
    )
