"""CIFAR ResNets (He 2015 §4.2) — tf_cnn_benchmarks' resnet20..110 family.

The depth-6n+2 networks for 32x32 inputs: a 3x3/16 stem, three stages of n
basic blocks at 16/32/64 filters (stride 2 between stages), global pool,
10-way head.  Reuses the ImageNet family's ``BasicBlock`` (models/resnet.py)
— same NHWC/bf16/local-batch-BN conventions.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from tpu_hc_bench.models.resnet import BasicBlock


class CifarResNet(nn.Module):
    stage_sizes: Sequence[int]          # n blocks per stage, 3 stages
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
        )
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype,
        )
        act = nn.relu

        x = x.astype(self.dtype)
        x = act(norm(name="bn_init")(conv(16, (3, 3), name="conv_init")(x)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = BasicBlock(
                    filters=16 * 2**i, strides=strides,
                    conv=conv, norm=norm, act=act,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def _make(depth):
    n = (depth - 2) // 6

    def create(num_classes=10, dtype=jnp.float32):
        return CifarResNet([n, n, n], num_classes=num_classes, dtype=dtype)

    create.__name__ = f"resnet{depth}_cifar"
    return create


resnet20_cifar = _make(20)
resnet32_cifar = _make(32)
resnet44_cifar = _make(44)
resnet56_cifar = _make(56)
resnet110_cifar = _make(110)
