"""DeepSpeech2 — tf_cnn_benchmarks' `deepspeech2` speech member.

Closes the final gap in the tf_cnn zoo inventory (SURVEY.md §2b #22).
The architecture follows the DS2 paper / tf_cnn shape: a 2-layer strided
conv frontend over the [time, freq] spectrogram, five bidirectional GRU
layers (sum-merged directions, the DS2 row convention), and a CTC head
over the 29-character English alphabet (blank id 0).

TPU-first choices:

- **Conv frontend as NHWC**: the spectrogram runs as a [B, T, F, C]
  image so the big 41x11/21x11 kernels land on the MXU like any CNN.
- **GRUs as `lax.scan`** (``flax.linen.RNN``/``Bidirectional``): the
  recurrence compiles to a single fused scan per direction — XLA's
  preferred RNN form — with all gate matmuls batched per step.  RNNs are
  inherently latency-bound on wide accelerators; this member exists for
  coverage, and its MFU ceiling is the recurrence, not the harness.
- **CTC via ``optax.ctc_loss``** (the driver's ``ctc`` loss arm): the
  forward-backward recursion is an XLA scan over logit frames, batched.

Batch contract (data/synthetic.SyntheticSpeech): ``(features [B, T, F],
labels [B, L] int32, label_paddings [B, L] float32)``; the model's fixed
frame count after the conv strides bounds the label length (CTC needs
T' >= len(label)).
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# 26 letters + space + apostrophe + CTC blank (id 0)
DS2_VOCAB = 29
DS2_FREQ = 161                 # spectrogram bins (paper/tf_cnn input)
DS2_FRAMES = 300               # synthetic utterance length (frames)
DS2_MAX_LABEL = 50             # synthetic transcript length bound
DS2_TIME_STRIDE = 4            # conv frontend's time downsampling
                               # (conv1 stride 2 x conv2 stride 2)


def max_label_for(frames: int) -> int:
    """Largest CTC-feasible transcript length for an utterance of
    ``frames``: bounded by the post-conv frame count with a margin for
    repeated characters (each repeat needs an extra blank frame)."""
    return min(DS2_MAX_LABEL, frames // DS2_TIME_STRIDE - 4)


class DeepSpeech2(nn.Module):
    vocab_size: int = DS2_VOCAB
    rnn_hidden: int = 800
    num_rnn_layers: int = 5
    conv_channels: int = 32
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        # [B, T, F] -> [B, T, F, 1]; strided conv frontend (DS2 shapes)
        x = x.astype(self.dtype)[..., None]
        for kernel, strides, name in (
                ((41, 11), (2, 2), "conv1"), ((21, 11), (2, 1), "conv2")):
            x = nn.Conv(self.conv_channels, kernel, strides=strides,
                        padding="SAME", use_bias=False, dtype=self.dtype,
                        name=name)(x)
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             epsilon=1e-5, dtype=self.dtype,
                             name=f"{name}_bn")(x)
            x = jnp.minimum(nn.relu(x), 20.0)      # DS2 clipped relu
        b, t, f, c = x.shape
        x = x.reshape(b, t, f * c)

        for i in range(self.num_rnn_layers):
            cell = lambda n: nn.RNN(nn.GRUCell(self.rnn_hidden,
                                               dtype=self.dtype), name=n)
            y = nn.Bidirectional(
                cell(f"gru{i}_fwd"), cell(f"gru{i}_bwd"),
                merge_fn=lambda a, b: a + b,        # DS2 sum-merge
                name=f"bigru{i}")(x)
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             epsilon=1e-5, dtype=self.dtype,
                             name=f"rnn{i}_bn")(y)
        # f32 CTC head like the zoo's other heads
        return nn.Dense(self.vocab_size, dtype=jnp.float32,
                        name="ctc_head")(x)


def deepspeech2(num_classes: int = DS2_VOCAB, dtype=jnp.float32):
    """DS2 at the paper/tf_cnn shape (5x800 summed BiGRU, ~48M params)."""
    del num_classes
    return DeepSpeech2(dtype=dtype)


def deepspeech2_tiny(num_classes: int = DS2_VOCAB, dtype=jnp.float32):
    """2x32 BiGRU variant for tests/CPU smoke runs."""
    del num_classes
    return DeepSpeech2(rnn_hidden=32, num_rnn_layers=2, conv_channels=4,
                       dtype=dtype)
