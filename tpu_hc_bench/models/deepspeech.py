"""DeepSpeech2 — tf_cnn_benchmarks' `deepspeech2` speech member.

Closes the final gap in the tf_cnn zoo inventory (SURVEY.md §2b #22).
The architecture follows the DS2 paper / tf_cnn shape: a 2-layer strided
conv frontend over the [time, freq] spectrogram, five bidirectional GRU
layers (sum-merged directions, the DS2 row convention), and a CTC head
over the 29-character English alphabet (blank id 0).

TPU-first choices:

- **Conv frontend as NHWC**: the spectrogram runs as a [B, T, F, C]
  image so the big 41x11/21x11 kernels land on the MXU like any CNN.
- **GRUs as `lax.scan` with hoisted input projections** (``HoistedGRU``,
  the round-4 default): the three input-gate matmuls do not depend on
  the carry, so they run for the whole utterance as ONE [B*T, I]x[I, 3H]
  MXU matmul before the scan; the recurrence carries only the fused
  [B, H]x[H, 3H] hidden matmul + gate nonlinearities — the canonical
  RNN-on-accelerator layout.  ``rnn_impl="flax"`` keeps the plain
  ``flax.linen.RNN``/``Bidirectional`` form as the A/B control.  RNNs
  remain latency-bound on wide accelerators; the hoist moves the bound,
  it does not remove it.
- **CTC via ``optax.ctc_loss``** (the driver's ``ctc`` loss arm): the
  forward-backward recursion is an XLA scan over logit frames, batched.

Batch contract (data/synthetic.SyntheticSpeech): ``(features [B, T, F],
labels [B, L] int32, label_paddings [B, L] float32)``; the model's fixed
frame count after the conv strides bounds the label length (CTC needs
T' >= len(label)).
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

# 26 letters + space + apostrophe + CTC blank (id 0)
DS2_VOCAB = 29
DS2_FREQ = 161                 # spectrogram bins (paper/tf_cnn input)
DS2_FRAMES = 300               # synthetic utterance length (frames)
DS2_MAX_LABEL = 50             # synthetic transcript length bound
DS2_TIME_STRIDE = 4            # conv frontend's time downsampling
                               # (conv1 stride 2 x conv2 stride 2)


def max_label_for(frames: int) -> int:
    """Largest CTC-feasible transcript length for an utterance of
    ``frames``: bounded by the post-conv frame count with a margin for
    repeated characters (each repeat needs an extra blank frame)."""
    return min(DS2_MAX_LABEL, frames // DS2_TIME_STRIDE - 4)


class HoistedGRU(nn.Module):
    """GRU layer with the input projections hoisted out of the scan.

    ``flax.linen.RNN(GRUCell)`` computes all six gate matmuls inside the
    recurrence, so the three input projections (which do not depend on the
    carry) re-dispatch as [B, I]x[I, H] matmuls T times.  The canonical
    RNN-on-accelerator layout computes them for the WHOLE utterance up
    front — one [B*T, I]x[I, 3H] MXU matmul — and the scan carries only
    the hidden-to-hidden [B, H]x[H, 3H] matmul plus the gate nonlinearity.
    Same math as flax's GRUCell (sigmoid r/z gates, tanh candidate with
    reset applied to the hidden projection, ``h' = (1-z)*n + z*h``), so a
    param-copy parity test pins equivalence (tests/test_models.py).

    Gate order in the fused 3H axis: [r | z | n].
    """

    hidden: int
    reverse: bool = False       # bwd direction of a BiGRU: scan T-1..0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, t, i = x.shape
        h = self.hidden
        dense = lambda feats, name, bias: nn.Dense(
            feats, use_bias=bias, dtype=self.dtype, name=name)
        # [B, T, 3H] in one batched matmul (biases b_ir/b_iz/b_in fused)
        xg = dense(3 * h, "input_gates", True)(x)
        # hidden-to-hidden: fused [H, 3H] kernel, no bias on r/z (flax
        # GRUCell convention), bias only on the candidate's hidden part
        wh = self.param("hidden_gates",
                        nn.initializers.orthogonal(column_axis=-1),
                        (h, 3 * h), jnp.float32).astype(self.dtype)
        bn = self.param("candidate_bias", nn.initializers.zeros_init(),
                        (h,), jnp.float32).astype(self.dtype)

        def step(carry, xg_t):
            hg = carry @ wh
            xr, xz, xn = jnp.split(xg_t, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = nn.sigmoid(xr + hr)
            z = nn.sigmoid(xz + hz)
            n = nn.tanh(xn + r * (hn + bn))
            new_h = (1.0 - z) * n + z * carry
            return new_h, new_h

        h0 = jnp.zeros((b, h), self.dtype)
        _, ys = jax.lax.scan(step, h0, xg.transpose(1, 0, 2),
                             reverse=self.reverse)
        return ys.transpose(1, 0, 2)        # [B, T, H]


class BiHoistedGRU(nn.Module):
    """Both directions of a sum-merged BiGRU in ONE ``lax.scan``.

    ``HoistedGRU`` pairs run as two separate T-step scans per layer, and
    XLA executes loops sequentially — so a 5-layer BiGRU serializes
    10·T latency-bound [B, H]x[H, 3H] matmuls.  The two directions are
    data-independent: at scan index j the forward direction processes
    frame j while the backward direction processes frame T-1-j.  This
    module stacks them into one scan — carry [2, B, H], hidden matmul
    ``einsum('dbh,dhk->dbk')`` over stacked [2, H, 3H] kernels — halving
    the sequential scan count (5·T steps of a double-batch matmul).
    Same math as a (HoistedGRU fwd + HoistedGRU reverse) sum, pinned by
    a param-copy parity test (tests/test_models.py).

    Param layout intentionally mirrors the HoistedGRU pair:
    ``{fwd,bwd}_input_gates`` Dense + ``{fwd,bwd}_hidden_gates`` /
    ``{fwd,bwd}_candidate_bias``, gate order [r | z | n].
    """

    hidden: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, t, i = x.shape
        h = self.hidden
        dense = lambda name: nn.Dense(3 * h, use_bias=True,
                                      dtype=self.dtype, name=name)
        xg_f = dense("fwd_input_gates")(x)          # [B, T, 3H]
        xg_b = dense("bwd_input_gates")(x)
        wkern = lambda name: self.param(
            name, nn.initializers.orthogonal(column_axis=-1),
            (h, 3 * h), jnp.float32).astype(self.dtype)
        bkern = lambda name: self.param(
            name, nn.initializers.zeros_init(), (h,),
            jnp.float32).astype(self.dtype)
        wh = jnp.stack([wkern("fwd_hidden_gates"),
                        wkern("bwd_hidden_gates")])       # [2, H, 3H]
        bn = jnp.stack([bkern("fwd_candidate_bias"),
                        bkern("bwd_candidate_bias")])     # [2, H]
        # scan inputs [T, 2, B, 3H]: fwd in frame order, bwd reversed so
        # scan index j carries its frame T-1-j
        xs = jnp.stack([xg_f.transpose(1, 0, 2),
                        xg_b[:, ::-1].transpose(1, 0, 2)], axis=1)

        def step(carry, xg_t):                      # carry [2, B, H]
            hg = jnp.einsum("dbh,dhk->dbk", carry, wh)
            xr, xz, xn = jnp.split(xg_t, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = nn.sigmoid(xr + hr)
            z = nn.sigmoid(xz + hz)
            n = nn.tanh(xn + r * (hn + bn[:, None, :]))
            new_h = (1.0 - z) * n + z * carry
            return new_h, new_h

        h0 = jnp.zeros((2, b, h), self.dtype)
        _, ys = jax.lax.scan(step, h0, xs)          # [T, 2, B, H]
        # fwd outputs are in frame order; bwd outputs come out in scan
        # order (frame T-1-j) and reverse back; DS2 sum-merge
        return (ys[:, 0] + ys[::-1, 1]).transpose(1, 0, 2)


class DeepSpeech2(nn.Module):
    vocab_size: int = DS2_VOCAB
    rnn_hidden: int = 800
    num_rnn_layers: int = 5
    conv_channels: int = 32
    dtype: Any = jnp.float32
    rnn_impl: str = "hoisted"   # hoisted (input projections batched out
                                # of the scan, the default) | bidi (both
                                # directions in one scan — measured
                                # 0.916x, kept as a recorded-null A/B
                                # arm) | flax (linen.RNN/GRUCell, all
                                # gates inside the recurrence)

    @nn.compact
    def __call__(self, x, train: bool = True):
        # [B, T, F] -> [B, T, F, 1]; strided conv frontend (DS2 shapes)
        x = x.astype(self.dtype)[..., None]
        for kernel, strides, name in (
                ((41, 11), (2, 2), "conv1"), ((21, 11), (2, 1), "conv2")):
            x = nn.Conv(self.conv_channels, kernel, strides=strides,
                        padding="SAME", use_bias=False, dtype=self.dtype,
                        name=name)(x)
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             epsilon=1e-5, dtype=self.dtype,
                             name=f"{name}_bn")(x)
            x = jnp.minimum(nn.relu(x), 20.0)      # DS2 clipped relu
        b, t, f, c = x.shape
        x = x.reshape(b, t, f * c)

        for i in range(self.num_rnn_layers):
            if self.rnn_impl == "hoisted":
                y = (HoistedGRU(self.rnn_hidden, dtype=self.dtype,
                                name=f"gru{i}_fwd")(x)
                     + HoistedGRU(self.rnn_hidden, dtype=self.dtype,
                                  reverse=True, name=f"gru{i}_bwd")(x))
            elif self.rnn_impl == "bidi":
                y = BiHoistedGRU(self.rnn_hidden, dtype=self.dtype,
                                 name=f"bigru{i}")(x)
            elif self.rnn_impl == "flax":
                cell = lambda n: nn.RNN(nn.GRUCell(self.rnn_hidden,
                                                   dtype=self.dtype),
                                        name=n)
                y = nn.Bidirectional(
                    cell(f"gru{i}_fwd"), cell(f"gru{i}_bwd"),
                    merge_fn=lambda a, b: a + b,    # DS2 sum-merge
                    name=f"bigru{i}")(x)
            else:
                raise ValueError(f"unknown rnn_impl {self.rnn_impl!r}")
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             epsilon=1e-5, dtype=self.dtype,
                             name=f"rnn{i}_bn")(y)
        # f32 CTC head like the zoo's other heads
        return nn.Dense(self.vocab_size, dtype=jnp.float32,
                        name="ctc_head")(x)


def deepspeech2(num_classes: int = DS2_VOCAB, dtype=jnp.float32,
                rnn_impl: str = "hoisted"):
    """DS2 at the paper/tf_cnn shape (5x800 summed BiGRU, ~48M params)."""
    del num_classes
    return DeepSpeech2(dtype=dtype, rnn_impl=rnn_impl)


def deepspeech2_tiny(num_classes: int = DS2_VOCAB, dtype=jnp.float32,
                     rnn_impl: str = "hoisted"):
    """2x32 BiGRU variant for tests/CPU smoke runs."""
    del num_classes
    return DeepSpeech2(rnn_hidden=32, num_rnn_layers=2, conv_channels=4,
                       dtype=dtype, rnn_impl=rnn_impl)
