"""CIFAR DenseNet in Flax (tf_cnn_benchmarks zoo's densenet family).

tf_cnn_benchmarks ships the CIFAR-scale DenseNets (Huang 2017) —
densenet40-k12, densenet100-k12, densenet100-k24 — 32x32 inputs, three
dense blocks of BN→relu→3x3conv layers with channel concatenation, 1x1
conv + 2x2 avg-pool transitions, global-pool head.

Concatenation-heavy graphs are bandwidth-shaped on TPU; XLA fuses the
BN/relu chains into the convs, and the whole model is small enough that
per-op overhead, not FLOPs, dominates — a useful stress of the framework's
small-model path (the CNN analog of ``trivial``).
"""

from __future__ import annotations

import functools
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class DenseNetCifar(nn.Module):
    depth: int = 40
    growth: int = 12
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype,
                                 padding="SAME")
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype,
        )
        layers_per_block = (self.depth - 4) // 3

        x = x.astype(self.dtype)
        x = conv(16, (3, 3), name="conv_init")(x)
        for b in range(3):
            for l in range(layers_per_block):
                y = nn.relu(norm(name=f"b{b}_l{l}_bn")(x))
                y = conv(self.growth, (3, 3), name=f"b{b}_l{l}_conv")(y)
                x = jnp.concatenate([x, y], axis=-1)
            if b < 2:   # transition: 1x1 conv, keep channels, then pool
                x = nn.relu(norm(name=f"t{b}_bn")(x))
                x = conv(x.shape[-1], (1, 1), name=f"t{b}_conv")(x)
                x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(norm(name="bn_final")(x))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def densenet40_k12(num_classes=10, dtype=jnp.float32):
    return DenseNetCifar(depth=40, growth=12, num_classes=num_classes,
                         dtype=dtype)


def densenet100_k12(num_classes=10, dtype=jnp.float32):
    return DenseNetCifar(depth=100, growth=12, num_classes=num_classes,
                         dtype=dtype)
