"""GoogLeNet / Inception-v1 in Flax (tf_cnn_benchmarks `googlenet`).

Classic Szegedy 2014 architecture: stem, nine inception modules with
1x1/3x3/5x5 branches + pooled projection, global average pool, single
classifier (aux heads omitted — benchmark runs never consume them),
~6.6M parameters, no batch norm.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class InceptionModule(nn.Module):
    f1: int          # 1x1 branch
    f3r: int         # 3x3 reduce
    f3: int
    f5r: int         # 5x5 reduce
    f5: int
    fp: int          # pool projection
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        conv = lambda f, k, name: nn.Conv(
            f, (k, k), padding="SAME", dtype=self.dtype, name=name
        )
        b1 = nn.relu(conv(self.f1, 1, "b1")(x))
        b3 = nn.relu(conv(self.f3r, 1, "b3r")(x))
        b3 = nn.relu(conv(self.f3, 3, "b3")(b3))
        b5 = nn.relu(conv(self.f5r, 1, "b5r")(x))
        b5 = nn.relu(conv(self.f5, 5, "b5")(b5))
        bp = nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = nn.relu(conv(self.fp, 1, "bp")(bp))
        return jnp.concatenate([b1, b3, b5, bp], axis=-1)


class GoogLeNet(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        d = self.dtype
        x = x.astype(d)
        x = nn.relu(nn.Conv(64, (7, 7), strides=(2, 2), padding="SAME",
                            dtype=d, name="conv1")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = nn.relu(nn.Conv(64, (1, 1), dtype=d, name="conv2r")(x))
        x = nn.relu(nn.Conv(192, (3, 3), padding="SAME", dtype=d,
                            name="conv2")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = InceptionModule(64, 96, 128, 16, 32, 32, dtype=d)(x)    # 3a
        x = InceptionModule(128, 128, 192, 32, 96, 64, dtype=d)(x)  # 3b
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = InceptionModule(192, 96, 208, 16, 48, 64, dtype=d)(x)   # 4a
        x = InceptionModule(160, 112, 224, 24, 64, 64, dtype=d)(x)  # 4b
        x = InceptionModule(128, 128, 256, 24, 64, 64, dtype=d)(x)  # 4c
        x = InceptionModule(112, 144, 288, 32, 64, 64, dtype=d)(x)  # 4d
        x = InceptionModule(256, 160, 320, 32, 128, 128, dtype=d)(x)  # 4e
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = InceptionModule(256, 160, 320, 32, 128, 128, dtype=d)(x)  # 5a
        x = InceptionModule(384, 192, 384, 48, 128, 128, dtype=d)(x)  # 5b
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.4, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def googlenet(num_classes=1000, dtype=jnp.float32):
    return GoogLeNet(num_classes=num_classes, dtype=dtype)
