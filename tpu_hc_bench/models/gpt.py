"""GPT-2-style causal LM in Flax — the decoder-side text family.

Beyond-reference member (the reference's text config is BERT MLM only;
BASELINE.json config 4): a pre-LN decoder with learned positions and tied
output embedding, sized to GPT-2 small (12L/768H, vocab 50257, ctx 1024,
~124M params) and medium (24L/1024H, ~355M).

This is the workload that exercises the framework's *causal* long-context
machinery end-to-end: ``--attention_impl=flash`` uses the Pallas kernel's
causal dead-tile skip (tiles above the diagonal never touch the MXU), and
under a seq mesh axis the ring/Ulysses paths apply their causal masking.
Shares ``MultiHeadAttention`` with the BERT family — one attention
dispatch serves both directions.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpu_hc_bench.models.bert import MultiHeadAttention, global_position_ids

GPT2_VOCAB = 50257
GPT2_CTX = 1024
# Dropout rates shared by __call__ and the pp_embed/pp_head PP interface
# below — change them here and both paths move together.
EMBED_DROPOUT = 0.1
RESID_DROPOUT = 0.1


class DecoderLayer(nn.Module):
    """Pre-LN (GPT-2): x + attn(LN(x)), then x + mlp(LN(x)).

    ``num_experts > 0`` swaps the dense MLP for a sparse MoE FFN
    (``models.moe.MoEFFN``, Mixtral-style decoder) — the expert-parallel
    workload.
    """

    hidden: int
    heads: int
    ffn: int
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    seq_axis: str | None = None
    num_experts: int = 0
    top_k: int = 2
    moe_impl: str = "einsum"
    moe_capacity_factor: float = 1.25
    moe_f_chunk: int = 0               # ragged path: FFN-dim tile (0 =
                                       # full width; measured FASTER at
                                       # every reachable shape, round 4)
    causal: bool = True                # ViT reuses this block bidirectional

    @nn.compact
    def __call__(self, x, train: bool = True):
        h = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        h = MultiHeadAttention(
            self.hidden, self.heads, dtype=self.dtype,
            attention_impl=self.attention_impl, seq_axis=self.seq_axis,
            causal=self.causal,
        )(h)
        x = x + nn.Dropout(RESID_DROPOUT, deterministic=not train)(h)
        h = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        if self.num_experts:
            from tpu_hc_bench.models.moe import MoEFFN

            h = MoEFFN(self.hidden, self.ffn, self.num_experts,
                       top_k=self.top_k, dtype=self.dtype,
                       impl=self.moe_impl,
                       capacity_factor=self.moe_capacity_factor,
                       ragged_f_chunk=self.moe_f_chunk,
                       name="moe")(h)
        else:
            h = nn.Dense(self.ffn, dtype=self.dtype, name="fc")(h)
            h = nn.gelu(h)
            h = nn.Dense(self.hidden, dtype=self.dtype, name="proj")(h)
        return x + nn.Dropout(RESID_DROPOUT, deterministic=not train)(h)


class GPTLM(nn.Module):
    vocab_size: int = GPT2_VOCAB
    hidden: int = 768
    num_layers: int = 12
    heads: int = 12
    ffn: int = 3072
    max_len: int = GPT2_CTX
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    seq_axis: str | None = None
    remat: bool = False                # recompute layers in backward
    num_experts: int = 0               # >0: MoE FFNs (models/moe.py)
    top_k: int = 2
    moe_impl: str = "einsum"           # einsum (GSPMD/EP) | ragged (fast DP)
    moe_capacity_factor: float = 1.25  # einsum slots/expert multiplier
    moe_f_chunk: int = 0               # ragged grouped-matmul FFN tile
                                       # (0 = full width, the measured
                                       # default; see BASELINE.md MoE)
    scan_layers: bool = False          # lax.scan over stacked layers: ONE
                                       # compiled layer body regardless of
                                       # depth.  The program-size lever:
                                       # unrolled deep stacks of HLO-heavy
                                       # layers (ragged MoE's per-layer
                                       # sort) can crash/bloat compilation
                                       # (round 4: ragged bs=16 compiled
                                       # at <=6 unrolled layers, died at
                                       # >=9; scan compiles any depth).
                                       # Param tree: layers/<...> stacked
                                       # [L, ...] instead of layer_i/<...>
                                       # -- NOT interchangeable with the
                                       # unrolled checkpoints and not yet
                                       # wired to TP/EP/PP sharding rules
                                       # (driver guards those combos).

    @nn.compact
    def __call__(self, token_ids, train: bool = True):
        b, s = token_ids.shape
        embed = nn.Embed(self.vocab_size, self.hidden, dtype=self.dtype,
                         name="wte")
        pos_ids = global_position_ids(s, self.seq_axis, self.max_len)
        x = embed(token_ids) + nn.Embed(
            self.max_len, self.hidden, dtype=self.dtype, name="wpe"
        )(pos_ids[None, :])
        x = nn.Dropout(EMBED_DROPOUT, deterministic=not train)(x)
        # static_argnums counts bound-method args with self=0:
        # (self, x, train) -> train is static
        layer_cls = (nn.remat(DecoderLayer, static_argnums=(2,))
                     if self.remat else DecoderLayer)
        layer_kw = dict(
            hidden=self.hidden, heads=self.heads, ffn=self.ffn,
            dtype=self.dtype, attention_impl=self.attention_impl,
            seq_axis=self.seq_axis, num_experts=self.num_experts,
            top_k=self.top_k, moe_impl=self.moe_impl,
            moe_capacity_factor=self.moe_capacity_factor,
            moe_f_chunk=self.moe_f_chunk)
        if self.scan_layers:
            # scan-over-layers: stacked params [L, ...], one compiled
            # body; dropout rngs split per layer, sown aux losses stack
            scan = nn.scan(
                lambda module, carry, _: (module(carry, train), None),
                variable_axes={"params": 0, "losses": 0},
                split_rngs={"params": True, "dropout": True},
                length=self.num_layers)
            x, _ = scan(layer_cls(**layer_kw, name="layers"), x, None)
        else:
            for i in range(self.num_layers):
                x = layer_cls(**layer_kw, name=f"layer_{i}")(x, train)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        # tied output projection: operands in compute dtype, f32
        # accumulation (the MXU-native mode; the 50k-vocab cross-entropy
        # still sees f32 logits, but a true-f32 matmul would be emulated)
        return jnp.einsum(
            "bsh,vh->bsv", x.astype(self.dtype),
            embed.embedding.astype(self.dtype),
            preferred_element_type=jnp.float32,
        )

    # --- pipeline-parallel interface (parallel/pipeline.py) -------------
    # Three pure functions over the model's OWN param tree, so the PP step
    # is derived from the model instead of reconstructing its wiring; any
    # decoder exposing these (+ `layer_i` param naming, num_layers, remat)
    # can pipeline.  Must stay numerically identical to __call__ (pinned
    # by tests/test_pipeline.py parity tests).

    @nn.nowrap
    def pp_layer_module(self) -> nn.Module:
        """The repeated trunk layer, identical to the `layer_i` instances
        built in ``__call__`` (same param tree as one stacked slice)."""
        return DecoderLayer(
            self.hidden, self.heads, self.ffn, dtype=self.dtype,
            attention_impl=self.attention_impl,
            num_experts=self.num_experts, top_k=self.top_k,
            moe_impl=self.moe_impl,
            moe_capacity_factor=self.moe_capacity_factor,
            moe_f_chunk=self.moe_f_chunk)

    @nn.nowrap
    def pp_embed(self, params: dict, token_ids, rng):
        """Token + learned-position embedding (+ embed dropout when
        ``rng`` is given); returns ``(x, rng)`` with the embed-dropout
        fold consumed from ``rng``."""
        wte = params["wte"]["embedding"]
        wpe = params["wpe"]["embedding"]
        s = token_ids.shape[1]
        x = (wte.astype(self.dtype)[token_ids]
             + wpe.astype(self.dtype)[jnp.arange(s)][None])
        if rng is not None:
            rng, ekey = jax.random.split(rng)
            x = nn.Dropout(EMBED_DROPOUT, deterministic=False).apply(
                {}, x, rngs={"dropout": ekey})
        return x, rng

    @nn.nowrap
    def pp_head(self, params: dict, x):
        """Final LN + tied f32-accumulated output projection."""
        x = nn.LayerNorm(dtype=self.dtype).apply(
            {"params": params["ln_f"]}, x)
        return jnp.einsum(
            "bsh,vh->bsv", x.astype(self.dtype),
            params["wte"]["embedding"].astype(self.dtype),
            preferred_element_type=jnp.float32)


def gpt2(num_classes: int = 0, dtype=jnp.float32,
         attention_impl: str = "dense", max_len: int | None = None,
         remat: bool = False, seq_axis: str | None = None,
         scan_layers: bool = False):
    """GPT-2 small (124M); num_classes is ignored (vocab is the space)."""
    del num_classes
    return GPTLM(dtype=dtype, attention_impl=attention_impl,
                 max_len=max(GPT2_CTX, max_len or 0), remat=remat,
                 seq_axis=seq_axis, scan_layers=scan_layers)


def gpt2_medium(num_classes: int = 0, dtype=jnp.float32,
                attention_impl: str = "dense", max_len: int | None = None,
                remat: bool = False, seq_axis: str | None = None,
                scan_layers: bool = False):
    """GPT-2 medium (~355M: 24L/1024H/16 heads)."""
    del num_classes
    return GPTLM(hidden=1024, num_layers=24, heads=16, ffn=4096,
                 dtype=dtype, attention_impl=attention_impl,
                 max_len=max(GPT2_CTX, max_len or 0), remat=remat,
                 seq_axis=seq_axis, scan_layers=scan_layers)


def gpt2_moe(num_classes: int = 0, dtype=jnp.float32,
             attention_impl: str = "dense", max_len: int | None = None,
             remat: bool = False, moe_impl: str = "einsum",
             seq_axis: str | None = None,
             moe_capacity_factor: float = 1.25,
             scan_layers: bool = False, moe_f_chunk: int = 0):
    """GPT-2-small trunk with 8-expert top-2 MoE FFNs (~520M params,
    ~180M active per token: the 124M dense trunk swaps its 57M of FFNs
    for 2x-of-8 expert FFNs) — the expert-parallel workload."""
    del num_classes
    return GPTLM(dtype=dtype, attention_impl=attention_impl,
                 max_len=max(GPT2_CTX, max_len or 0), remat=remat,
                 num_experts=8, top_k=2, moe_impl=moe_impl,
                 moe_capacity_factor=moe_capacity_factor,
                 seq_axis=seq_axis, scan_layers=scan_layers,
                 moe_f_chunk=moe_f_chunk)


def moe_tiny(num_classes: int = 0, dtype=jnp.float32,
             attention_impl: str = "dense", max_len: int | None = None,
             remat: bool = False, moe_impl: str = "einsum",
             seq_axis: str | None = None,
             moe_capacity_factor: float = 1.25,
             scan_layers: bool = False, moe_f_chunk: int = 0):
    """4-layer/128-hidden 4-expert decoder for tests and CPU smoke runs."""
    del num_classes
    return GPTLM(vocab_size=1024, hidden=128, num_layers=4, heads=4,
                 ffn=256, dtype=dtype, attention_impl=attention_impl,
                 max_len=max(128, max_len or 0), remat=remat,
                 num_experts=4, top_k=2, moe_impl=moe_impl,
                 moe_capacity_factor=moe_capacity_factor,
                 seq_axis=seq_axis, scan_layers=scan_layers,
                 moe_f_chunk=moe_f_chunk)
