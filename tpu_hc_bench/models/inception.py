"""Inception-v3/v4 in Flax (BASELINE.json config 3; tf_cnn_benchmarks `inception3`/`inception4`).

Standard Inception-v3 (Szegedy et al. 2015) at 299x299 NHWC: stem, 3x
InceptionA (35x35), grid reduction B, 4x InceptionC (17x17), reduction D,
2x InceptionE (8x8), global pool, classifier.  The auxiliary classifier is
omitted (benchmark runs never consume the aux loss).  All convs are
Conv+BN+ReLU, BN with local (per-worker) statistics — Horovod DP semantics.
"""

from __future__ import annotations

import functools
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class ConvBN(nn.Module):
    features: int
    kernel: tuple[int, int]
    strides: tuple[int, int] = (1, 1)
    padding: str = "SAME"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(
            self.features, self.kernel, strides=self.strides,
            padding=self.padding, use_bias=False, dtype=self.dtype,
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-3,
            dtype=self.dtype,
        )(x)
        return nn.relu(x)


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b1 = c(64, (1, 1))(x, train)
        b2 = c(64, (5, 5))(c(48, (1, 1))(x, train), train)
        b3 = c(96, (3, 3))(c(96, (3, 3))(c(64, (1, 1))(x, train), train), train)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = c(self.pool_features, (1, 1))(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionB(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b1 = c(384, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        b2 = c(64, (1, 1))(x, train)
        b2 = c(96, (3, 3))(b2, train)
        b2 = c(96, (3, 3), strides=(2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        f = self.channels_7x7
        b1 = c(192, (1, 1))(x, train)
        b2 = c(f, (1, 1))(x, train)
        b2 = c(f, (1, 7))(b2, train)
        b2 = c(192, (7, 1))(b2, train)
        b3 = c(f, (1, 1))(x, train)
        b3 = c(f, (7, 1))(b3, train)
        b3 = c(f, (1, 7))(b3, train)
        b3 = c(f, (7, 1))(b3, train)
        b3 = c(192, (1, 7))(b3, train)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = c(192, (1, 1))(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionD(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b1 = c(192, (1, 1))(x, train)
        b1 = c(320, (3, 3), strides=(2, 2), padding="VALID")(b1, train)
        b2 = c(192, (1, 1))(x, train)
        b2 = c(192, (1, 7))(b2, train)
        b2 = c(192, (7, 1))(b2, train)
        b2 = c(192, (3, 3), strides=(2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionE(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b1 = c(320, (1, 1))(x, train)
        b2 = c(384, (1, 1))(x, train)
        b2 = jnp.concatenate(
            [c(384, (1, 3))(b2, train), c(384, (3, 1))(b2, train)], axis=-1
        )
        b3 = c(448, (1, 1))(x, train)
        b3 = c(384, (3, 3))(b3, train)
        b3 = jnp.concatenate(
            [c(384, (1, 3))(b3, train), c(384, (3, 1))(b3, train)], axis=-1
        )
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = c(192, (1, 1))(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        x = x.astype(self.dtype)
        # stem: 299x299x3 -> 35x35x192
        x = c(32, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        x = c(32, (3, 3), padding="VALID")(x, train)
        x = c(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = c(80, (1, 1), padding="VALID")(x, train)
        x = c(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        # 35x35
        x = InceptionA(32, dtype=self.dtype)(x, train)
        x = InceptionA(64, dtype=self.dtype)(x, train)
        x = InceptionA(64, dtype=self.dtype)(x, train)
        x = ReductionB(dtype=self.dtype)(x, train)
        # 17x17
        x = InceptionC(128, dtype=self.dtype)(x, train)
        x = InceptionC(160, dtype=self.dtype)(x, train)
        x = InceptionC(160, dtype=self.dtype)(x, train)
        x = InceptionC(192, dtype=self.dtype)(x, train)
        x = ReductionD(dtype=self.dtype)(x, train)
        # 8x8
        x = InceptionE(dtype=self.dtype)(x, train)
        x = InceptionE(dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def inception_v3(num_classes=1000, dtype=jnp.float32):
    return InceptionV3(num_classes=num_classes, dtype=dtype)


# ---------------------------------------------------------------------------
# Inception-v4 (Szegedy et al. 2016) — tf_cnn_benchmarks `inception4`.
# Same ConvBN building block; pure-Inception variant (no residuals), 299x299.
# ---------------------------------------------------------------------------


class StemV4(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        x = c(32, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        x = c(32, (3, 3), padding="VALID")(x, train)
        x = c(64, (3, 3))(x, train)
        x = jnp.concatenate([
            nn.max_pool(x, (3, 3), strides=(2, 2)),
            c(96, (3, 3), strides=(2, 2), padding="VALID")(x, train),
        ], axis=-1)
        b1 = c(96, (3, 3), padding="VALID")(c(64, (1, 1))(x, train), train)
        b2 = c(64, (1, 1))(x, train)
        b2 = c(64, (1, 7))(b2, train)
        b2 = c(64, (7, 1))(b2, train)
        b2 = c(96, (3, 3), padding="VALID")(b2, train)
        x = jnp.concatenate([b1, b2], axis=-1)
        return jnp.concatenate([
            c(192, (3, 3), strides=(2, 2), padding="VALID")(x, train),
            nn.max_pool(x, (3, 3), strides=(2, 2)),
        ], axis=-1)                     # 35x35x384


class InceptionA4(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b1 = c(96, (1, 1))(x, train)
        b2 = c(96, (3, 3))(c(64, (1, 1))(x, train), train)
        b3 = c(96, (3, 3))(c(96, (3, 3))(c(64, (1, 1))(x, train), train), train)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = c(96, (1, 1))(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionA4(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b1 = c(384, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        b2 = c(192, (1, 1))(x, train)
        b2 = c(224, (3, 3))(b2, train)
        b2 = c(256, (3, 3), strides=(2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)   # 17x17x1024


class InceptionB4(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b1 = c(384, (1, 1))(x, train)
        b2 = c(192, (1, 1))(x, train)
        b2 = c(224, (1, 7))(b2, train)
        b2 = c(256, (7, 1))(b2, train)
        b3 = c(192, (1, 1))(x, train)
        b3 = c(192, (7, 1))(b3, train)
        b3 = c(224, (1, 7))(b3, train)
        b3 = c(224, (7, 1))(b3, train)
        b3 = c(256, (1, 7))(b3, train)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = c(128, (1, 1))(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionB4(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b1 = c(192, (1, 1))(x, train)
        b1 = c(192, (3, 3), strides=(2, 2), padding="VALID")(b1, train)
        b2 = c(256, (1, 1))(x, train)
        b2 = c(256, (1, 7))(b2, train)
        b2 = c(320, (7, 1))(b2, train)
        b2 = c(320, (3, 3), strides=(2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)   # 8x8x1536


class InceptionC4(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b1 = c(256, (1, 1))(x, train)
        b2 = c(384, (1, 1))(x, train)
        b2 = jnp.concatenate(
            [c(256, (1, 3))(b2, train), c(256, (3, 1))(b2, train)], axis=-1
        )
        b3 = c(384, (1, 1))(x, train)
        b3 = c(448, (1, 3))(b3, train)
        b3 = c(512, (3, 1))(b3, train)
        b3 = jnp.concatenate(
            [c(256, (3, 1))(b3, train), c(256, (1, 3))(b3, train)], axis=-1
        )
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = c(256, (1, 1))(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV4(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = StemV4(dtype=self.dtype)(x, train)
        for _ in range(4):
            x = InceptionA4(dtype=self.dtype)(x, train)
        x = ReductionA4(dtype=self.dtype)(x, train)
        for _ in range(7):
            x = InceptionB4(dtype=self.dtype)(x, train)
        x = ReductionB4(dtype=self.dtype)(x, train)
        for _ in range(3):
            x = InceptionC4(dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.2, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def inception_v4(num_classes=1000, dtype=jnp.float32):
    return InceptionV4(num_classes=num_classes, dtype=dtype)
