"""Llama-style decoder (RMSNorm + RoPE + SwiGLU + GQA) in Flax.

Beyond-reference member (the reference's only text config is BERT MLM —
SURVEY.md §2c): the modern decoder architecture family, so a user of this
framework finds current-generation LM building blocks alongside the
GPT-2/BERT classics.  TPU-first choices:

- **RoPE** is applied after the QK projections with positions from
  ``global_position_ids``, so it is sequence-parallel-aware for free
  (each seq shard rotates by its global offset).
- **GQA**: ``num_kv_heads < heads`` shrinks the KV projection params; the
  attention dispatch broadcasts KV heads to the query-head count
  (``kv_repeat``) — up front for the single-device impls, but *after or
  inside the collective* for ring/ulysses, so sequence parallelism moves
  only the un-repeated KV bytes over the fabric.  MXU work equals MHA;
  params and SP wire traffic shrink.
- **SwiGLU** gate/up/down projections are three MXU-shaped matmuls;
  RMSNorm statistics accumulate in f32 (bf16-safe).
- Untied LM head (Llama convention), computed with compute-dtype operands
  and f32 accumulation like the other families.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpu_hc_bench.models.bert import global_position_ids


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        xf = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        y = xf * jax.lax.rsqrt(var + self.eps)
        return (y * scale.astype(jnp.float32)).astype(self.dtype)


def apply_rope(x, positions, theta: float = 10000.0):
    """Rotary position embedding over the trailing head_dim.

    ``x``: [batch, seq, heads, head_dim]; ``positions``: [seq] global
    token positions shared across the batch (sequence-parallel shards
    pass their offset range), or [batch, seq] per-row positions (the
    serving lane's decode step, where every in-flight request sits at
    its own cache depth).  Split-half convention (rotate_half), f32
    trig, output in x's dtype.
    """
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[..., None] * freqs
    if angles.ndim == 2:                             # [S, half]
        cos = jnp.cos(angles)[None, :, None, :]      # [1, S, 1, half]
        sin = jnp.sin(angles)[None, :, None, :]
    else:                                            # [B, S, half]
        cos = jnp.cos(angles)[:, :, None, :]         # [B, S, 1, half]
        sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out = jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin], axis=-1)
    return out.astype(x.dtype)


class LlamaAttention(nn.Module):
    """Causal self-attention with RoPE and grouped-query KV heads."""

    hidden: int
    heads: int
    num_kv_heads: int
    max_len: int
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    seq_axis: str | None = None

    @nn.compact
    def __call__(self, x):
        if self.heads % self.num_kv_heads:
            raise ValueError(
                f"heads={self.heads} not divisible by "
                f"num_kv_heads={self.num_kv_heads}")
        d = self.hidden // self.heads
        group = self.heads // self.num_kv_heads
        q = nn.DenseGeneral((self.heads, d), use_bias=False,
                            dtype=self.dtype, name="wq")(x)
        k = nn.DenseGeneral((self.num_kv_heads, d), use_bias=False,
                            dtype=self.dtype, name="wk")(x)
        v = nn.DenseGeneral((self.num_kv_heads, d), use_bias=False,
                            dtype=self.dtype, name="wv")(x)
        pos = global_position_ids(x.shape[1], self.seq_axis, self.max_len)
        q = apply_rope(q, pos)
        k = apply_rope(k, pos)
        # GQA: the dispatch broadcasts KV heads to the query-head count —
        # up front for single-device impls, after/inside the collective
        # for sequence-parallel ones (un-repeated KV bytes on the wire)
        from tpu_hc_bench.parallel.sequence import local_attention

        out = local_attention(q, k, v, impl=self.attention_impl,
                              axis_name=self.seq_axis, causal=True,
                              kv_repeat=group)
        return nn.DenseGeneral(self.hidden, axis=(-2, -1), use_bias=False,
                               dtype=self.dtype, name="wo")(out)


class LlamaBlock(nn.Module):
    hidden: int
    heads: int
    num_kv_heads: int
    ffn: int
    max_len: int
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    seq_axis: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train  # Llama uses no dropout
        h = RMSNorm(dtype=self.dtype, name="attn_norm")(x)
        x = x + LlamaAttention(
            self.hidden, self.heads, self.num_kv_heads, self.max_len,
            dtype=self.dtype, attention_impl=self.attention_impl,
            seq_axis=self.seq_axis, name="attn")(h)
        h = RMSNorm(dtype=self.dtype, name="mlp_norm")(x)
        gate = nn.Dense(self.ffn, use_bias=False, dtype=self.dtype,
                        name="gate")(h)
        up = nn.Dense(self.ffn, use_bias=False, dtype=self.dtype,
                      name="up")(h)
        down = nn.Dense(self.hidden, use_bias=False, dtype=self.dtype,
                        name="down")(nn.silu(gate) * up)
        return x + down


class LlamaLM(nn.Module):
    vocab_size: int = 32000
    hidden: int = 2048
    num_layers: int = 16
    heads: int = 32
    num_kv_heads: int = 8
    ffn: int = 8192
    max_len: int = 2048
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    seq_axis: str | None = None
    remat: bool = False
    scan_layers: bool = False          # lax.scan over stacked layers: ONE
                                       # compiled layer body regardless of
                                       # depth — same program-size lever as
                                       # GPTLM.scan_layers (round 5: built
                                       # because llama_1b's UNROLLED 16-layer
                                       # 1.1B program is what the remote
                                       # compile helper 500s on; round-4
                                       # bisect: <=6 unrolled layers compile,
                                       # >=9 crash).  Param tree: layers/<..>
                                       # stacked [L, ...] instead of
                                       # layer_i/<..> — not interchangeable
                                       # with unrolled checkpoints, guarded
                                       # off TP/EP/PP by the driver.

    @nn.compact
    def __call__(self, token_ids, train: bool = True):
        x = nn.Embed(self.vocab_size, self.hidden, dtype=self.dtype,
                     name="tok_embed")(token_ids)
        block_cls = (nn.remat(LlamaBlock, static_argnums=(2,))
                     if self.remat else LlamaBlock)
        block_kw = dict(
            hidden=self.hidden, heads=self.heads,
            num_kv_heads=self.num_kv_heads, ffn=self.ffn,
            max_len=self.max_len, dtype=self.dtype,
            attention_impl=self.attention_impl, seq_axis=self.seq_axis)
        if self.scan_layers:
            # scan-over-layers: stacked params [L, ...], one compiled body
            # (no dropout in the family, but params rngs still split per
            # layer so each stacked slice initializes independently)
            scan = nn.scan(
                lambda module, carry, _: (module(carry, train), None),
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=self.num_layers)
            x, _ = scan(block_cls(**block_kw, name="layers"), x, None)
        else:
            for i in range(self.num_layers):
                x = block_cls(**block_kw, name=f"layer_{i}")(x, train)
        x = RMSNorm(dtype=self.dtype, name="final_norm")(x)
        head = self.param(
            "lm_head", nn.initializers.normal(0.02),
            (self.hidden, self.vocab_size))
        return jnp.einsum("bsh,hv->bsv", x.astype(self.dtype),
                          head.astype(self.dtype),
                          preferred_element_type=jnp.float32)

    # --- pipeline-parallel interface (parallel/pipeline.py) -------------
    # Same contract as GPTLM's: the PP step builder derives the stage
    # forward from these instead of hardcoding any family's wiring.

    @nn.nowrap
    def pp_layer_module(self) -> nn.Module:
        return LlamaBlock(
            self.hidden, self.heads, self.num_kv_heads, self.ffn,
            self.max_len, dtype=self.dtype,
            attention_impl=self.attention_impl)

    @nn.nowrap
    def pp_embed(self, params: dict, token_ids, rng):
        """Token embedding only (no positions here — RoPE rotates inside
        attention; no embed dropout in the Llama family)."""
        emb = params["tok_embed"]["embedding"]
        return emb.astype(self.dtype)[token_ids], rng

    @nn.nowrap
    def pp_head(self, params: dict, x):
        x = RMSNorm(dtype=self.dtype).apply(
            {"params": params["final_norm"]}, x)
        return jnp.einsum("bsh,hv->bsv", x.astype(self.dtype),
                          params["lm_head"].astype(self.dtype),
                          preferred_element_type=jnp.float32)


def llama_1b(num_classes: int = 0, dtype=jnp.float32,
             attention_impl: str = "dense", max_len: int | None = None,
             remat: bool = False, seq_axis: str | None = None,
             scan_layers: bool = False):
    """Llama-3.2-1B-shaped decoder (16L/2048H, 32q/8kv heads, SwiGLU
    8192, 32k vocab here to keep the head sane on one chip; ~1.1B
    params)."""
    del num_classes
    return LlamaLM(dtype=dtype, attention_impl=attention_impl,
                   max_len=max(2048, max_len or 0), remat=remat,
                   seq_axis=seq_axis, scan_layers=scan_layers)


def llama_tiny(num_classes: int = 0, dtype=jnp.float32,
               attention_impl: str = "dense", max_len: int | None = None,
               remat: bool = False, seq_axis: str | None = None,
               scan_layers: bool = False):
    """4-layer/128-hidden 8q/2kv variant for tests and CPU smoke runs."""
    del num_classes
    return LlamaLM(vocab_size=1024, hidden=128, num_layers=4, heads=8,
                   num_kv_heads=2, ffn=256, max_len=max(128, max_len or 0),
                   dtype=dtype, attention_impl=attention_impl, remat=remat,
                   seq_axis=seq_axis, scan_layers=scan_layers)
