"""MobileNet v1 in Flax (tf_cnn_benchmarks zoo's mobile family).

Depthwise-separable CNN (Howard 2017) at the standard 1.0 width, 224x224.
Depthwise convolutions are expressed with ``feature_group_count=channels``
— XLA:TPU lowers these to VPU-friendly per-channel convs; the pointwise
1x1s are plain MXU matmuls and carry nearly all the FLOPs.

TPU conventions shared with the zoo: NHWC, parameterized compute dtype
(params/BN stats fp32), local-batch BN (Horovod DP semantics — see
``models/resnet.py`` module docstring).
"""

from __future__ import annotations

import functools
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

# (pointwise output channels, stride of the depthwise stage)
_V1_BLOCKS = [
    (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
    (512, 1), (512, 1), (512, 1), (512, 1), (512, 1),
    (1024, 2), (1024, 1),
]


class MobileNetV1(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype,
                                 padding="SAME")
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-3, dtype=self.dtype,
        )

        x = x.astype(self.dtype)
        x = conv(32, (3, 3), strides=(2, 2), name="conv_init")(x)
        x = nn.relu6(norm(name="bn_init")(x))
        for i, (filters, stride) in enumerate(_V1_BLOCKS):
            c_in = x.shape[-1]
            x = conv(c_in, (3, 3), strides=(stride, stride),
                     feature_group_count=c_in, name=f"dw_{i}")(x)
            x = nn.relu6(norm(name=f"dw_bn_{i}")(x))
            x = conv(filters, (1, 1), name=f"pw_{i}")(x)
            x = nn.relu6(norm(name=f"pw_bn_{i}")(x))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def mobilenet(num_classes=1000, dtype=jnp.float32):
    return MobileNetV1(num_classes=num_classes, dtype=dtype)
