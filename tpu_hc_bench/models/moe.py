"""Mixture-of-Experts FFN + expert parallelism (GShard/Switch style).

Beyond-reference capability (the reference is DP-only, SURVEY.md §2c —
expert parallelism listed "absent"): sparse MoE layers for the decoder
family, designed TPU-first.

The dispatch is the classic GShard einsum formulation: per-group (= per
batch row) top-k routing builds dense ``dispatch``/``combine`` tensors of
shape ``[B, S, E, C]`` (C = expert capacity), and all data movement is
einsum contractions — no gather/scatter, no dynamic shapes, every op lands
on the MXU.  Expert parallelism is pure GSPMD: the expert-major parameter
tensors ``wi [E, H, F]`` / ``wo [E, F, H]`` are sharded over the mesh
"model" axis (``train.step.tp_param_spec`` rules), tokens stay sharded
over "data", and XLA's SPMD partitioner inserts the expert all-to-alls for
the ``[E, ...]``-sharded einsums itself — the same GSPMD arm the tensor-
parallel path rides (``--expert_parallel`` ↦ mesh model axis).

Router details: router logits in float32 (softmax stability under bf16
params); top-k selection by iterative argmax masking; capacity overflow
tokens are dropped (their combine weight is zero, the residual connection
carries them through — standard Switch behavior); the Switch load-balance
auxiliary loss is sown into the ``"losses"`` collection and picked up by
``train.step._loss_and_updates``.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

# Switch-Transformer convention: aux = E * Σ_e f_e · p̄_e, weighted into the
# total loss at this coefficient (Fedus et al. use 1e-2).
AUX_LOSS_COEF = 0.01


def top_k_routing(probs: jax.Array, top_k: int, capacity: int):
    """Build dispatch/combine tensors from router probabilities.

    ``probs``: [B, S, E] float32 router softmax.  Returns
    ``(dispatch [B,S,E,C] bool-ish float, combine [B,S,E,C] float32,
    aux_loss scalar)``.  Routing is per-group (group = batch row): each
    expert accepts at most ``capacity`` tokens *per group*, assigned in
    sequence order with earlier-k choices taking priority (GShard's
    position-in-expert cumsum).
    """
    b, s, e = probs.shape
    masks, gates = [], []
    p = probs
    for _ in range(top_k):
        idx = jnp.argmax(p, axis=-1)                    # [B, S]
        mask = jax.nn.one_hot(idx, e, dtype=probs.dtype)  # [B, S, E]
        gates.append((p * mask).sum(-1))                # [B, S]
        masks.append(mask)
        p = p * (1.0 - mask)

    # Switch aux loss from the k=0 assignment (pre-capacity): fraction of
    # tokens routed to each expert x mean router prob, summed, scaled by E.
    frac = masks[0].mean(axis=(0, 1))                   # [E]
    mean_prob = probs.mean(axis=(0, 1))                 # [E]
    aux_loss = e * jnp.sum(frac * mean_prob)

    # normalize the selected gates to sum to 1 per token (top-2 convention)
    denom = jnp.maximum(sum(gates), 1e-9)
    gates = [g / denom for g in gates]

    dispatch = jnp.zeros((b, s, e, capacity), probs.dtype)
    combine = jnp.zeros((b, s, e, capacity), probs.dtype)
    offset = jnp.zeros((b, 1, e), probs.dtype)
    for mask, gate in zip(masks, gates):
        # position of each token within its expert's queue (per group)
        pos = jnp.cumsum(mask, axis=1) - mask + offset   # [B, S, E]
        offset = offset + mask.sum(axis=1, keepdims=True)
        mask = mask * (pos < capacity)                   # drop overflow
        pos_tok = (pos * mask).sum(-1).astype(jnp.int32)  # [B, S]
        slot = jax.nn.one_hot(pos_tok, capacity, dtype=probs.dtype)
        placed = mask[..., None] * slot[:, :, None, :]   # [B, S, E, C]
        dispatch = dispatch + placed
        combine = combine + gate[..., None, None] * placed
    return dispatch, combine, aux_loss


class MoEFFN(nn.Module):
    """Sparse MoE feed-forward block: drop-in for a transformer's dense FFN.

    Expert-major params (``wi [E, H, F]``, ``wo [E, F, H]``) so expert
    parallelism is a single leading-dim PartitionSpec.  All dispatch math
    is einsum; activations follow ``dtype`` (bf16-safe), router in f32.
    """

    hidden: int
    ffn: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, s, h = x.shape
        e = self.num_experts
        # per-group (= per batch row) expert capacity, floor of 4 slots
        import math

        capacity = max(4, math.ceil(self.capacity_factor * self.top_k * s / e))

        router = nn.Dense(e, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32, name="router")
        probs = jax.nn.softmax(router(x.astype(jnp.float32)), axis=-1)
        dispatch, combine, aux = top_k_routing(probs, self.top_k, capacity)
        self.sow("losses", "moe_aux", aux)
        # the [B,S,E,C] dispatch/combine tensors dominate the layer's
        # activation memory (they are saved for backward); store them in
        # the compute dtype — dispatch is 0/1 exactly, combine gates lose
        # only bf16 rounding on weights the router learned in f32
        dispatch = dispatch.astype(self.dtype)
        combine = combine.astype(self.dtype)

        init = nn.initializers.lecun_normal(batch_axis=(0,))
        wi = self.param("wi", init, (e, h, self.ffn))
        wo = self.param("wo", init, (e, self.ffn, h))

        xin = jnp.einsum("bsec,bsh->ebch", dispatch, x.astype(self.dtype))
        act = nn.gelu(jnp.einsum("ebch,ehf->ebcf", xin,
                                 wi.astype(self.dtype)))
        out = jnp.einsum("ebcf,efh->ebch", act, wo.astype(self.dtype))
        y = jnp.einsum("bsec,ebch->bsh", combine, out)
        return y.astype(x.dtype)
