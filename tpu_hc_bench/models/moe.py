"""Mixture-of-Experts FFN + expert parallelism (GShard/Switch style).

Beyond-reference capability (the reference is DP-only, SURVEY.md §2c —
expert parallelism listed "absent"): sparse MoE layers for the decoder
family, designed TPU-first.

The dispatch is the classic GShard einsum formulation: per-group (= per
batch row) top-k routing builds dense ``dispatch``/``combine`` tensors of
shape ``[B, S, E, C]`` (C = expert capacity), and all data movement is
einsum contractions — no gather/scatter, no dynamic shapes, every op lands
on the MXU.  Expert parallelism is pure GSPMD: the expert-major parameter
tensors ``wi [E, H, F]`` / ``wo [E, F, H]`` are sharded over the mesh
"model" axis (``train.step.tp_param_spec`` rules), tokens stay sharded
over "data", and XLA's SPMD partitioner inserts the expert all-to-alls for
the ``[E, ...]``-sharded einsums itself — the same GSPMD arm the tensor-
parallel path rides (``--expert_parallel`` ↦ mesh model axis).

Router details: router logits in float32 (softmax stability under bf16
params); top-k selection by iterative argmax masking; capacity overflow
tokens are dropped (their combine weight is zero, the residual connection
carries them through — standard Switch behavior); the Switch load-balance
auxiliary loss is sown into the ``"losses"`` collection and picked up by
``train.step._loss_and_updates``.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

# Switch-Transformer convention: aux = E * Σ_e f_e · p̄_e, weighted into the
# total loss at this coefficient (Fedus et al. use 1e-2).
AUX_LOSS_COEF = 0.01


def topk_select(probs: jax.Array, top_k: int):
    """Shared top-k routing selection over the trailing expert axis.

    ``probs``: [..., E] router softmax.  Returns ``(masks, gates,
    choices, aux)``: per-k one-hot masks [..., E], per-k gate weights
    [...] normalized to sum to 1 per token, per-k argmax indices [...],
    and the Switch load-balance aux (E * Σ_e f_e · p̄_e from the k=0
    assignment, token means over all leading axes).  Both dispatch impls
    (einsum capacity routing, ragged grouped matmuls) derive from this
    one selection so they cannot diverge.
    """
    e = probs.shape[-1]
    masks, gates, choices = [], [], []
    p = probs
    for _ in range(top_k):
        idx = jnp.argmax(p, axis=-1)
        mask = jax.nn.one_hot(idx, e, dtype=probs.dtype)
        choices.append(idx)
        gates.append((p * mask).sum(-1))
        masks.append(mask)
        p = p * (1.0 - mask)
    token_axes = tuple(range(probs.ndim - 1))
    aux = e * jnp.sum(masks[0].mean(token_axes) * probs.mean(token_axes))
    # normalize the selected gates to sum to 1 per token (top-2 convention)
    denom = jnp.maximum(sum(gates), 1e-9)
    gates = [g / denom for g in gates]
    return masks, gates, choices, aux


def top_k_routing(probs: jax.Array, top_k: int, capacity: int):
    """Build dispatch/combine tensors from router probabilities.

    ``probs``: [B, S, E] float32 router softmax.  Returns
    ``(dispatch [B,S,E,C] bool-ish float, combine [B,S,E,C] float32,
    aux_loss scalar)``.  Routing is per-group (group = batch row): each
    expert accepts at most ``capacity`` tokens *per group*, assigned in
    sequence order with earlier-k choices taking priority (GShard's
    position-in-expert cumsum).
    """
    b, s, e = probs.shape
    masks, gates, _, aux_loss = topk_select(probs, top_k)

    dispatch = jnp.zeros((b, s, e, capacity), probs.dtype)
    combine = jnp.zeros((b, s, e, capacity), probs.dtype)
    offset = jnp.zeros((b, 1, e), probs.dtype)
    for mask, gate in zip(masks, gates):
        # position of each token within its expert's queue (per group)
        pos = jnp.cumsum(mask, axis=1) - mask + offset   # [B, S, E]
        offset = offset + mask.sum(axis=1, keepdims=True)
        mask = mask * (pos < capacity)                   # drop overflow
        pos_tok = (pos * mask).sum(-1).astype(jnp.int32)  # [B, S]
        slot = jax.nn.one_hot(pos_tok, capacity, dtype=probs.dtype)
        placed = mask[..., None] * slot[:, :, None, :]   # [B, S, E, C]
        dispatch = dispatch + placed
        combine = combine + gate[..., None, None] * placed
    return dispatch, combine, aux_loss


class MoEFFN(nn.Module):
    """Sparse MoE feed-forward block: drop-in for a transformer's dense FFN.

    Expert-major params (``wi [E, H, F]``, ``wo [E, F, H]``) so expert
    parallelism is a single leading-dim PartitionSpec.  Router in f32,
    activations follow ``dtype`` (bf16-safe).  Two dispatch impls:

    - ``impl="einsum"`` (default): GShard dense dispatch/combine tensors.
      Fully GSPMD-shardable — the expert-parallel path — but pays the
      O(B·S·E·C) dispatch einsums and drops capacity-overflow tokens.
    - ``impl="ragged"``: sort token-expert pairs by expert and run the
      experts as grouped matmuls (``jax.lax.ragged_dot``, the TPU's
      native MoE primitive).  No capacity concept (zero token drops), no
      dispatch matmuls, no padding waste; single-shard expert compute, so
      it is the fast path for DP runs (``--expert_parallel`` requires
      einsum).
    """

    hidden: int
    ffn: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25      # einsum path: slots per expert =
                                       # ceil(cf * k * S / E); lower = more
                                       # drops, less dispatch memory — the
                                       # long-context pressure valve
    dtype: Any = jnp.float32
    impl: str = "einsum"
    ragged_chunk: int = 8192           # ragged path: max token-pair rows
                                       # per grouped matmul; larger inputs
                                       # run as a lax.map over chunks so
                                       # Mosaic's scoped-VMEM tiling never
                                       # sees an oversized operand
    ragged_f_chunk: int = 0            # ragged path: optionally tile the
                                       # FFN (F) dim of the [E,H,F]/[E,F,H]
                                       # weights (0 = full width).  Round 4
                                       # measured full width FASTER at every
                                       # reachable shape (the round-3 bs=16
                                       # failure was a whole-program compile
                                       # crash, not this kernel's VMEM —
                                       # see BASELINE.md MoE); the knob
                                       # stays for exploration

    @nn.compact
    def __call__(self, x):
        b, s, h = x.shape
        e = self.num_experts

        router = nn.Dense(e, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32, name="router")
        probs = jax.nn.softmax(router(x.astype(jnp.float32)), axis=-1)

        init = nn.initializers.lecun_normal(batch_axis=(0,))
        wi = self.param("wi", init, (e, h, self.ffn))
        wo = self.param("wo", init, (e, self.ffn, h))

        if self.impl == "ragged":
            y, aux = self._ragged(x, probs, wi, wo)
        elif self.impl == "einsum":
            y, aux = self._einsum(x, probs, wi, wo, s, e)
        else:
            raise ValueError(f"unknown moe impl {self.impl!r}")
        self.sow("losses", "moe_aux", aux)
        return y.astype(x.dtype)

    def _einsum(self, x, probs, wi, wo, s, e):
        # per-group (= per batch row) expert capacity, floor of 4 slots
        import math

        capacity = max(4, math.ceil(self.capacity_factor * self.top_k * s / e))
        dispatch, combine, aux = top_k_routing(probs, self.top_k, capacity)
        # the [B,S,E,C] dispatch/combine tensors dominate the layer's
        # activation memory (they are saved for backward); store them in
        # the compute dtype — dispatch is 0/1 exactly, combine gates lose
        # only bf16 rounding on weights the router learned in f32
        dispatch = dispatch.astype(self.dtype)
        combine = combine.astype(self.dtype)

        xin = jnp.einsum("bsec,bsh->ebch", dispatch, x.astype(self.dtype))
        act = nn.gelu(jnp.einsum("ebch,ehf->ebcf", xin,
                                 wi.astype(self.dtype)))
        out = jnp.einsum("ebcf,efh->ebch", act, wo.astype(self.dtype))
        y = jnp.einsum("bsec,ebch->bsh", combine, out)
        return y, aux

    def _ragged(self, x, probs, wi, wo):
        b, s, h = x.shape
        e, k = self.num_experts, self.top_k
        n = b * s
        flat = x.reshape(n, h).astype(self.dtype)
        p = probs.reshape(n, e)
        _, gate_list, choices, aux = topk_select(p, k)
        gates = jnp.stack(gate_list, 1)                   # [N, k]

        # token-major (token, choice) pairs sorted by expert -> grouped
        # matmuls over contiguous expert segments
        pair_expert = jnp.stack(choices, 1).reshape(n * k)
        pair_token = jnp.repeat(jnp.arange(n), k)
        order = jnp.argsort(pair_expert)
        xs = flat[pair_token[order]]                      # [N*k, H]
        wi_c, wo_c = wi.astype(self.dtype), wo.astype(self.dtype)

        total = n * k
        if total <= self.ragged_chunk:
            group_sizes = jnp.bincount(pair_expert, length=e).astype(
                jnp.int32)
            out = self._grouped_ffn(xs, group_sizes, wi_c, wo_c)
        else:
            # chunked grouped matmuls (round 2): big batchxseq blew past
            # Mosaic's scoped-VMEM tiling limit (BASELINE.md r1: 19.4M >
            # 16M at bs=16/seq=1024).  A contiguous slice of the sorted
            # pair array is still expert-sorted, so each chunk is a valid
            # ragged_dot with its own histogram; padding rows are tagged
            # with the last expert (keeps sortedness) and dropped after.
            chunk = self.ragged_chunk
            pad = (-total) % chunk
            seg = jnp.concatenate(
                [pair_expert[order],
                 jnp.full((pad,), e - 1, pair_expert.dtype)])
            xs_p = jnp.pad(xs, ((0, pad), (0, 0)))
            chunks = (total + pad) // chunk
            seg_c = seg.reshape(chunks, chunk)
            sizes = jax.nn.one_hot(seg_c, e, dtype=jnp.int32).sum(1)

            def body(args):
                xc, sz = args
                return self._grouped_ffn(xc, sz, wi_c, wo_c)

            out = jax.lax.map(body, (xs_p.reshape(chunks, chunk, h), sizes))
            out = out.reshape(chunks * chunk, h)[:total]
        # inverse-permute back to token-major pair order; weighted sum
        # over each token's k picks (pure gathers, no scatter)
        inv = jnp.argsort(order)
        out = out[inv].reshape(n, k, h)
        y = (out * gates[..., None].astype(self.dtype)).sum(axis=1)
        return y.reshape(b, s, h), aux

    def _grouped_ffn(self, xs, sizes, wi_c, wo_c):
        """Expert FFN over one expert-sorted row block: two grouped
        matmuls, with the FFN dim tiled to ``ragged_f_chunk``.

        The full-width contraction hands Mosaic a [E, F, H] weight block
        whose scoped-VMEM footprint scales with F (the round-3 bs=16
        failure); slicing F keeps every ragged_dot's weight tile small
        while the row dim stays the whole (expert-sorted) chunk.  gelu is
        elementwise over F, so per-slice activation is exact, and the
        second matmul's F-contraction distributes over slices as a sum —
        a lax.scan accumulates it without materializing [rows, F].
        """
        f = wi_c.shape[-1]
        fc = self.ragged_f_chunk
        if not fc or f <= fc:
            h1 = nn.gelu(jax.lax.ragged_dot(xs, wi_c, sizes))
            return jax.lax.ragged_dot(h1, wo_c, sizes)
        e, h = wi_c.shape[0], wi_c.shape[1]
        pad = (-f) % fc
        if pad:
            # zero-pad F: gelu(0)=0 and wo's zero rows contribute 0
            wi_c = jnp.pad(wi_c, ((0, 0), (0, 0), (0, pad)))
            wo_c = jnp.pad(wo_c, ((0, 0), (0, pad), (0, 0)))
        nf = (f + pad) // fc
        wi_t = wi_c.reshape(e, h, nf, fc).transpose(2, 0, 1, 3)
        wo_t = wo_c.reshape(e, nf, fc, h).transpose(1, 0, 2, 3)

        def slice_body(acc, ws):
            wi_s, wo_s = ws
            h1 = nn.gelu(jax.lax.ragged_dot(xs, wi_s, sizes))
            return acc + jax.lax.ragged_dot(h1, wo_s, sizes), None

        acc0 = jnp.zeros((xs.shape[0], h), self.dtype)
        out, _ = jax.lax.scan(slice_body, acc0, (wi_t, wo_t))
        return out
