"""NASNet-A in Flax (tf_cnn_benchmarks zoo's `nasnet`/`nasnetlarge`).

NASNet-A (Zoph et al. 2018) from the paper's cell spec: a learned normal
cell (6-branch concat) and reduction cell (4-branch concat) stacked as
stem -> 2 reduction cells -> 3 x [N normal cells (+ reduction)] -> head.
`nasnet` is the mobile size (4 @ 1056: N=4, 44 base filters, 224x224);
`nasnetlarge` is 6 @ 4032 (N=6, 168 base filters, 331x331).

TPU notes: separable convs run depthwise on the VPU
(``feature_group_count``) and pointwise on the MXU like MobileNet; the
many small branch ops make this the most fusion-stressing member of the
zoo (same role DenseNet plays at CIFAR scale).  Aux head omitted (zoo
convention here — benchmark loss never consumes it).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

# (op, hidden-state index) pairs, two per block, from the NASNet-A cells.
# States list starts [current(0), previous(1)]; each block appends its sum.
_NORMAL = [
    ("sep5", 0), ("sep3", 1),
    ("sep5", 1), ("sep3", 1),
    ("avg", 0), ("id", 1),
    ("avg", 1), ("avg", 1),
    ("sep3", 0), ("id", 0),
]
_NORMAL_CONCAT = [1, 2, 3, 4, 5, 6]      # unused states (0 is consumed)

_REDUCTION = [
    ("sep5", 0), ("sep7", 1),
    ("max", 0), ("sep7", 1),
    ("avg", 0), ("sep5", 1),
    ("id", 3), ("avg", 2),
    ("sep3", 2), ("max", 0),
]
_REDUCTION_CONCAT = [3, 4, 5, 6]         # states 0..2 are consumed


class SepConv(nn.Module):
    """NASNet separable op: 2x (relu -> depthwise k×k -> 1x1 -> BN); the
    stride lives on the first depthwise."""

    filters: int
    kernel: int
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        for rep, stride in enumerate((self.stride, 1)):
            c = x.shape[-1]
            x = nn.relu(x)
            x = nn.Conv(c, (self.kernel, self.kernel),
                        strides=(stride, stride), feature_group_count=c,
                        use_bias=False, padding="SAME", dtype=self.dtype,
                        name=f"dw{rep}")(x)
            x = nn.Conv(self.filters, (1, 1), use_bias=False,
                        dtype=self.dtype, name=f"pw{rep}")(x)
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9997,
                             epsilon=1e-3, dtype=self.dtype,
                             name=f"bn{rep}")(x)
        return x


class _CellCommon(nn.Module):
    """Shared machinery: input adjustment + op dispatch + block loop."""

    filters: int
    spec: tuple
    concat: tuple
    reduction: bool = False
    dtype: Any = jnp.float32

    def _norm(self, name, train):
        return nn.BatchNorm(use_running_average=not train,
                            momentum=0.9997, epsilon=1e-3, dtype=self.dtype,
                            name=name)

    def _fit(self, x, name, train):
        """relu -> 1x1 -> BN to `filters` channels."""
        x = nn.relu(x)
        x = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype,
                    name=f"{name}_1x1")(x)
        return self._norm(f"{name}_bn", train)(x)

    def _factorized_reduce(self, x, name, train):
        """Halve spatial, land on `filters` channels, without aliasing: two
        stride-2 paths offset by one pixel, concatenated."""
        x = nn.relu(x)
        p1 = nn.avg_pool(x, (1, 1), strides=(2, 2))
        p1 = nn.Conv(self.filters // 2, (1, 1), use_bias=False,
                     dtype=self.dtype, name=f"{name}_p1")(p1)
        p2 = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))[:, 1:, 1:, :]
        p2 = nn.avg_pool(p2, (1, 1), strides=(2, 2))
        p2 = nn.Conv(self.filters - self.filters // 2, (1, 1),
                     use_bias=False, dtype=self.dtype, name=f"{name}_p2")(p2)
        return self._norm(f"{name}_bn", train)(
            jnp.concatenate([p1, p2], axis=-1))

    def _op(self, kind, x, stride, name, train):
        if kind == "id":
            return x
        if kind in ("avg", "max"):
            pool = nn.avg_pool if kind == "avg" else nn.max_pool
            return pool(x, (3, 3), strides=(stride, stride), padding="SAME")
        k = {"sep3": 3, "sep5": 5, "sep7": 7}[kind]
        return SepConv(self.filters, k, stride, dtype=self.dtype,
                       name=name)(x, train=train)

    @nn.compact
    def __call__(self, x, prev, train: bool = True):
        if prev is None:
            prev = x
        if prev.shape[1] != x.shape[1]:
            prev = self._factorized_reduce(prev, "adjust_prev", train)
        elif prev.shape[-1] != self.filters:
            prev = self._fit(prev, "adjust_prev", train)
        cur = self._fit(x, "base", train)
        states = [cur, prev]
        for b in range(5):
            (op_l, i_l), (op_r, i_r) = self.spec[2 * b], self.spec[2 * b + 1]
            outs = []
            for side, (op, i) in (("l", (op_l, i_l)), ("r", (op_r, i_r))):
                stride = 2 if self.reduction and i < 2 else 1
                outs.append(self._op(op, states[i], stride,
                                     f"b{b}{side}_{op}", train))
            states.append(outs[0] + outs[1])
        return jnp.concatenate([states[i] for i in self.concat], axis=-1)


def NormalCell(filters, dtype, name):
    return _CellCommon(filters, tuple(_NORMAL), tuple(_NORMAL_CONCAT),
                       reduction=False, dtype=dtype, name=name)


def ReductionCell(filters, dtype, name):
    return _CellCommon(filters, tuple(_REDUCTION), tuple(_REDUCTION_CONCAT),
                       reduction=True, dtype=dtype, name=name)


class NASNetA(nn.Module):
    num_cells: int = 4                   # normal cells per stack
    base_filters: int = 44
    stem_filters: int = 32
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        f = self.base_filters
        x = x.astype(self.dtype)
        x = nn.Conv(self.stem_filters, (3, 3), strides=(2, 2),
                    use_bias=False, padding="VALID", dtype=self.dtype,
                    name="stem_conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9997,
                         epsilon=1e-3, dtype=self.dtype, name="stem_bn")(x)
        prev, cur = None, x
        cur, prev = ReductionCell(f // 4, self.dtype, "stem_reduce0")(
            cur, prev, train), cur
        cur, prev = ReductionCell(f // 2, self.dtype, "stem_reduce1")(
            cur, prev, train), cur
        for stack in range(3):
            filters = f * 2 ** stack
            for i in range(self.num_cells):
                cur, prev = NormalCell(
                    filters, self.dtype, f"s{stack}_cell{i}")(
                        cur, prev, train), cur
            if stack < 2:
                cur, prev = ReductionCell(
                    filters * 2, self.dtype, f"reduce{stack}")(
                        cur, prev, train), cur
        x = nn.relu(cur)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def nasnet(num_classes=1000, dtype=jnp.float32):
    """NASNet-A mobile, 4 @ 1056 (224x224)."""
    return NASNetA(num_cells=4, base_filters=44, stem_filters=32,
                   num_classes=num_classes, dtype=dtype)


def nasnetlarge(num_classes=1000, dtype=jnp.float32):
    """NASNet-A large, 6 @ 4032 (331x331)."""
    return NASNetA(num_cells=6, base_filters=168, stem_filters=96,
                   num_classes=num_classes, dtype=dtype)
