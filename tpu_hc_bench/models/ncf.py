"""Neural Collaborative Filtering (NeuMF) — tf_cnn_benchmarks' `ncf`.

Closes the last gap in the tf_cnn model-zoo inventory (SURVEY.md §2b #22;
`ncf` and `deepspeech2` were the two members previously excluded).  The
tf_cnn version is the MLPerf NCF recommendation benchmark: MovieLens
user/item ids through a GMF (elementwise-product) tower and an MLP tower,
fused into one prediction head (He et al. 2017 NeuMF).

TPU-first framing: the prediction head is a 2-way softmax instead of a
sigmoid — mathematically equivalent for binary implicit feedback, and it
drops straight into the benchmark driver's image-family contract
(``logits [B, num_classes]`` vs ``labels [B]``), so the standard loss,
eval top-1 (= binary accuracy), and every parallelism arm work unchanged.
Inputs are ``[B, 2] int32`` (user, item) id pairs — the registry marks
the member ``integer_input`` and the driver feeds ``SyntheticIds``.
Embedding gathers and the MLP land on the MXU as dense ops; there is no
sequence dim, so like the CNNs it is a pure DP workload.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# MovieLens ml-20m cardinalities (the MLPerf NCF dataset tf_cnn targets)
ML20M_USERS = 138_493
ML20M_ITEMS = 26_744


class NeuMF(nn.Module):
    num_users: int = ML20M_USERS
    num_items: int = ML20M_ITEMS
    mf_dim: int = 64                       # GMF embedding width
    mlp_dims: Sequence[int] = (256, 256, 128, 64)   # MLP tower (mlperf NCF)
    num_classes: int = 2                   # binary implicit feedback
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, ids, train: bool = True):
        del train                           # no dropout in the benchmark
        users, items = ids[:, 0], ids[:, 1]
        mf_u = nn.Embed(self.num_users, self.mf_dim, dtype=self.dtype,
                        name="mf_user")(users)
        mf_i = nn.Embed(self.num_items, self.mf_dim, dtype=self.dtype,
                        name="mf_item")(items)
        gmf = mf_u * mf_i

        mlp_dim = self.mlp_dims[0] // 2
        ml_u = nn.Embed(self.num_users, mlp_dim, dtype=self.dtype,
                        name="mlp_user")(users)
        ml_i = nn.Embed(self.num_items, mlp_dim, dtype=self.dtype,
                        name="mlp_item")(items)
        x = jnp.concatenate([ml_u, ml_i], axis=-1)
        for i, width in enumerate(self.mlp_dims[1:]):
            x = nn.relu(nn.Dense(width, dtype=self.dtype,
                                 name=f"mlp_{i}")(x))
        fused = jnp.concatenate([gmf, x], axis=-1)
        # f32 head like the rest of the zoo (loss numerics)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(fused)


def ncf(num_classes: int = 2, dtype=jnp.float32):
    """NeuMF at the MLPerf/ml-20m shape (~31.8M params — GMF + MLP
    embeddings dominate: (138493+26744)x(64+128)).  ``num_classes`` is
    forced to 2 (binary feedback)."""
    del num_classes
    return NeuMF(dtype=dtype)


def ncf_tiny(num_classes: int = 2, dtype=jnp.float32):
    """Small-vocab variant for tests/CPU smoke runs (~100k params)."""
    del num_classes
    return NeuMF(num_users=1000, num_items=500, mf_dim=8,
                 mlp_dims=(32, 32, 16, 8), dtype=dtype)
