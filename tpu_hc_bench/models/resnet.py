"""ResNet family in Flax — the reference's flagship workload.

The reference pins ``MODEL=resnet50`` (``run-tf-sing-ucx-openmpi.sh:34``)
and drives tf_cnn_benchmarks' ResNet-50 v1.5 implementation (the variant
with stride 2 on the 3x3 conv of the downsampling bottleneck) on 224x224
ImageNet in NCHW for MKL-DNN.  This is a fresh TPU-first implementation:

- NHWC only: channels on the 128-lane minor axis is what the MXU tiles
  (the launcher's ``--data_format=NCHW`` is translated by flags.resolve).
- Parameterized compute dtype: fp32 for reference parity, bf16 for the TPU
  fast path; parameters and BN statistics stay fp32 either way.
- BatchNorm uses *local* batch statistics per data-parallel worker, which is
  exactly Horovod DP semantics (each rank normalizes over its own
  per-worker batch; only gradients are allreduced).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """ResNet-v1.5 bottleneck: 1x1 -> 3x3(stride) -> 1x1, projection shortcut."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        # v1.5: stride lives on the 3x3, not the 1x1
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                name="shortcut_conv",
            )(residual)
            residual = self.norm(name="shortcut_bn")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    """Two-3x3 block for ResNet-18/34."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), strides=(self.strides, self.strides),
                name="shortcut_conv",
            )(residual)
            residual = self.norm(name="shortcut_bn")(residual)
        return self.act(residual + y)


class PreactBottleneckBlock(nn.Module):
    """ResNet-v2 bottleneck (He 2016 full preactivation): BN-relu precede
    every conv, identity carries no norm/act.  tf_cnn_benchmarks exposes
    these as ``resnet50_v2``/``101_v2``/``152_v2``."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        preact = self.act(self.norm()(x))
        out_ch = self.filters * 4
        if x.shape[-1] != out_ch or self.strides != 1:
            residual = self.conv(
                out_ch, (1, 1), strides=(self.strides, self.strides),
                name="shortcut_conv",
            )(preact)
        else:
            residual = x
        y = self.conv(self.filters, (1, 1))(preact)
        y = self.act(self.norm()(y))
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.act(self.norm()(y))
        y = self.conv(out_ch, (1, 1))(y)
        return residual + y


class ResNet(nn.Module):
    """ImageNet ResNet, NHWC, parameterized depth and dtype."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32
    preact: bool = False                # v2: BN-relu inside blocks only
    space_to_depth: bool = False        # pack 2x2 blocks into channels and
                                        # run the stem as a 4x4/s1 conv — the
                                        # standard TPU stem transform (3-ch
                                        # 7x7/s2 convs map poorly to the MXU)
    barrier: str = "none"               # fusion-split experiment knob
                                        # (scripts/exp_resnet_mfu.py):
                                        # pre  = barrier conv-out -> BN-in
                                        # post = barrier BN-out -> act/conv
                                        # both = both edges

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
        )
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        def barriered(factory):
            # factory -> factory whose modules emit through an
            # optimization_barrier, splitting the fusion at that edge
            # (e.g. conv-backward from the BN-stat reductions XLA would
            # fuse into it — the round-1 ~43%-MXU-efficiency pattern)
            def make(*a, **k):
                m = factory(*a, **k)
                return lambda y: jax.lax.optimization_barrier(m(y))
            return make

        if self.barrier in ("pre", "both"):
            conv = barriered(conv)
        if self.barrier in ("post", "both"):
            norm = barriered(norm)
        act = nn.relu

        x = x.astype(self.dtype)
        if self.space_to_depth:
            # [N, 2h, 2w, c] -> [N, h, w, 4c]; the 7x7/s2 stem conv becomes a
            # 4x4/s1 conv over the packed image whose kernel rows/cols
            # interleave the (zero-padded-to-8x8) 7x7 weights.  Same math,
            # one quarter the spatial positions, 4x the contraction depth.
            n, h, w, c = x.shape
            x = x.reshape(n, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
            # padding ((1,2),(1,2)) in packed space reproduces SAME padding
            # (2 before, 3 after) of the 7x7/s2 conv at even input sizes
            x = conv(
                self.num_filters, (4, 4), padding=((1, 2), (1, 2)),
                name="conv_init_s2d",
            )(x)
        else:
            x = conv(self.num_filters, (7, 7), strides=(2, 2),
                     name="conv_init")(x)
        if not self.preact:
            x = act(norm(name="bn_init")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=act,
                )(x)
        if self.preact:
            x = act(norm(name="bn_final")(x))
        x = jnp.mean(x, axis=(1, 2))  # global average pool over H,W
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def _family(stages, block, preact=False):
    def create(num_classes=1000, dtype=jnp.float32, space_to_depth=False):
        return ResNet(stages, block, num_classes=num_classes, dtype=dtype,
                      preact=preact, space_to_depth=space_to_depth)
    return create


resnet18 = _family([2, 2, 2, 2], BasicBlock)
resnet34 = _family([3, 4, 6, 3], BasicBlock)
resnet50 = _family([3, 4, 6, 3], BottleneckBlock)
resnet101 = _family([3, 4, 23, 3], BottleneckBlock)
resnet152 = _family([3, 8, 36, 3], BottleneckBlock)
resnet50_v2 = _family([3, 4, 6, 3], PreactBottleneckBlock, preact=True)
resnet101_v2 = _family([3, 4, 23, 3], PreactBottleneckBlock, preact=True)
resnet152_v2 = _family([3, 8, 36, 3], PreactBottleneckBlock, preact=True)
