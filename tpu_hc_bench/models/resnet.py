"""ResNet family in Flax — the reference's flagship workload.

The reference pins ``MODEL=resnet50`` (``run-tf-sing-ucx-openmpi.sh:34``)
and drives tf_cnn_benchmarks' ResNet-50 v1.5 implementation (the variant
with stride 2 on the 3x3 conv of the downsampling bottleneck) on 224x224
ImageNet in NCHW for MKL-DNN.  This is a fresh TPU-first implementation:

- NHWC only: channels on the 128-lane minor axis is what the MXU tiles
  (the launcher's ``--data_format=NCHW`` is translated by flags.resolve).
- Parameterized compute dtype: fp32 for reference parity, bf16 for the TPU
  fast path; parameters and BN statistics stay fp32 either way.
- BatchNorm uses *local* batch statistics per data-parallel worker, which is
  exactly Horovod DP semantics (each rank normalizes over its own
  per-worker batch; only gradients are allreduced).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """ResNet-v1.5 bottleneck: 1x1 -> 3x3(stride) -> 1x1, projection shortcut."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        # v1.5: stride lives on the 3x3, not the 1x1
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                name="shortcut_conv",
            )(residual)
            residual = self.norm(name="shortcut_bn")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    """Two-3x3 block for ResNet-18/34."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), strides=(self.strides, self.strides),
                name="shortcut_conv",
            )(residual)
            residual = self.norm(name="shortcut_bn")(residual)
        return self.act(residual + y)


def _bn_scale_shift(mdl, x, stats, momentum, epsilon, use_running_average):
    """BatchNorm folded to per-channel scale/shift ``(a, b)``.

    Creates the scale/bias params and running-stat variables ON ``mdl``
    (so callers keep nn.BatchNorm's variable layout), derives batch
    statistics from ``stats=(sum, sumsq)`` when given (the fused kernel's
    epilogue) or by reducing ``x``, and updates the running averages in
    train mode.  The ONE home of this logic for both fused-BN modules.
    """
    c = x.shape[-1]
    scale = mdl.param("scale", nn.initializers.ones, (c,), jnp.float32)
    bias = mdl.param("bias", nn.initializers.zeros, (c,), jnp.float32)
    ra_mean = mdl.variable("batch_stats", "mean",
                           lambda s: jnp.zeros(s, jnp.float32), (c,))
    ra_var = mdl.variable("batch_stats", "var",
                          lambda s: jnp.ones(s, jnp.float32), (c,))
    if use_running_average:
        mean, var = ra_mean.value, ra_var.value
    else:
        if stats is None:
            xf = x.astype(jnp.float32)
            mean = xf.mean((0, 1, 2))
            var = (xf * xf).mean((0, 1, 2)) - mean * mean
        else:
            s1, s2 = stats
            n = x.shape[0] * x.shape[1] * x.shape[2]
            mean = s1 / n
            var = s2 / n - mean * mean
        if not mdl.is_initializing():
            ra_mean.value = (momentum * ra_mean.value
                             + (1.0 - momentum) * mean)
            ra_var.value = (momentum * ra_var.value
                            + (1.0 - momentum) * var)
    a = scale * jax.lax.rsqrt(var + epsilon)
    b = bias - mean * a
    return a, b


class FusedBNReluConv3x3(nn.Module):
    """BatchNorm(input) -> relu -> 3x3 conv as ONE Pallas pass.

    Round-3 kernel (`ops/fused_conv.py`): at stage-2/3 shapes XLA does not
    fuse the BN-apply+relu into the conv's input read (measured 35% slower
    than the fused kernel, BASELINE.md round-3 table), so this module owns
    the input's BN params/running stats AND the conv kernel and emits the
    fused call where `fused_conv.eligible` says it wins; everywhere else
    (strided blocks, stage-1/4 shapes, tiny test images) it emits the
    identical XLA composition.  Returns ``(y, (sum, sumsq))`` — the
    epilogue's per-channel stats of y, consumed by the NEXT BatchNorm so
    no extra pass over y is ever made.
    """

    features: int
    strides: int = 1
    use_running_average: bool = False
    dtype: Any = jnp.float32
    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x):
        from tpu_hc_bench.ops import fused_conv as fc

        cin = x.shape[-1]
        a, b = _bn_scale_shift(self, x, None, self.momentum, self.epsilon,
                               self.use_running_average)
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (3, 3, cin, self.features), jnp.float32)
        w = kernel.astype(self.dtype)
        if fc.eligible(x.shape, (3, 3), self.strides, cin):
            y, s1, s2 = fc.fused_bn_relu_conv(x, a, b, w)
        else:
            # same-dtype conv (like nn.Conv: MXU accumulates f32
            # internally, output in compute dtype) — a f32-preferred
            # output here would make autodiff transpose the conv with a
            # f32 cotangent against bf16 operands, which lax rejects
            xn = jnp.maximum(
                x.astype(jnp.float32) * a + b, 0.0).astype(self.dtype)
            y = jax.lax.conv_general_dilated(
                xn, w, (self.strides, self.strides), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
            yf = y.astype(jnp.float32)
            s1 = yf.sum((0, 1, 2))
            s2 = (yf * yf).sum((0, 1, 2))
        return y, (s1, s2)


class StatsBatchNorm(nn.Module):
    """BatchNorm that consumes precomputed ``(sum, sumsq)`` stats (the
    fused kernel's epilogue) instead of re-reducing its input; same
    variable layout and running-stat semantics as ``nn.BatchNorm``."""

    use_running_average: bool = False
    dtype: Any = jnp.float32
    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x, stats=None):
        a, b = _bn_scale_shift(self, x, stats, self.momentum, self.epsilon,
                               self.use_running_average)
        return (x.astype(jnp.float32) * a + b).astype(self.dtype)


class FusedBottleneckBlock(nn.Module):
    """BottleneckBlock with the BN1-relu-conv3x3 segment fused (Pallas)
    and BN2 fed from the kernel's stats epilogue.  Same math as
    ``BottleneckBlock`` (pinned by tests/test_fused_conv_model.py); the
    param tree differs (the fused module owns bn1+conv2 jointly), so
    checkpoints do not interchange with the unfused layout.
    """

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    use_running_average: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y, st2 = FusedBNReluConv3x3(
            self.filters, strides=self.strides,
            use_running_average=self.use_running_average, dtype=self.dtype,
        )(y)
        y = self.act(StatsBatchNorm(
            use_running_average=self.use_running_average, dtype=self.dtype,
        )(y, stats=st2))
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                name="shortcut_conv",
            )(residual)
            residual = self.norm(name="shortcut_bn")(residual)
        return self.act(residual + y)


class PreactBottleneckBlock(nn.Module):
    """ResNet-v2 bottleneck (He 2016 full preactivation): BN-relu precede
    every conv, identity carries no norm/act.  tf_cnn_benchmarks exposes
    these as ``resnet50_v2``/``101_v2``/``152_v2``."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        preact = self.act(self.norm()(x))
        out_ch = self.filters * 4
        if x.shape[-1] != out_ch or self.strides != 1:
            residual = self.conv(
                out_ch, (1, 1), strides=(self.strides, self.strides),
                name="shortcut_conv",
            )(preact)
        else:
            residual = x
        y = self.conv(self.filters, (1, 1))(preact)
        y = self.act(self.norm()(y))
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.act(self.norm()(y))
        y = self.conv(out_ch, (1, 1))(y)
        return residual + y


class ResNet(nn.Module):
    """ImageNet ResNet, NHWC, parameterized depth and dtype."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32
    preact: bool = False                # v2: BN-relu inside blocks only
    fused_conv: bool = False            # round 3: Pallas fused
                                        # BN-relu-conv3x3 bottleneck segment
                                        # (ops/fused_conv.py win region)
    space_to_depth: bool = False        # pack 2x2 blocks into channels and
                                        # run the stem as a 4x4/s1 conv — the
                                        # standard TPU stem transform (3-ch
                                        # 7x7/s2 convs map poorly to the MXU)
    barrier: str = "none"               # fusion-split experiment knob
                                        # (scripts/exp_resnet_mfu.py):
                                        # pre  = barrier conv-out -> BN-in
                                        # post = barrier BN-out -> act/conv
                                        # both = both edges

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
        )
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        def barriered(factory):
            # factory -> factory whose modules emit through an
            # optimization_barrier, splitting the fusion at that edge
            # (e.g. conv-backward from the BN-stat reductions XLA would
            # fuse into it — the round-1 ~43%-MXU-efficiency pattern)
            def make(*a, **k):
                m = factory(*a, **k)
                return lambda y: jax.lax.optimization_barrier(m(y))
            return make

        if self.barrier in ("pre", "both"):
            conv = barriered(conv)
        if self.barrier in ("post", "both"):
            norm = barriered(norm)
        act = nn.relu

        x = x.astype(self.dtype)
        if self.space_to_depth:
            # [N, 2h, 2w, c] -> [N, h, w, 4c]; the 7x7/s2 stem conv becomes a
            # 4x4/s1 conv over the packed image whose kernel rows/cols
            # interleave the (zero-padded-to-8x8) 7x7 weights.  Same math,
            # one quarter the spatial positions, 4x the contraction depth.
            n, h, w, c = x.shape
            x = x.reshape(n, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
            # padding ((1,2),(1,2)) in packed space reproduces SAME padding
            # (2 before, 3 after) of the 7x7/s2 conv at even input sizes
            x = conv(
                self.num_filters, (4, 4), padding=((1, 2), (1, 2)),
                name="conv_init_s2d",
            )(x)
        else:
            x = conv(self.num_filters, (7, 7), strides=(2, 2),
                     name="conv_init")(x)
        if not self.preact:
            x = act(norm(name="bn_init")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        block_cls, extra = self.block_cls, {}
        if self.fused_conv:
            if self.block_cls is not BottleneckBlock:
                raise ValueError(
                    "fused_conv applies to the v1 bottleneck family "
                    "(resnet50/101/152) only")
            block_cls = FusedBottleneckBlock
            extra = dict(use_running_average=not train, dtype=self.dtype)
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=act,
                    **extra,
                )(x)
        if self.preact:
            x = act(norm(name="bn_final")(x))
        x = jnp.mean(x, axis=(1, 2))  # global average pool over H,W
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def _family(stages, block, preact=False):
    def create(num_classes=1000, dtype=jnp.float32, space_to_depth=False,
               fused_conv=False):
        return ResNet(stages, block, num_classes=num_classes, dtype=dtype,
                      preact=preact, space_to_depth=space_to_depth,
                      fused_conv=fused_conv)
    return create


resnet18 = _family([2, 2, 2, 2], BasicBlock)
resnet34 = _family([3, 4, 6, 3], BasicBlock)
resnet50 = _family([3, 4, 6, 3], BottleneckBlock)
resnet101 = _family([3, 4, 23, 3], BottleneckBlock)
resnet152 = _family([3, 8, 36, 3], BottleneckBlock)
resnet50_v2 = _family([3, 4, 6, 3], PreactBottleneckBlock, preact=True)
resnet101_v2 = _family([3, 4, 23, 3], PreactBottleneckBlock, preact=True)
resnet152_v2 = _family([3, 8, 36, 3], PreactBottleneckBlock, preact=True)
