"""ResNet family in Flax — the reference's flagship workload.

The reference pins ``MODEL=resnet50`` (``run-tf-sing-ucx-openmpi.sh:34``)
and drives tf_cnn_benchmarks' ResNet-50 v1.5 implementation (the variant
with stride 2 on the 3x3 conv of the downsampling bottleneck) on 224x224
ImageNet in NCHW for MKL-DNN.  This is a fresh TPU-first implementation:

- NHWC only: channels on the 128-lane minor axis is what the MXU tiles
  (the launcher's ``--data_format=NCHW`` is translated by flags.resolve).
- Parameterized compute dtype: fp32 for reference parity, bf16 for the TPU
  fast path; parameters and BN statistics stay fp32 either way.
- BatchNorm uses *local* batch statistics per data-parallel worker, which is
  exactly Horovod DP semantics (each rank normalizes over its own
  per-worker batch; only gradients are allreduced).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """ResNet-v1.5 bottleneck: 1x1 -> 3x3(stride) -> 1x1, projection shortcut."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        # v1.5: stride lives on the 3x3, not the 1x1
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                name="shortcut_conv",
            )(residual)
            residual = self.norm(name="shortcut_bn")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    """Two-3x3 block for ResNet-18/34."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), strides=(self.strides, self.strides),
                name="shortcut_conv",
            )(residual)
            residual = self.norm(name="shortcut_bn")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ImageNet ResNet, NHWC, parameterized depth and dtype."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
        )
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        act = nn.relu

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), strides=(2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=act,
                )(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool over H,W
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def resnet18(num_classes=1000, dtype=jnp.float32):
    return ResNet([2, 2, 2, 2], BasicBlock, num_classes=num_classes, dtype=dtype)


def resnet34(num_classes=1000, dtype=jnp.float32):
    return ResNet([3, 4, 6, 3], BasicBlock, num_classes=num_classes, dtype=dtype)


def resnet50(num_classes=1000, dtype=jnp.float32):
    return ResNet([3, 4, 6, 3], BottleneckBlock, num_classes=num_classes, dtype=dtype)


def resnet101(num_classes=1000, dtype=jnp.float32):
    return ResNet([3, 4, 23, 3], BottleneckBlock, num_classes=num_classes, dtype=dtype)


def resnet152(num_classes=1000, dtype=jnp.float32):
    return ResNet([3, 8, 36, 3], BottleneckBlock, num_classes=num_classes, dtype=dtype)
