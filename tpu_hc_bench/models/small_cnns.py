"""LeNet and OverFeat in Flax (tf_cnn_benchmarks zoo members).

The reference drives tf_cnn_benchmarks' full ``--model=`` zoo (the harness
pins resnet50 at ``run-tf-sing-ucx-openmpi.sh:34`` but the driven CLI
accepts every zoo member); these are the two classic small members:

- ``lenet``: tf_cnn_benchmarks' lenet5 (two 5x5 conv/pool stages then a
  512-wide FC), run at 28x28.
- ``overfeat``: the OverFeat "fast" network (Sermanet 2014) as
  tf_cnn_benchmarks sizes it — 231x231 input, 11x11 stride-4 conv1,
  five conv stages, 3072/4096 FCs.

Same TPU conventions as the rest of the zoo: NHWC, parameterized compute
dtype with fp32 head, dropout active only in training.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class LeNet(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(32, (5, 5), padding="SAME", dtype=self.dtype,
                            name="conv1")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (5, 5), padding="SAME", dtype=self.dtype,
                            name="conv2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512, dtype=self.dtype, name="fc1")(x))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc2")(x)
        return x.astype(jnp.float32)


class OverFeat(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(96, (11, 11), strides=(4, 4), padding="VALID",
                            dtype=self.dtype, name="conv1")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(256, (5, 5), padding="SAME", dtype=self.dtype,
                            name="conv2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(512, (3, 3), padding="SAME", dtype=self.dtype,
                            name="conv3")(x))
        x = nn.relu(nn.Conv(1024, (3, 3), padding="SAME", dtype=self.dtype,
                            name="conv4")(x))
        x = nn.relu(nn.Conv(1024, (3, 3), padding="SAME", dtype=self.dtype,
                            name="conv5")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(3072, dtype=self.dtype, name="fc6")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc7")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc8")(x)
        return x.astype(jnp.float32)


def lenet(num_classes=1000, dtype=jnp.float32):
    return LeNet(num_classes=num_classes, dtype=dtype)


def overfeat(num_classes=1000, dtype=jnp.float32):
    return OverFeat(num_classes=num_classes, dtype=dtype)
