"""VGG-11/16/19 in Flax (BASELINE.json config 3: "Inception-v3 / VGG-16 sweep").

Classic VGG (Simonyan & Zisserman) as driven by tf_cnn_benchmarks: conv
stacks without batch norm, two 4096-unit FC layers, NHWC.  Fresh TPU-first
implementation — the big FC layers are exactly MXU-shaped matmuls.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class VGG(nn.Module):
    stage_sizes: Sequence[int]          # convs per stage, 5 stages
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        filters = (64, 128, 256, 512, 512)
        for stage, n_convs in enumerate(self.stage_sizes):
            for i in range(n_convs):
                x = nn.Conv(
                    filters[stage], (3, 3), padding="SAME", dtype=self.dtype,
                    name=f"conv{stage + 1}_{i + 1}",
                )(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc6")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc7")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc8")(x)
        return x.astype(jnp.float32)


def vgg11(num_classes=1000, dtype=jnp.float32):
    return VGG([1, 1, 2, 2, 2], num_classes=num_classes, dtype=dtype)


def vgg16(num_classes=1000, dtype=jnp.float32):
    return VGG([2, 2, 3, 3, 3], num_classes=num_classes, dtype=dtype)


def vgg19(num_classes=1000, dtype=jnp.float32):
    return VGG([2, 2, 4, 4, 4], num_classes=num_classes, dtype=dtype)
