"""Vision Transformers (ViT-B/16, ViT-L/16) in Flax — modern ImageNet
members.

Beyond-reference members (the reference's zoo is conv-era CNNs driven
through tf_cnn_benchmarks — SURVEY.md §2b #22): ViT bridges the CNN zoo
and the transformer stack, reusing the framework's attention dispatch so
``--attention_impl=flash`` applies to an image model too.

TPU-first notes: patchify is one stride-16 conv (a [patch²·3, hidden]-
shaped matmul per patch — MXU-native, unlike the tiny 7x7 CNN stems);
the encoder is pre-LN with learned position embeddings and a class
token; all matmuls are MXU-shaped (hidden 768/1024).  Sequence length is
197 (196 patches + cls), far below where sequence parallelism pays, so
the ViT members are data/tensor-parallel workloads (tensor parallelism
works unchanged — the shared encoder block carries the param names the
Megatron TP rules match).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

# the pre-LN encoder block is gpt.DecoderLayer with causal=False — one
# block implementation serves GPT, MoE-GPT, and ViT
from tpu_hc_bench.models.gpt import DecoderLayer


class ViT(nn.Module):
    """ViT: patchify conv -> cls token + pos embed -> pre-LN encoder ->
    LN -> cls-token classification head."""

    num_classes: int = 1000
    patch: int = 16
    hidden: int = 768
    num_layers: int = 12
    heads: int = 12
    ffn: int = 3072
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        b = x.shape[0]
        x = x.astype(self.dtype)
        x = nn.Conv(self.hidden, (self.patch, self.patch),
                    strides=(self.patch, self.patch), padding="VALID",
                    dtype=self.dtype, name="patchify")(x)
        x = x.reshape(b, -1, self.hidden)            # [B, patches, H]
        cls = self.param("cls", nn.initializers.zeros, (1, 1, self.hidden))
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(self.dtype),
                              (b, 1, self.hidden)), x], axis=1)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, x.shape[1], self.hidden))
        x = x + pos.astype(self.dtype)
        x = nn.Dropout(0.1, deterministic=not train)(x)
        layer_cls = (nn.remat(DecoderLayer, static_argnums=(2,))
                     if self.remat else DecoderLayer)
        for i in range(self.num_layers):
            x = layer_cls(self.hidden, self.heads, self.ffn,
                          dtype=self.dtype, causal=False,
                          attention_impl=self.attention_impl,
                          name=f"layer_{i}")(x, train)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x[:, 0])


def vit_b16(num_classes: int = 1000, dtype=jnp.float32,
            attention_impl: str = "dense", remat: bool = False):
    """ViT-Base/16 (12L/768H/12 heads, ~86M params at 1000 classes)."""
    return ViT(num_classes=num_classes, dtype=dtype,
               attention_impl=attention_impl, remat=remat)


def vit_l16(num_classes: int = 1000, dtype=jnp.float32,
            attention_impl: str = "dense", remat: bool = False):
    """ViT-Large/16 (24L/1024H/16 heads, ~304M params)."""
    return ViT(num_classes=num_classes, hidden=1024, num_layers=24,
               heads=16, ffn=4096, dtype=dtype,
               attention_impl=attention_impl, remat=remat)


def vit_tiny(num_classes: int = 1000, dtype=jnp.float32,
             attention_impl: str = "dense", remat: bool = False):
    """4-layer/64-hidden patch-8 variant for tests and CPU smoke runs."""
    return ViT(num_classes=num_classes, patch=8, hidden=64, num_layers=4,
               heads=4, ffn=128, dtype=dtype, attention_impl=attention_impl,
               remat=remat)
