"""ctypes bindings for the native TFRecord scanner (tfrecord_reader.cpp).

Builds the shared library on first use if g++ is available (a one-second
build — the reference spent ~80 minutes building its native stack,
README.md:23-24); falls back cleanly to the pure-Python codec otherwise.
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

import numpy as np

_DIR = Path(__file__).parent
_LIB_PATH = _DIR / "libthb_tfrecord.so"
_lib = None
_build_failed = False


def _build_and_load(lib_path: Path) -> ctypes.CDLL | None:
    """make the specific target (so one library failing to build — e.g.
    missing libjpeg headers — never disables the others), then dlopen."""
    if not lib_path.exists():
        try:
            subprocess.run(
                ["make", "-s", "-C", str(_DIR), lib_path.name],
                check=True, capture_output=True, timeout=120,
            )
        except (OSError, subprocess.SubprocessError):
            return None
    try:
        return ctypes.CDLL(str(lib_path))
    except OSError:
        return None


def _load() -> ctypes.CDLL | None:
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    lib = _build_and_load(_LIB_PATH)
    if lib is None:
        _build_failed = True
        return None
    lib.thb_crc32c.restype = ctypes.c_uint32
    lib.thb_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.thb_masked_crc32c.restype = ctypes.c_uint32
    lib.thb_masked_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.thb_index_file.restype = ctypes.c_int64
    lib.thb_index_file.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
    ]
    lib.thb_free.restype = None
    lib.thb_free.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def crc32c(data: bytes) -> int:
    lib = _load()
    if lib is None:
        from tpu_hc_bench.data import tfrecord

        return tfrecord.crc32c(data)
    return lib.thb_crc32c(data, len(data))


def index_tfrecord(
    path: str | Path, verify: bool = True
) -> tuple[np.ndarray, np.ndarray] | None:
    """(payload_offsets, lengths) for every record, or None if native
    support is unavailable.  Raises IOError on corrupt files."""
    lib = _load()
    if lib is None:
        return None
    offs = ctypes.POINTER(ctypes.c_uint64)()
    lens = ctypes.POINTER(ctypes.c_uint64)()
    n = lib.thb_index_file(
        str(path).encode(), 1 if verify else 0,
        ctypes.byref(offs), ctypes.byref(lens),
    )
    if n < 0:
        raise IOError(f"thb_index_file({path}) failed with code {n}")
    try:
        offsets = np.ctypeslib.as_array(offs, shape=(n,)).copy() if n else \
            np.empty((0,), np.uint64)
        lengths = np.ctypeslib.as_array(lens, shape=(n,)).copy() if n else \
            np.empty((0,), np.uint64)
    finally:
        if n:
            lib.thb_free(offs)
            lib.thb_free(lens)
    return offsets, lengths


def read_records_native(path: str | Path, verify: bool = True):
    """Iterate record payloads using the native index + one buffered read."""
    idx = index_tfrecord(path, verify=verify)
    if idx is None:
        return None
    offsets, lengths = idx
    data = Path(path).read_bytes()
    return [
        data[int(o) : int(o) + int(l)] for o, l in zip(offsets, lengths)
    ]


# --- native JPEG decode (jpeg_decoder.cpp; system libjpeg) ----------------

_JPEG_PATH = _DIR / "libthb_jpeg.so"
_jpeg_lib = None
_jpeg_failed = False
_jpeg_lock = threading.Lock()


def _load_jpeg() -> ctypes.CDLL | None:
    global _jpeg_lib, _jpeg_failed
    if _jpeg_lib is not None:
        return _jpeg_lib
    if _jpeg_failed:
        return None
    with _jpeg_lock:
        return _load_jpeg_locked()


def _load_jpeg_locked() -> ctypes.CDLL | None:
    """Build+dlopen under _jpeg_lock: the decode pool's first batch hits
    this from many threads at once, and a concurrent double-`make` could
    dlopen a half-written .so and latch _jpeg_failed permanently."""
    global _jpeg_lib, _jpeg_failed
    if _jpeg_lib is not None:        # raced: another thread finished first
        return _jpeg_lib
    if _jpeg_failed:
        return None
    lib = _build_and_load(_JPEG_PATH)
    if lib is None:
        _jpeg_failed = True
        return None
    lib.thb_jpeg_dims.restype = ctypes.c_int
    lib.thb_jpeg_dims.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
    ]
    lib.thb_decode_crop_resize.restype = ctypes.c_int
    lib.thb_decode_crop_resize.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
    ]
    _jpeg_lib = lib
    return lib


def jpeg_available() -> bool:
    return _load_jpeg() is not None


def jpeg_dims(data: bytes) -> tuple[int, int] | None:
    """(width, height) without decoding, or None if native unavailable."""
    lib = _load_jpeg()
    if lib is None:
        return None
    w, h = ctypes.c_int(), ctypes.c_int()
    if lib.thb_jpeg_dims(data, len(data), ctypes.byref(w), ctypes.byref(h)):
        raise ValueError("thb_jpeg_dims: not a decodable JPEG")
    return w.value, h.value


def jpeg_decode_crop_resize(
    data: bytes, crop: tuple[int, int, int, int], out_size: int,
    flip: bool = False,
) -> np.ndarray | None:
    """Decode + crop (x, y, w, h) + bilinear resize to [out_size]^2 uint8
    RGB; None if native unavailable.  Raises ValueError on bad input."""
    lib = _load_jpeg()
    if lib is None:
        return None
    out = np.empty((out_size, out_size, 3), np.uint8)
    rc = lib.thb_decode_crop_resize(
        data, len(data), crop[0], crop[1], crop[2], crop[3],
        out_size, 1 if flip else 0, out.ctypes.data_as(ctypes.c_void_p),
    )
    if rc:
        raise ValueError(f"thb_decode_crop_resize failed with code {rc}")
    return out
