// Native JPEG decode + crop + bilinear resize for the ImageNet pipeline.
//
// The reference's data plane rode Intel-MKL TensorFlow's native input ops
// (SURVEY.md §2b #21-22); this is the TPU-native counterpart for the host
// side: one C call turns a JPEG byte string into a ready [size, size, 3]
// uint8 crop, skipping the PIL/Python object churn that dominates the
// pure-Python path.  Uses the system libjpeg(-turbo) and its DCT scaling
// (decode directly at 1/2, 1/4, 1/8 resolution when the target is small —
// most of the speedup on large ImageNet photos).
//
// C ABI (ctypes, like tfrecord_reader.cpp):
//   thb_jpeg_dims(buf, len, &w, &h)            -> 0 on success
//   thb_decode_crop_resize(buf, len, cx, cy, cw, ch, out_size, flip, out)
//       decode, crop [cx, cy, cw, ch] (full-resolution coordinates),
//       bilinear-resize to [out_size, out_size, 3], optional horizontal
//       flip; out must hold out_size*out_size*3 bytes.  -> 0 on success.
//
// Build: `make -C tpu_hc_bench/native` (adds -ljpeg).

#include <csetjmp>
#include <cstdint>
#include <cstdio>   // jpeglib.h needs FILE declared first
#include <cstring>
#include <vector>

#include <jpeglib.h>

namespace {

struct ErrMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void on_error(j_common_ptr cinfo) {
  ErrMgr* err = reinterpret_cast<ErrMgr*>(cinfo->err);
  longjmp(err->jump, 1);
}

// Decode `buf` to RGB.  Picks the largest libjpeg DCT scale denominator in
// {1, 2, 4, 8} that keeps the decoded crop at least `min_crop` pixels on
// both axes (0 disables scaling).  Returns false on any libjpeg error.
bool decode_rgb(const uint8_t* buf, size_t len, int min_crop_w,
                int min_crop_h, int full_cw, int full_ch,
                std::vector<uint8_t>& pixels, int& w, int& h, int& denom) {
  jpeg_decompress_struct cinfo;
  ErrMgr err;
  cinfo.err = jpeg_std_error(&err.pub);
  err.pub.error_exit = on_error;
  if (setjmp(err.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  denom = 1;
  if (min_crop_w > 0 && min_crop_h > 0) {
    for (int d = 2; d <= 8; d *= 2) {
      if (full_cw / d >= min_crop_w && full_ch / d >= min_crop_h) denom = d;
    }
  }
  cinfo.scale_num = 1;
  cinfo.scale_denom = denom;
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  w = cinfo.output_width;
  h = cinfo.output_height;
  pixels.resize(static_cast<size_t>(w) * h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = pixels.data() + static_cast<size_t>(cinfo.output_scanline) * w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

}  // namespace

extern "C" {

int thb_jpeg_dims(const uint8_t* buf, size_t len, int* w, int* h) {
  jpeg_decompress_struct cinfo;
  ErrMgr err;
  cinfo.err = jpeg_std_error(&err.pub);
  err.pub.error_exit = on_error;
  if (setjmp(err.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  *w = cinfo.image_width;
  *h = cinfo.image_height;
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

int thb_decode_crop_resize(const uint8_t* buf, size_t len, int cx, int cy,
                           int cw, int ch, int out_size, int flip,
                           uint8_t* out) {
  if (cw <= 0 || ch <= 0 || out_size <= 0) return 2;
  std::vector<uint8_t> pixels;
  int w = 0, h = 0, denom = 1;
  if (!decode_rgb(buf, len, out_size, out_size, cw, ch, pixels, w, h,
                  denom)) {
    return 1;
  }
  // crop coordinates in the (possibly DCT-downscaled) image
  int sx = cx / denom, sy = cy / denom;
  int sw = cw / denom, sh = ch / denom;
  if (sw < 1) sw = 1;
  if (sh < 1) sh = 1;
  if (sx + sw > w) sx = w - sw;
  if (sy + sh > h) sy = h - sh;
  if (sx < 0 || sy < 0) return 2;

  // bilinear resize crop -> out_size x out_size (align-corners=false,
  // matching PIL/TF conventions)
  const float scale_x = static_cast<float>(sw) / out_size;
  const float scale_y = static_cast<float>(sh) / out_size;
  for (int oy = 0; oy < out_size; ++oy) {
    float fy = (oy + 0.5f) * scale_y - 0.5f;
    if (fy < 0) fy = 0;
    int y0 = static_cast<int>(fy);
    if (y0 > sh - 1) y0 = sh - 1;
    int y1 = y0 + 1 > sh - 1 ? sh - 1 : y0 + 1;
    float wy = fy - y0;
    const uint8_t* row0 = pixels.data() + (static_cast<size_t>(sy + y0) * w + sx) * 3;
    const uint8_t* row1 = pixels.data() + (static_cast<size_t>(sy + y1) * w + sx) * 3;
    for (int ox = 0; ox < out_size; ++ox) {
      float fx = (ox + 0.5f) * scale_x - 0.5f;
      if (fx < 0) fx = 0;
      int x0 = static_cast<int>(fx);
      if (x0 > sw - 1) x0 = sw - 1;
      int x1 = x0 + 1 > sw - 1 ? sw - 1 : x0 + 1;
      float wx = fx - x0;
      int out_x = flip ? (out_size - 1 - ox) : ox;
      uint8_t* dst = out + (static_cast<size_t>(oy) * out_size + out_x) * 3;
      for (int c = 0; c < 3; ++c) {
        float top = row0[x0 * 3 + c] * (1 - wx) + row0[x1 * 3 + c] * wx;
        float bot = row1[x0 * 3 + c] * (1 - wx) + row1[x1 * 3 + c] * wx;
        float v = top * (1 - wy) + bot * wy;
        dst[c] = static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
  return 0;
}

}  // extern "C"
