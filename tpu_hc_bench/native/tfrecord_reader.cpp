// Native TFRecord scanner: CRC32C (slicing-by-8) + record indexing.
//
// The reference's input path runs inside TensorFlow's C++ runtime (TFRecord
// reader ops + MKL pipeline, SURVEY.md §2b #21-#22).  This is the TPU-native
// framework's native data-plane piece: it scans TFRecord shards, verifies
// the masked CRC32C framing, and returns (offset, length) indices so the
// Python pipeline can slice records out of one buffer-read — removing the
// per-record Python framing/CRC cost (pure-Python CRC32C is ~1 MB/s; this
// is ~GB/s).
//
// C ABI (consumed via ctypes from tpu_hc_bench.native):
//   uint32_t thb_crc32c(const uint8_t* data, uint64_t len);
//   uint32_t thb_masked_crc32c(const uint8_t* data, uint64_t len);
//   int64_t  thb_index_file(const char* path, int verify,
//                           uint64_t** offsets, uint64_t** lengths);
//     -> record count (>=0), or -errno-style negative on error;
//        *offsets/*lengths are malloc'd arrays the caller frees with
//        thb_free.  offsets point at record *payload* start.
//   void     thb_free(void* p);

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// CRC32C, slicing-by-8
// ---------------------------------------------------------------------------

uint32_t g_table[8][256];
bool g_init = false;

void init_tables() {
  const uint32_t poly = 0x82F63B78u;  // reflected Castagnoli
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = i;
    for (int k = 0; k < 8; ++k) crc = (crc & 1) ? (crc >> 1) ^ poly : crc >> 1;
    g_table[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = g_table[0][i];
    for (int s = 1; s < 8; ++s) {
      crc = g_table[0][crc & 0xFF] ^ (crc >> 8);
      g_table[s][i] = crc;
    }
  }
  g_init = true;
}

inline uint32_t crc32c_impl(const uint8_t* p, uint64_t len) {
  if (!g_init) init_tables();
  uint32_t crc = 0xFFFFFFFFu;
  while (len >= 8) {
    uint64_t chunk;
    memcpy(&chunk, p, 8);
    crc ^= static_cast<uint32_t>(chunk);
    uint32_t hi = static_cast<uint32_t>(chunk >> 32);
    crc = g_table[7][crc & 0xFF] ^ g_table[6][(crc >> 8) & 0xFF] ^
          g_table[5][(crc >> 16) & 0xFF] ^ g_table[4][crc >> 24] ^
          g_table[3][hi & 0xFF] ^ g_table[2][(hi >> 8) & 0xFF] ^
          g_table[1][(hi >> 16) & 0xFF] ^ g_table[0][hi >> 24];
    p += 8;
    len -= 8;
  }
  while (len--) crc = g_table[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

inline uint32_t mask_crc(uint32_t crc) {
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

}  // namespace

extern "C" {

uint32_t thb_crc32c(const uint8_t* data, uint64_t len) {
  return crc32c_impl(data, len);
}

uint32_t thb_masked_crc32c(const uint8_t* data, uint64_t len) {
  return mask_crc(crc32c_impl(data, len));
}

int64_t thb_index_file(const char* path, int verify, uint64_t** offsets_out,
                       uint64_t** lengths_out) {
  FILE* f = fopen(path, "rb");
  if (!f) return -static_cast<int64_t>(errno ? errno : 1);

  std::vector<uint64_t> offsets, lengths;
  std::vector<uint8_t> buf;
  uint64_t pos = 0;
  int64_t err = 0;

  for (;;) {
    uint8_t header[12];
    size_t got = fread(header, 1, 12, f);
    if (got == 0) break;  // clean EOF
    if (got < 12) { err = -EIO; break; }
    uint64_t length;
    uint32_t len_crc;
    memcpy(&length, header, 8);
    memcpy(&len_crc, header + 8, 4);
    if (verify && mask_crc(crc32c_impl(header, 8)) != len_crc) {
      err = -EBADMSG; break;
    }
    uint64_t payload_off = pos + 12;
    if (verify) {
      buf.resize(length);
      if (fread(buf.data(), 1, length, f) != length) { err = -EIO; break; }
      uint32_t data_crc;
      if (fread(&data_crc, 1, 4, f) != 4) { err = -EIO; break; }
      if (mask_crc(crc32c_impl(buf.data(), length)) != data_crc) {
        err = -EBADMSG; break;
      }
    } else {
      if (fseek(f, static_cast<long>(length) + 4, SEEK_CUR) != 0) {
        err = -EIO; break;
      }
    }
    offsets.push_back(payload_off);
    lengths.push_back(length);
    pos = payload_off + length + 4;
  }
  fclose(f);
  if (err) return err;

  auto* off = static_cast<uint64_t*>(malloc(offsets.size() * sizeof(uint64_t)));
  auto* len = static_cast<uint64_t*>(malloc(lengths.size() * sizeof(uint64_t)));
  if ((!off || !len) && !offsets.empty()) {
    free(off); free(len);
    return -ENOMEM;
  }
  memcpy(off, offsets.data(), offsets.size() * sizeof(uint64_t));
  memcpy(len, lengths.data(), lengths.size() * sizeof(uint64_t));
  *offsets_out = off;
  *lengths_out = len;
  return static_cast<int64_t>(offsets.size());
}

void thb_free(void* p) { free(p); }

}  // extern "C"
