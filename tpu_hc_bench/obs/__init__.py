"""Runtime observability: traces, metrics, goodput, fleet, efficiency.

The runtime counterpart of the static ``tpu_hc_bench.analysis`` package.
Where ``analysis`` inspects the *compiled program* (HLO, jaxpr),
``obs`` inspects *runs*:

- ``obs.trace`` — reusable perfetto-trace analysis promoted out of the
  one-off experiment scripts (``scripts/exp_vit_trace.py``,
  ``scripts/exp_moe_trace_r05.py``): leaf-op extraction with the
  same-tid containment rule, op classification, per-step timeline
  reconstruction, and compute/collective/host-transfer/idle-bubble
  bucket attribution.
- ``obs.metrics`` — the per-run artifact: a ``metrics.jsonl`` stream of
  windowed measurements plus a ``manifest.json`` (resolved flags, mesh
  shape, world size, versions, git sha) written next to it, so every
  benchmark run leaves something machine-readable behind.
- ``obs.goodput`` — the wall-clock ledger: driver phase transitions
  (init/compile/step/data_wait/checkpoint/rewind_replay/...) folded,
  with resilience events counted as wasted work, into a goodput
  fraction and per-category breakdown.
- ``obs.fleet`` — per-host heartbeat files (``metrics.<k>.jsonl``,
  every process writes its own) and clock-free straggler skew from a
  sync-window progress allgather.
- ``obs.efficiency`` — measured MFU (``compiled.cost_analysis()`` of
  the actual step program, source-labeled against the analytic table)
  and achieved-collective-bandwidth attribution against a measured
  fabric ceiling (``microbench.osu --json`` sweeps).
- ``obs.memory`` — measured device memory: the AOT
  ``compiled.memory_analysis()`` report cross-checked against an
  analytic params+opt+batch table, a per-sync-window HBM ledger whose
  high-water mark is attributed to the goodput phase that set it, OOM/
  emergency forensics (``memory_dump.json``), and the ``--hbm_budget``
  pre-run check.
- ``obs.timeline`` — the always-on host flight recorder: a bounded
  preallocated span ring every lane records into (train driver, data
  service, serve engine, checkpoint), persisted per rank as
  ``spans.<k>.jsonl``, merged cross-rank (heartbeat clock alignment)
  into Chrome-trace JSON, and dumped as ``timeline_dump.json`` by the
  watchdog/OOM/preemption paths — the time forensics twin of
  ``memory_dump.json``.
- ``obs.regress`` — the noise-aware regression gate: a fresh BENCH
  record vs the median/MAD of the matching-config-fingerprint history,
  direction-aware per metric (throughput down, p99/HBM up).
- ``obs.requests`` — the per-request lifecycle ledger (serving lane):
  every request's e2e decomposed into conserved components
  (queue_wait / prefill / decode_active / decode_stall /
  retire_overhead) stamped by the engine, the slowest-decile tail
  attribution (``summarize`` names where the p99 lives, ``diff``
  renders component deltas, ``regress`` gates on attribution shift),
  per-bucket occupancy folds, and per-request Chrome-trace lanes
  merged into the ``timeline`` view.
- ``obs.kv`` — the KV-pool utilization ledger (serving lane):
  ``kv_pool_util`` (written-page-seconds / reserved-page-seconds) from
  the engine's periodic pool snapshots, the per-request reservation
  honesty gap (``pages_reserved`` vs ``pages_final`` at retirement),
  the r20 ``queue_wait`` component's cause split (``pool_starved`` vs
  ``batch_full`` — WHICH resource gated the tail), and the pool
  occupancy counter track merged into the ``timeline`` view.
- ``python -m tpu_hc_bench.obs`` — ``summarize`` renders either
  artifact kind (a metrics run or a raw trace directory); ``diff``
  compares two runs at bucket/metric granularity, so a regression
  reads "collective +40%, compute flat" instead of a single throughput
  delta; ``watch`` tails a live run in place and exits when it
  completes; ``timeline`` writes the merged cross-rank Chrome trace;
  ``regress`` runs the history gate (exit 1 on a real regression).
"""

from tpu_hc_bench.obs import metrics, trace  # noqa: F401
