"""Runtime observability: trace analysis, run metrics, summarize/diff.

The runtime counterpart of the static ``tpu_hc_bench.analysis`` package.
Where ``analysis`` inspects the *compiled program* (HLO, jaxpr),
``obs`` inspects *runs*:

- ``obs.trace`` — reusable perfetto-trace analysis promoted out of the
  one-off experiment scripts (``scripts/exp_vit_trace.py``,
  ``scripts/exp_moe_trace_r05.py``): leaf-op extraction with the
  same-tid containment rule, op classification, per-step timeline
  reconstruction, and compute/collective/host-transfer/idle-bubble
  bucket attribution.
- ``obs.metrics`` — the per-run artifact: a ``metrics.jsonl`` stream of
  windowed measurements plus a ``manifest.json`` (resolved flags, mesh
  shape, world size, versions, git sha) written next to it, so every
  benchmark run leaves something machine-readable behind.
- ``python -m tpu_hc_bench.obs`` — ``summarize`` renders either
  artifact kind (a metrics run or a raw trace directory);
  ``diff`` compares two runs at bucket/metric granularity, so a
  regression reads "collective +40%, compute flat" instead of a single
  throughput delta.
"""

from tpu_hc_bench.obs import metrics, trace  # noqa: F401
