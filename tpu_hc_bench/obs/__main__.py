"""CLI: ``python -m tpu_hc_bench.obs`` — summarize / diff run artifacts.

Examples::

    # render a metrics run (dir with metrics.jsonl + manifest.json)
    python -m tpu_hc_bench.obs summarize /runs/r50_bs128

    # render a raw jax.profiler trace directory
    python -m tpu_hc_bench.obs summarize /tmp/vit_trace_vit_b16_64

    # bucket-level regression view between two runs:
    # "collective +40%, compute flat" instead of one throughput delta
    python -m tpu_hc_bench.obs diff /runs/before /runs/after

Both subcommands are pure file operations — no jax backend is touched,
so artifacts copied off a TPU VM diff fine on a laptop.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

from tpu_hc_bench.obs import metrics as metrics_mod
from tpu_hc_bench.obs import trace as trace_mod


def _kind(path: str) -> str:
    """Autodetect an artifact path: 'metrics' run or raw 'trace' dir."""
    if os.path.isfile(path):
        # direct files: a perfetto trace (compressed or gunzipped for
        # inspection — load_events handles both) vs a metrics jsonl
        name = os.path.basename(path)
        return "trace" if (name.endswith(".gz")
                           or ".trace.json" in name) else "metrics"
    if os.path.isfile(os.path.join(path, metrics_mod.METRICS_NAME)):
        return "metrics"
    if glob.glob(f"{path}/**/*.trace.json.gz", recursive=True):
        return "trace"
    raise FileNotFoundError(
        f"{path}: neither a metrics run (no {metrics_mod.METRICS_NAME}) "
        "nor a trace dir (no *.trace.json.gz)")


def _summarize(path: str, out) -> int:
    if _kind(path) == "metrics":
        lines = metrics_mod.summarize_run(path)
    else:
        summary = trace_mod.summarize_trace_dir(path)
        lines = trace_mod.format_summary(summary, title=f"trace {path}")
    print("\n".join(lines), file=out)
    return 0


def _diff(path_a: str, path_b: str, out) -> int:
    kind_a, kind_b = _kind(path_a), _kind(path_b)
    if kind_a != kind_b:
        print(f"cannot diff a {kind_a} run against a {kind_b} run",
              file=sys.stderr)
        return 2
    if kind_a == "metrics":
        lines = metrics_mod.diff_runs(path_a, path_b)
    else:
        a = trace_mod.summarize_trace_dir(path_a)
        b = trace_mod.summarize_trace_dir(path_b)
        lines = [f"trace diff: {path_a} -> {path_b}"]
        lines.extend(trace_mod.diff_buckets(a.totals, b.totals))
    print("\n".join(lines), file=out)
    return 0


def main(argv: list[str] | None = None, out=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_hc_bench.obs",
        description="summarize/diff benchmark-run artifacts "
                    "(metrics runs or jax.profiler trace dirs)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("summarize",
                       help="render one run (metrics dir/jsonl or trace dir)")
    s.add_argument("path")
    d = sub.add_parser("diff",
                       help="per-bucket/per-metric deltas between two runs")
    d.add_argument("run_a")
    d.add_argument("run_b")
    args = ap.parse_args(argv)
    out = out or sys.stdout
    if args.cmd == "summarize":
        return _summarize(args.path, out)
    return _diff(args.run_a, args.run_b, out)


if __name__ == "__main__":
    raise SystemExit(main())
