"""CLI: ``python -m tpu_hc_bench.obs`` — summarize / diff / watch /
timeline / signals / regress.

Examples::

    # render a metrics run (dir with metrics.jsonl + manifest.json)
    python -m tpu_hc_bench.obs summarize /runs/r50_bs128

    # merge every rank's flight-recorder spans into ONE aligned
    # Chrome-trace file (open in chrome://tracing or Perfetto)
    python -m tpu_hc_bench.obs timeline /runs/r50_bs128

    # noise-aware regression gate: fresh BENCH json vs the history's
    # median/MAD per config fingerprint (exit 1 on a real regression)
    python -m tpu_hc_bench.obs regress BENCH_fresh.json \
        --history 'BENCH_*.json'

    # ... judging collective bandwidth against a measured fabric sweep
    python -m tpu_hc_bench.obs summarize /runs/r50_bs128 \
        --fabric_ceiling /runs/osu_sweep.json

    # render a raw jax.profiler trace directory
    python -m tpu_hc_bench.obs summarize /tmp/vit_trace_vit_b16_64

    # bucket-level regression view between two runs:
    # "collective +40%, compute flat" instead of one throughput delta
    python -m tpu_hc_bench.obs diff /runs/before /runs/after

    # live tail of a running (or finished) benchmark
    python -m tpu_hc_bench.obs watch /runs/r50_bs128

    # health signals: recorded signals.jsonl + an offline hysteresis
    # re-evaluation of the stream (exit 1 when anything fired)
    python -m tpu_hc_bench.obs signals /runs/r50_serve

All subcommands are pure file operations — no jax backend is touched,
so artifacts copied off a TPU VM render fine on a laptop.

Exit codes: 0 clean; 1 degraded run dir (rendered what survived — a
missing manifest.json or a truncated jsonl tail, each reported as one
WARNING line on stderr) or ``watch --timeout`` expiry; 2 unusable
input (no metrics stream/trace at the path — one-line error, no
traceback).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from tpu_hc_bench.obs import metrics as metrics_mod
from tpu_hc_bench.obs import trace as trace_mod


def _kind(path: str) -> str:
    """Autodetect an artifact path: 'metrics' run or raw 'trace' dir."""
    if os.path.isfile(path):
        # direct files: a perfetto trace (compressed or gunzipped for
        # inspection — load_events handles both) vs a metrics jsonl
        name = os.path.basename(path)
        return "trace" if (name.endswith(".gz")
                           or ".trace.json" in name) else "metrics"
    if os.path.isfile(os.path.join(path, metrics_mod.METRICS_NAME)):
        return "metrics"
    if glob.glob(f"{path}/**/*.trace.json.gz", recursive=True):
        return "trace"
    raise FileNotFoundError(
        f"{path}: neither a metrics run (no {metrics_mod.METRICS_NAME}) "
        "nor a trace dir (no *.trace.json.gz)")


def _report_problems(problems: list[str]) -> int:
    for p in problems:
        print(f"WARNING: {p}", file=sys.stderr)
    return 1 if problems else 0


def _summarize(path: str, out, fabric_ceiling: str | None = None) -> int:
    if _kind(path) == "metrics":
        problems: list[str] = []
        lines = metrics_mod.summarize_run(path, fabric_ceiling=fabric_ceiling,
                                          problems=problems)
        print("\n".join(lines), file=out)
        return _report_problems(problems)
    summary = trace_mod.summarize_trace_dir(path)
    lines = trace_mod.format_summary(summary, title=f"trace {path}")
    if fabric_ceiling:
        # never drop a flag silently: ceiling attribution needs the
        # metrics run's step times and byte accounting, which a raw
        # trace dir does not carry
        lines.append(
            "fabric ceiling: --fabric_ceiling applies to metrics runs "
            "(needs wall step times + allreduce bytes); pass the "
            "--metrics_dir artifact instead of the raw trace dir")
    print("\n".join(lines), file=out)
    return 0


def _diff(path_a: str, path_b: str, out) -> int:
    kind_a, kind_b = _kind(path_a), _kind(path_b)
    if kind_a != kind_b:
        print(f"cannot diff a {kind_a} run against a {kind_b} run",
              file=sys.stderr)
        return 2
    if kind_a == "metrics":
        problems: list[str] = []
        lines = metrics_mod.diff_runs(path_a, path_b, problems=problems)
        print("\n".join(lines), file=out)
        return _report_problems(problems)
    a = trace_mod.summarize_trace_dir(path_a)
    b = trace_mod.summarize_trace_dir(path_b)
    lines = [f"trace diff: {path_a} -> {path_b}"]
    lines.extend(trace_mod.diff_buckets(a.totals, b.totals))
    print("\n".join(lines), file=out)
    return 0


def main(argv: list[str] | None = None, out=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_hc_bench.obs",
        description="summarize/diff/watch benchmark-run artifacts "
                    "(metrics runs or jax.profiler trace dirs)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("summarize",
                       help="render one run (metrics dir/jsonl or trace dir)")
    s.add_argument("path")
    s.add_argument("--fabric_ceiling", metavar="SWEEP_JSON", default=None,
                   help="osu sweep export (microbench.osu --json): adds "
                        "per-collective %%-of-measured-ceiling lines")
    d = sub.add_parser("diff",
                       help="per-bucket/per-metric deltas between two runs")
    d.add_argument("run_a")
    d.add_argument("run_b")
    w = sub.add_parser("watch",
                       help="live tail: step rate, goodput, MFU, last "
                            "resilience event; exits when the run does")
    w.add_argument("path")
    w.add_argument("--interval", type=float, default=1.0,
                   help="poll/refresh period, seconds (default 1)")
    w.add_argument("--timeout", type=float, default=None,
                   help="give up (exit 1) after this many seconds")
    w.add_argument("--no-follow", dest="follow", action="store_false",
                   help="render one snapshot and exit")
    t = sub.add_parser("timeline",
                       help="merge every rank's flight-recorder spans "
                            "(spans.<k>.jsonl) into one clock-aligned "
                            "Chrome-trace JSON")
    t.add_argument("run_dir")
    t.add_argument("-o", "--out", default=None, metavar="TRACE_JSON",
                   help="output path (default <run_dir>/"
                        "timeline.trace.json)")
    g = sub.add_parser("signals",
                       help="health signals: the run's recorded "
                            "signals.jsonl plus an offline hysteresis "
                            "re-evaluation of the stream; exit 1 when "
                            "anything fired")
    g.add_argument("path")
    g.add_argument("--window_s", type=float, default=None,
                   help="evaluation window seconds (default: completion "
                        "span / 8, the burn-rate convention)")
    g.add_argument("--json", action="store_true",
                   help="emit the raw event list as JSON instead of "
                        "the rendered report")
    r = sub.add_parser("regress",
                       help="noise-aware regression gate: a fresh BENCH "
                            "json vs the history's median/MAD per config "
                            "fingerprint; exit 1 on regression")
    r.add_argument("fresh", help="fresh BENCH json (bare record or the "
                                 "harness {'parsed': ...} wrapper)")
    r.add_argument("--history", nargs="+", default=None,
                   metavar="FILE|DIR|GLOB",
                   help="history sources (default: BENCH_*.json + "
                        "artifacts/ in the cwd)")
    r.add_argument("--mad_k", type=float, default=None,
                   help="noise multiplier on the MAD-sigma (default 4)")
    r.add_argument("--rel_floor", type=float, default=None,
                   help="relative noise floor vs the median (default "
                        "0.03: a quiet history never flags <3%% jitter)")
    args = ap.parse_args(argv)
    out = out or sys.stdout
    try:
        if args.cmd == "summarize":
            return _summarize(args.path, out,
                              fabric_ceiling=args.fabric_ceiling)
        if args.cmd == "diff":
            return _diff(args.run_a, args.run_b, out)
        if args.cmd == "timeline":
            from tpu_hc_bench.obs import timeline as timeline_mod

            trace = timeline_mod.merge_chrome_trace(args.run_dir)
            path = timeline_mod.write_trace_json(
                trace, args.out or os.path.join(
                    args.run_dir, "timeline.trace.json"))
            # clock-fallback ranks merge with identity offset but must
            # be LOUD (the degraded-run-dir contract: rendered
            # survivors + WARNING on stderr + exit 1)
            warnings = trace["metadata"].get("warnings", [])
            for w in warnings:
                print(f"WARNING: {w}", file=sys.stderr)
            for ln in timeline_mod.timeline_lines(args.run_dir):
                print(ln.strip(), file=out)
            lanes = trace["metadata"].get("request_lanes", 0)
            if lanes:
                print(f"request lanes: {lanes} request(s) rendered as "
                      f"their own timeline rows (pid 'requests')",
                      file=out)
            kv_samples = trace["metadata"].get("kv_counter_samples", 0)
            if kv_samples:
                print(f"kv pool track: {kv_samples} occupancy sample(s) "
                      f"rendered as a counter track (pid 'kv pool')",
                      file=out)
            print(f"chrome trace written: {path} (open in "
                  f"chrome://tracing or https://ui.perfetto.dev)",
                  file=out)
            return 1 if warnings else 0
        if args.cmd == "signals":
            from tpu_hc_bench.obs import signals as signals_mod

            rep = signals_mod.evaluate_run(args.path,
                                           window_s=args.window_s)
            if args.json:
                print(json.dumps({"recorded": rep["recorded"],
                                  "evaluated": rep["evaluated"],
                                  "fired": rep["fired"]}), file=out)
            else:
                print("\n".join(rep["lines"]), file=out)
            return _report_problems(rep["problems"]) \
                or (1 if rep["fired"] else 0)
        if args.cmd == "regress":
            from tpu_hc_bench.obs import regress as regress_mod

            kwargs = {}
            if args.mad_k is not None:
                kwargs["mad_k"] = args.mad_k
            if args.rel_floor is not None:
                kwargs["rel_floor"] = args.rel_floor
            return regress_mod.run_regress(args.fresh, args.history,
                                           out=out, **kwargs)
        from tpu_hc_bench.obs import watch as watch_mod

        return watch_mod.watch(args.path, out=out, interval=args.interval,
                               timeout_s=args.timeout, follow=args.follow)
    except (FileNotFoundError, json.JSONDecodeError, ValueError,
            RuntimeError) as e:
        # a missing/garbage artifact gets ONE clear line and a distinct
        # exit code, not a traceback — this CLI meets operators mid-
        # incident, exactly when run dirs are least likely to be whole
        print(f"error: {e}", file=sys.stderr)
        return 2
    except KeyboardInterrupt:
        return 130


if __name__ == "__main__":
    raise SystemExit(main())
