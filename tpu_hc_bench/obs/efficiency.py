"""Honest efficiency accounting: measured MFU + fabric-ceiling attribution.

Two dishonesties this module removes from the headline numbers:

- **MFU from a hand-maintained FLOP table.**  ``spec.flops_per_example``
  is a curated constant (2*MACs at the canonical shape) times a 3x
  fwd+bwd multiplier — fine until the table rots or a model variant
  (seq-len override, MoE capacity, remat recompute) drifts from it.
  ``measured_step_flops`` asks XLA instead: the already-built step
  function is AOT-lowered and compiled, and ``compiled.cost_analysis()``
  returns the per-device FLOPs of the *exact program the run executes*.
  The driver reports MFU from the measured figure when available,
  labels the source, and prints both when they disagree by >10% —
  the table cross-check that keeps the registry honest.

- **Collective bandwidth judged against datasheet numbers.**  The only
  ceiling that matters is the one THIS fabric measured:
  ``python -m tpu_hc_bench.microbench.osu --op all --json sweep.json``
  saves the OSU-style sweep, and ``--fabric_ceiling=sweep.json`` lets
  the driver/``summarize`` compare the achieved gradient-allreduce bus
  bandwidth against the sweep's peak — "all_reduce at 61% of measured
  ceiling" instead of a context-free GB/s.

Achieved bandwidth derivation (documented because every term matters):
collective seconds/step = (trace collective bucket / trace total,
including idle) x the *wall-measured* mean step time — the trace
supplies only the RATIO, so the unknown constant scale of
tunneled-platform trace timestamps cancels (obs.trace docstring);
bytes/step for the gradient allreduce = the gradient tree's bytes at
the wire dtype (bf16 when ``--accum_dtype=bf16`` keeps the tree bf16
through the allreduce); busbw = algbw * 2*(n-1)/n, the same ring
convention as ``microbench.osu``, so achieved and ceiling are
comparable by construction.
"""

from __future__ import annotations

import json
import os

# trace collective-leaf substrings -> microbench.osu sweep op names
KIND_TO_SWEEP_OP = (
    ("all-reduce", "allreduce"),
    ("allreduce", "allreduce"),
    ("reduce-scatter", "reduce_scatter"),
    ("all-gather", "all_gather"),
    ("allgather", "all_gather"),
    ("all-to-all", "all_to_all"),
    ("permute", "ppermute"),
)


# ---------------------------------------------------------------------
# measured FLOPs (needs jax; driver-side only)


def _abstractify(x):
    import jax

    if hasattr(x, "shape") and hasattr(x, "dtype"):
        # carry the committed sharding where one exists (the GSPMD TP
        # arm follows input shardings — an unsharded abstract value
        # would lower a different program than the run executes)
        sharding = getattr(x, "sharding", None)
        try:
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=sharding)
        except TypeError:
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
    return x


def flops_of_compiled(compiled) -> float | None:
    """The ``flops`` entry of ``compiled.cost_analysis()``, tolerant of
    the cross-version return shapes (dict on modern jax, list-of-dicts
    per device on 0.4.x, None where the backend has no analysis)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = ca.get("flops")
    if flops is None or float(flops) <= 0:
        return None
    return float(flops)


def _probe_handles(step_fn, example_args):
    """``(jitted, abstract_args)`` for the FLOPs probe, or None.

    ``step_fn`` must expose its underlying jitted callable as
    ``_jitted`` (``train.step`` builders attach it); the example args
    are abstracted to ShapeDtypeStructs so donated or already-consumed
    buffers are never touched and nothing executes."""
    import jax

    jitted = getattr(step_fn, "_jitted", None)
    if jitted is None:
        return None
    try:
        return jitted, jax.tree.map(_abstractify, example_args)
    except Exception:
        return None


def aot_compile(jitted, *example_args):
    """AOT lower+compile a jitted callable at abstracted argument shapes.

    The ONE lowering path shared by the FLOPs/memory probe and the
    serving engine's bucket warmup (``tpu_hc_bench.serve.engine``): the
    example args are abstracted to ShapeDtypeStructs (committed
    shardings carried, donated/consumed buffers never touched, nothing
    executes), then ``jitted.lower(...).compile()`` produces the
    executable.  Because the result is an AOT ``Compiled`` handle, a
    call at any OTHER shape raises instead of silently recompiling —
    the property the serving lane's zero-recompile-after-warmup
    contract is built on.  Raises on lowering failure (callers that
    want the probe's None-degradation use ``_lowered_compiled``).
    """
    import jax

    abstract = jax.tree.map(_abstractify, example_args)
    return jitted.lower(*abstract).compile()


def _lowered_compiled(jitted, abstract):
    try:
        return jitted.lower(*abstract).compile()
    except Exception:
        return None


def _lowered_flops(jitted, abstract) -> float | None:
    compiled = _lowered_compiled(jitted, abstract)
    if compiled is None:
        return None
    return flops_of_compiled(compiled)


def measured_step_flops(step_fn, *example_args) -> float | None:
    """Per-device per-step FLOPs of the compiled step, or None.

    Cost: one extra (cached where the stack supports it) compile —
    which is why the driver only probes on observability-enabled runs
    (and there through the background ``StepFlopsProbe``).
    """
    handles = _probe_handles(step_fn, example_args)
    if handles is None:
        return None
    return _lowered_flops(*handles)


class StepFlopsProbe:
    """``measured_step_flops`` (+ the AOT memory analysis) on a
    background thread.

    The probe's AOT lower+compile is pure telemetry — nothing the step
    loop depends on — so billing it to the ledger's compile phase was
    pure latency (round 10).  The example args are abstracted to
    ShapeDtypeStructs on the CALLING thread (so no device buffer
    outlives the handoff and donated args are never touched), then the
    lower+compile+cost_analysis runs on a daemon thread, overlapped
    with the timed loop; ``result()`` joins and returns the per-device
    FLOPs (None on any failure — same degradation contract as the
    synchronous probe).

    The SAME compiled handle also answers ``memory_analysis()`` —
    the argument/output/temp bytes of the step program (round 15,
    ``obs.memory``): one compile serves both probes.

    ``background=False`` runs the compile on the calling thread
    instead: ``--hbm_budget`` needs the memory report BEFORE the
    warmup pays for the full run's compile, and a budget check that
    joins after the timed loop would defeat its purpose.
    """

    def __init__(self, step_fn, *example_args, background: bool = True):
        self._flops: float | None = None
        self._memory: dict | None = None
        self._thread = None
        handles = _probe_handles(step_fn, example_args)
        if handles is None:
            return

        def _run():
            from tpu_hc_bench.obs import memory as memory_mod

            compiled = _lowered_compiled(*handles)
            if compiled is None:
                return
            self._flops = flops_of_compiled(compiled)
            self._memory = memory_mod.memory_analysis_of_compiled(compiled)

        if not background:
            _run()
            return
        import threading

        self._thread = threading.Thread(
            target=_run, name="tpu-hc-bench-flops-probe", daemon=True)
        self._thread.start()

    def _join(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def result(self) -> float | None:
        self._join()
        return self._flops

    def memory_analysis(self) -> dict | None:
        """The step program's AOT byte accounting (obs.memory record
        shape), or None where the backend has no analysis."""
        self._join()
        return self._memory


def grad_allreduce_bytes(params, accum_dtype: str = "f32") -> int:
    """Per-device message bytes of the gradient allreduce: the gradient
    tree matches the param tree leaf-for-leaf; ``--accum_dtype=bf16``
    keeps the tree bf16 through the allreduce (train.step), halving the
    wire bytes."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(params):
        if not hasattr(leaf, "size"):
            continue
        itemsize = 2 if accum_dtype == "bf16" else getattr(
            leaf.dtype, "itemsize", 4)
        total += int(leaf.size) * itemsize
    return total


# ---------------------------------------------------------------------
# MFU bookkeeping (pure)


def mfu_report(measured_flops_per_step: float | None,
               analytic_flops_per_step: float,
               mean_step_s: float, peak_flops: float) -> dict:
    """The honest MFU record: value, source label, both FLOP figures,
    and the disagreement flag (>10% — the table-rot tripwire)."""
    denom = mean_step_s * peak_flops
    mfu_analytic = analytic_flops_per_step / denom if denom > 0 else 0.0
    out = {
        "mfu": mfu_analytic,
        "mfu_source": "analytic",
        "mfu_analytic": mfu_analytic,
        "analytic_flops_per_step": analytic_flops_per_step,
    }
    if measured_flops_per_step is not None and denom > 0:
        mfu_measured = measured_flops_per_step / denom
        out.update(mfu=mfu_measured, mfu_source="measured",
                   mfu_measured=mfu_measured,
                   measured_flops_per_step=measured_flops_per_step)
        if analytic_flops_per_step > 0:
            rel = abs(measured_flops_per_step - analytic_flops_per_step) \
                / analytic_flops_per_step
            out["flops_disagreement"] = rel
            out["flops_disagree"] = rel > 0.10
    return out


def mfu_lines(summary: dict) -> list[str]:
    """Render the MFU-source attribution from a summary record (shared
    by the driver's final print and ``obs summarize``)."""
    src = summary.get("mfu_source")
    if not src:
        return []
    lines = [f"  MFU {100 * summary.get('mfu', 0.0):.1f}% "
             f"(flops source: {src})"]
    if summary.get("flops_disagree"):
        lines.append(
            f"  WARNING: measured vs analytic FLOPs disagree "
            f"{summary.get('flops_disagreement', 0.0):.0%}: measured "
            f"{summary.get('measured_flops_per_step', 0.0):.3g} vs "
            f"analytic {summary.get('analytic_flops_per_step', 0.0):.3g} "
            f"flops/step — spec.flops_per_example may have rotted")
    return lines


# ---------------------------------------------------------------------
# fabric ceiling (pure file ops; the sweep json is written by
# `python -m tpu_hc_bench.microbench.osu --json`)


def load_fabric_ceiling(path: str) -> dict:
    """Load an osu sweep export; returns ``{"world_size", "device_kind",
    "ceilings": {op: {"busbw_gbps", "message_bytes"}}}`` where each
    op's ceiling is its best measured busbw over the swept sizes."""
    if not os.path.isfile(path):
        raise FileNotFoundError(f"--fabric_ceiling: no such file: {path}")
    with open(path) as f:
        data = json.load(f)
    sweeps = data.get("sweeps")
    if not isinstance(sweeps, dict) or not sweeps:
        raise ValueError(
            f"--fabric_ceiling: {path} is not an osu sweep export "
            f"(write one with `python -m tpu_hc_bench.microbench.osu "
            f"--op all --json {path}`)")
    ceilings = {}
    for op, rows in sweeps.items():
        best = max(rows, key=lambda r: r.get("busbw_gbps", 0.0),
                   default=None)
        if best:
            ceilings[op] = {"busbw_gbps": float(best["busbw_gbps"]),
                            "message_bytes": int(best["message_bytes"])}
    return {"world_size": data.get("world_size"),
            "device_kind": data.get("device_kind"),
            "ceilings": ceilings}


def _merge_intervals(
    intervals: list[tuple[float, float]]) -> list[tuple[float, float]]:
    """Sorted disjoint union of [start, end) intervals."""
    out: list[tuple[float, float]] = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _intersection_len(a: list[tuple[float, float]],
                      b: list[tuple[float, float]]) -> float:
    """Total overlap length of two sorted disjoint interval lists."""
    total = 0.0
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def collective_overlap(
        intervals: list[tuple[str, float, float]]) -> dict | None:
    """Overlapped-vs-exposed collective attribution from trace intervals.

    ``intervals`` is ``obs.trace.leaf_intervals``'s output.  *Exposed*
    collective wall is the part of the collective-busy span no
    compute/host-transfer op covers concurrently (a sibling track's DMA
    or MXU work hides a collective; a collective running alone is pure
    step-time cost).  This is the measurement behind
    ``--overlap_grad_comm``: the flag's win is exposed fraction going
    DOWN while total collective time stays ~flat.  Same ratio-only
    trust contract as every trace consumer (obs.trace docstring).
    Returns None when the trace has no collective ops.
    """
    from tpu_hc_bench.obs import trace as trace_mod

    coll: list[tuple[float, float]] = []
    comp: list[tuple[float, float]] = []
    for name, s, e in intervals:
        if e <= s:
            continue
        if trace_mod.bucket_of(name) == "collective":
            coll.append((s, e))
        else:
            comp.append((s, e))
    if not coll:
        return None
    coll_u = _merge_intervals(coll)
    comp_u = _merge_intervals(comp)
    total = sum(e - s for s, e in coll_u)
    covered = _intersection_len(coll_u, comp_u)
    exposed = max(0.0, total - covered)
    frac = exposed / total if total > 0 else 0.0
    return {
        "collective_us": total,
        "exposed_us": exposed,
        "exposed_frac": frac,
        "overlapped_frac": 1.0 - frac,
    }


def overlap_lines(rec: dict) -> list[str]:
    """Render a ``collective_overlap`` record (driver + summarize)."""
    return [
        f"  collective exposure: {rec.get('exposed_frac', 0.0):.1%} of "
        f"collective wall exposed, {rec.get('overlapped_frac', 0.0):.1%} "
        f"overlapped with compute"
    ]


def collective_busbw_lines(summary: dict,
                           trace_rec: dict | None) -> list[str]:
    """Absolute achieved gradient-collective bus bandwidth (GB/s).

    The ceiling-free companion of ``ceiling_utilization_lines``: the
    same trace-ratio x wall-step-time x wire-bytes derivation, printed
    in absolute GB/s so a run WITHOUT a ``--fabric_ceiling`` sweep still
    reports what the fabric achieved instead of gating the number on an
    artifact the operator may not have.  The zero1 arm's reduce-scatter
    + all-gather pair is folded into the same figure (together they move
    the allreduce's ring volume over the same gradient bytes).
    """
    if not trace_rec or not trace_rec.get("buckets"):
        return []
    buckets = trace_rec["buckets"]
    total_us = sum(buckets.values())
    if total_us <= 0 or buckets.get("collective", 0.0) <= 0:
        return []
    mean_step_s = summary.get("mean_step_ms", 0.0) / 1e3
    world = int(summary.get("total_workers") or 0)
    bytes_per_step = summary.get("allreduce_bytes_per_step")
    if mean_step_s <= 0 or world <= 1 or not bytes_per_step:
        return []
    coll_ops = trace_rec.get("collective_ops") or {
        "allreduce": buckets["collective"]}
    # every gradient-carrying kind, summed: the psum arm's all-reduce
    # buckets, the zero1 arm's reduce-scatter + all-gather pair (a zero1
    # trace ALSO has a small all-reduce — the loss pmean/BN-stat sync —
    # which must not become the denominator on its own)
    grad_us = (coll_ops.get("allreduce", 0.0)
               + coll_ops.get("reduce_scatter", 0.0)
               + coll_ops.get("all_gather", 0.0))
    if grad_us <= 0:
        return []
    frac = grad_us / total_us
    sec_per_step = frac * mean_step_s
    algbw = bytes_per_step / sec_per_step / 1e9
    busbw = algbw * 2.0 * (world - 1) / world
    return [
        f"  fabric: gradient collectives {busbw:.2f} GB/s busbw "
        f"({algbw:.2f} GB/s algbw, {frac:.1%} of step time, "
        f"{bytes_per_step / 2**20:.1f} MiB/step; absolute — pass "
        f"--fabric_ceiling for %-of-measured-ceiling)"
    ]


def collective_kind_times(op_times: dict[str, float]) -> dict[str, float]:
    """Fold leaf-op durations into sweep-op kinds (all-reduce leaves of
    any fusion spelling -> "allreduce", ...)."""
    from tpu_hc_bench.obs import trace as trace_mod

    out: dict[str, float] = {}
    for name, us in op_times.items():
        if trace_mod.classify(name) != "collective":
            continue
        n = name.lower()
        for sub, op in KIND_TO_SWEEP_OP:
            if sub in n:
                out[op] = out.get(op, 0.0) + us
                break
        else:
            out["allreduce"] = out.get("allreduce", 0.0) + us
    return out


def ceiling_utilization_lines(summary: dict, trace_rec: dict | None,
                              ceiling: dict) -> list[str]:
    """Per-collective %-of-ceiling lines from run artifacts.

    ``summary``: the metrics ``summary`` record (mean_step_ms,
    total_workers, allreduce_bytes_per_step); ``trace_rec``: the
    ``trace_buckets`` record (buckets + optional ``collective_ops``
    per-kind split).  Degrades to an explanatory line when a term is
    missing rather than silently printing nothing.
    """
    if not trace_rec or not trace_rec.get("buckets"):
        return ["  fabric ceiling: no trace buckets in this run — rerun "
                "with --trace_dir/--profile_steps to attribute "
                "collective time"]
    buckets = trace_rec["buckets"]
    total_us = sum(buckets.values())
    if total_us <= 0 or buckets.get("collective", 0.0) <= 0:
        return ["  fabric ceiling: trace shows no collective time"]
    mean_step_s = summary.get("mean_step_ms", 0.0) / 1e3
    world = int(summary.get("total_workers") or 0)
    if mean_step_s <= 0 or world <= 1:
        return ["  fabric ceiling: needs a timed multi-worker summary "
                "record"]
    coll_ops = trace_rec.get("collective_ops") or {
        "allreduce": buckets["collective"]}
    bytes_per_step = summary.get("allreduce_bytes_per_step")
    cworld = ceiling.get("world_size")
    lines = []
    if cworld and cworld != world:
        lines.append(
            f"  fabric ceiling: sweep world={cworld} != run world="
            f"{world} — %-of-ceiling is indicative only")
    for op, us in sorted(coll_ops.items(), key=lambda kv: -kv[1]):
        frac = us / total_us
        sec_per_step = frac * mean_step_s
        ceil = ceiling.get("ceilings", {}).get(op)
        if ceil is None:
            lines.append(f"  fabric: {op} {frac:.1%} of step time "
                         f"(no {op} sweep in the ceiling file)")
            continue
        if op == "allreduce" and bytes_per_step and sec_per_step > 0:
            algbw = bytes_per_step / sec_per_step / 1e9
            busbw = algbw * 2.0 * (world - 1) / world
            util = busbw / ceil["busbw_gbps"] if ceil["busbw_gbps"] else 0.0
            lines.append(
                f"  fabric: {op} {busbw:.2f} GB/s busbw = {util:.0%} of "
                f"measured ceiling {ceil['busbw_gbps']:.2f} GB/s "
                f"({frac:.1%} of step time, "
                f"{bytes_per_step / 2**20:.1f} MiB/step)")
        else:
            lines.append(
                f"  fabric: {op} {frac:.1%} of step time "
                f"(ceiling {ceil['busbw_gbps']:.2f} GB/s; no byte "
                f"accounting for this collective)")
    return lines
