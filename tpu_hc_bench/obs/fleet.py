"""Fleet-wide per-host visibility: heartbeats + straggler skew.

The main ``metrics.jsonl`` is written by process 0 only (its records
are globally aggregated — ``obs.metrics`` docstring), which means a
fleet where one host is quietly 2 steps behind every sync window looks
identical to a healthy one.  Two mechanisms close that gap:

- **Heartbeats**: every process appends one compact record per sync
  window to its *own* ``metrics.<process_index>.jsonl`` next to the
  main stream — host id, last completed step, a step-duration EWMA,
  and the local devices' memory stats.  Pure appends, no coordination,
  so a wedged host's file simply stops growing (itself a signal).

- **Straggler skew**: per-host wall clocks cannot be compared (no
  trust in NTP on a preemptible fleet), so the skew measurement rides
  a collective instead: at a sync-window boundary every process
  contributes its last *completed* step to a host-level allgather.
  The collective itself is the common time reference — every value is
  sampled at the same program point — so ``max - median`` of the
  gathered steps is a clock-free lag measure, converted to
  milliseconds by the median host's step EWMA.  Process 0 writes the
  result as a ``straggler`` record into the main stream.

``read_heartbeats`` / ``straggler_lines`` are pure file operations so
``summarize`` renders fleet state from artifacts on any machine.
"""

from __future__ import annotations

import json
import os
import re
import time

_HEARTBEAT_RE = re.compile(r"^metrics\.(\d+)\.jsonl$")


def heartbeat_path(out_dir: str, process_index: int) -> str:
    return os.path.join(out_dir, f"metrics.{process_index}.jsonl")


class StepEwma:
    """Step-duration EWMA from (step, wall-time) samples at sync windows.

    ``update`` returns the current EWMA in milliseconds (0.0 until two
    samples exist).  Smoothing favors recency (alpha 0.3) so a host
    that *becomes* slow shows up within a few windows.
    """

    def __init__(self, alpha: float = 0.3):
        self.alpha = alpha
        self._last: tuple[int, float] | None = None
        self.ewma_ms = 0.0

    def update(self, step: int, now: float | None = None) -> float:
        now = time.monotonic() if now is None else now
        if self._last is not None:
            last_step, last_t = self._last
            dsteps = step - last_step
            if dsteps > 0:
                sample_ms = 1e3 * (now - last_t) / dsteps
                self.ewma_ms = (sample_ms if self.ewma_ms == 0.0 else
                                self.alpha * sample_ms
                                + (1 - self.alpha) * self.ewma_ms)
        self._last = (step, now)
        return self.ewma_ms


def _tail_record(path: str, nbytes: int = 8192) -> dict | None:
    """The newest parseable JSON record in the file's tail — heartbeat
    files grow O(run), and every per-tick/startup reader must stay
    O(1), not re-parse the whole history."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return None
    if size == 0:
        return None
    try:
        with open(path, "rb") as f:
            f.seek(max(0, size - nbytes))
            tail = f.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return None


def next_incarnation(path: str) -> int:
    """The incarnation counter the NEXT ``FleetWriter`` on this file
    will stamp: one more than the last record's (0 for a fresh file).
    Public because the fleet supervisor derives its expected
    incarnation from the SAME file tail at launch time — deriving it
    from a launch count instead would drift permanently ahead the
    first time a life dies before its first beat."""
    try:
        if os.path.getsize(path) == 0:
            return 0
    except OSError:
        return 0
    rec = _tail_record(path)
    if rec is None:
        return 1    # non-empty file with no parseable tail: a relaunch
    return int(rec.get("incarnation", 0) or 0) + 1


class FleetWriter:
    """Append-only heartbeat stream for THIS process.

    Unlike ``MetricsWriter`` every process writes (that is the point);
    disabled (no-op) when ``out_dir`` is falsy.  Each heartbeat is
    flushed immediately — the file must be readable while the run is
    live, and a killed process must not lose its last sign of life.

    The file opens in APPEND mode: an elastic resume into the same run
    dir must extend the prior life's history, not truncate it (the
    pre-round-17 ``"w"`` open silently erased every heartbeat the
    crashed incarnation left behind — exactly the forensics a resume
    postmortem needs).  Each record carries an ``incarnation`` counter
    (0 for the first life, +1 per relaunch) so readers can tell the
    lives apart, and a ``t_mono`` stamp pairing the wall clock with
    this process's monotonic clock — the span-timeline merge's
    per-rank clock-alignment source (``obs.timeline``).
    """

    def __init__(self, out_dir: str | None, process_index: int | None = None):
        self._f = None
        self.process_index = 0
        self.incarnation = 0
        if not out_dir:
            return
        if process_index is None:
            import jax

            process_index = jax.process_index()
        self.process_index = process_index
        os.makedirs(out_dir, exist_ok=True)
        path = heartbeat_path(out_dir, process_index)
        self.incarnation = next_incarnation(path)
        self._f = open(path, "a")

    @property
    def enabled(self) -> bool:
        return self._f is not None

    def heartbeat(self, step: int, step_ewma_ms: float,
                  mem_peak_bytes: int | None = None,
                  kv_peak_pages: int | None = None, **extra) -> None:
        if self._f is None:
            return
        rec = {"kind": "heartbeat", "host": self.process_index,
               "step": int(step), "step_ewma_ms": float(step_ewma_ms),
               "t_unix": time.time(), "t_mono": time.monotonic(),
               "incarnation": self.incarnation}
        if mem_peak_bytes:
            # the ONE heartbeat memory field name — readers
            # (watch/summarize) consume it via heartbeat_mem_peak
            rec["mem_peak_bytes"] = int(mem_peak_bytes)
        if kv_peak_pages:
            # serve-lane KV pool high-water (round 22) — writer and the
            # heartbeat_kv_peak reader land in the same PR, per the
            # round-15 mem_peak_bytes lesson
            rec["kv_peak_pages"] = int(kv_peak_pages)
        rec.update(extra)
        try:
            self._f.write(json.dumps(rec, default=str) + "\n")
            self._f.flush()
        except OSError:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None      # heartbeats are telemetry, never fatal

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            except OSError:
                pass
            self._f.close()
            self._f = None


def straggler_gather(step: int, ewma_ms: float) -> dict | None:
    """The device-backed allgather of per-host progress (a COLLECTIVE:
    every process must call at the same step).  Returns the straggler
    record fields, or None when the gather is unavailable."""
    import numpy as np

    import jax

    if jax.process_count() <= 1:
        host_steps = [int(step)]
        host_ewmas = [float(ewma_ms)]
    else:
        try:
            from jax.experimental import multihost_utils

            gathered = multihost_utils.process_allgather(
                np.asarray([float(step), float(ewma_ms)], np.float64))
            arr = np.asarray(gathered).reshape(jax.process_count(), 2)
            host_steps = [int(s) for s in arr[:, 0]]
            host_ewmas = [float(e) for e in arr[:, 1]]
        except Exception:
            return None
    return compute_skew(host_steps, host_ewmas)


def compute_skew(host_steps: list[int],
                 host_ewmas: list[float]) -> dict:
    """max - median host lag, in steps and (EWMA-scaled) milliseconds."""
    import statistics

    med = statistics.median(host_steps)
    skew_steps = max(host_steps) - med
    med_ewma = statistics.median(host_ewmas) if host_ewmas else 0.0
    return {
        "host_steps": host_steps,
        "skew_steps": float(skew_steps),
        "skew_ms": float(skew_steps) * med_ewma,
        "median_step_ewma_ms": med_ewma,
    }


# ---------------------------------------------------------------------
# liveness (heartbeat staleness — shared by the fleet supervisor and
# `obs watch`)

ALIVE = "ALIVE"
STALE = "STALE"
DEAD = "DEAD"

#: default staleness thresholds, in seconds of heartbeat silence.  A
#: heartbeat lands once per sync window (seconds at most), so tens of
#: seconds of silence is a wedged host, not a slow one.
STALE_AFTER_S = 15.0
DEAD_AFTER_S = 60.0


def classify_liveness(recs: list[dict], now: float | None = None,
                      stale_after_s: float = STALE_AFTER_S,
                      dead_after_s: float = DEAD_AFTER_S,
                      expect_incarnation: int | None = None) -> dict:
    """ALIVE/STALE/DEAD verdict over one rank's heartbeat records.

    The signal is the NEWEST heartbeat's wall-clock age plus its
    incarnation counter: a file whose freshest beat is older than
    ``dead_after_s`` belongs to a process that stopped beating (killed,
    hung past the watchdog, or wedged in uninterruptible I/O) — exactly
    the state the pre-round-19 ``watch`` rendered as silently-old
    numbers.  ``expect_incarnation`` (the fleet supervisor's relaunch
    counter) guards the elastic-resume window: a beat from an OLDER
    life must not count as the new life's sign of life, so it reports
    at most STALE until the expected incarnation appears.

    Returns ``{"status", "age_s", "step", "incarnation"}``; no records
    at all classify DEAD with ``age_s=None`` (a job that never beat).
    """
    now = time.time() if now is None else now
    newest = None
    for rec in recs:
        if rec.get("kind") != "heartbeat":
            continue
        if newest is None or rec.get("t_unix", 0) >= newest.get("t_unix", 0):
            newest = rec
    if newest is None:
        return {"status": DEAD, "age_s": None, "step": None,
                "incarnation": None}
    age = max(0.0, now - float(newest.get("t_unix", now)))
    inc = int(newest.get("incarnation", 0) or 0)
    if expect_incarnation is not None and inc < expect_incarnation:
        # an old life's beat: fresh-looking numbers, wrong process —
        # never ALIVE, DEAD once the old beat itself has aged out
        status = DEAD if age > dead_after_s else STALE
    elif age > dead_after_s:
        status = DEAD
    elif age > stale_after_s:
        status = STALE
    else:
        status = ALIVE
    return {"status": status, "age_s": age,
            "step": newest.get("step"), "incarnation": inc}


# ---------------------------------------------------------------------
# reading (pure file ops)


def heartbeat_mem_peak(rec: dict) -> int | None:
    """The heartbeat's device-memory peak, under the unified
    ``mem_peak_bytes`` name (round 15); falls back to the pre-unification
    ``peak_bytes_in_use`` spelling so old run dirs still render."""
    v = rec.get("mem_peak_bytes", rec.get("peak_bytes_in_use"))
    return int(v) if v else None


def heartbeat_kv_peak(rec: dict) -> int | None:
    """The serve-lane heartbeat's KV-pool high-water (``kv_peak_pages``,
    round 22); ``None`` on train-lane and pre-r22 heartbeats — readers
    render absent, never KeyError."""
    v = rec.get("kv_peak_pages")
    return int(v) if v else None


def latest_heartbeats(run_dir: str) -> dict[int, dict]:
    """Each host's NEWEST heartbeat record, by bounded tail read — the
    fleet supervisor's per-tick liveness source (``read_heartbeats``
    parses the whole history; calling that every scheduler tick would
    make the control loop's cost grow with run length)."""
    out: dict[int, dict] = {}
    try:
        names = os.listdir(run_dir)
    except OSError:
        return out
    for name in sorted(names):
        m = _HEARTBEAT_RE.match(name)
        if not m:
            continue
        rec = _tail_record(os.path.join(run_dir, name))
        if rec is not None:
            out[int(m.group(1))] = rec
    return out


def read_heartbeats(run_dir: str) -> dict[int, list[dict]]:
    """All hosts' heartbeat records, keyed by process index.  Corrupt
    lines (a heartbeat interrupted by the very death it reports) are
    skipped silently — partial fleet state beats none."""
    from tpu_hc_bench.obs.metrics import read_jsonl

    out: dict[int, list[dict]] = {}
    try:
        names = os.listdir(run_dir)
    except OSError:
        return out
    for name in sorted(names):
        m = _HEARTBEAT_RE.match(name)
        if not m:
            continue
        out[int(m.group(1))] = read_jsonl(os.path.join(run_dir, name))
    return out


def input_lines(run_dir: str | None, records: list[dict],
                ledger=None) -> list[str]:
    """The ``summarize`` input-plane account (real-data runs only):
    data_wait fraction from the goodput ledger, the input service's
    ring occupancy/stall record, and per-host ring occupancy mined from
    the heartbeats' ``input`` fields."""
    svc = [r for r in records if r.get("kind") == "input_service"]
    data = [r for r in records if r.get("kind") == "data"]
    if not svc and not data:
        return []                   # synthetic input: no input plane
    head = "  input:"
    if ledger is not None and ledger.wall_s > 0:
        dw = ledger.seconds.get("data_wait", 0.0)
        head += f" data_wait {dw / ledger.wall_s:.1%} of wall"
    if svc:
        s = svc[-1]
        depth = s.get("depth", "?")
        head += (f"  service rings occ p50 {s.get('occ_p50', 0)}/{depth} "
                 f"p99 {s.get('occ_p99', 0)}/{depth}  producer stalls "
                 f"{s.get('producer_stall_s', 0.0):.2f}s  consumer waits "
                 f"{s.get('consumer_wait_s', 0.0):.2f}s  "
                 f"({s.get('decode_workers', '?')} decode thread(s) -> "
                 f"{s.get('workers', '?')} worker(s))")
    else:
        head += " (per-process pipeline)"
    lines = [head]
    beats = read_heartbeats(run_dir) if run_dir else {}
    occ = sorted(
        rec["input"]["ring_occ"]
        for recs in beats.values() for rec in recs
        if isinstance(rec.get("input"), dict)
        and "ring_occ" in rec["input"])
    if occ:
        def pct(q):
            return occ[min(len(occ) - 1, int(q * (len(occ) - 1)))]

        lines.append(
            f"    host rings (heartbeats): occ p50 {pct(0.5)} "
            f"p99 {pct(0.99)} over {len(occ)} window(s), "
            f"{len(beats)} host(s)")
    return lines


def straggler_lines(run_dir: str, records: list[dict]) -> list[str]:
    """Fleet lines for ``summarize``: the last in-stream ``straggler``
    record (collective-sampled, clock-free) plus the per-host heartbeat
    tail (last step each host reported, EWMA, time since last beat)."""
    lines: list[str] = []
    stragglers = [r for r in records if r.get("kind") == "straggler"]
    if stragglers:
        s = stragglers[-1]
        lines.append(
            f"  straggler skew: max-median {s.get('skew_steps', 0):.0f} "
            f"step(s) (~{s.get('skew_ms', 0.0):.1f}ms) across "
            f"{len(s.get('host_steps', []))} host(s) "
            f"at step {s.get('step', '?')}")
    beats = read_heartbeats(run_dir)
    if beats:
        last = {h: recs[-1] for h, recs in beats.items() if recs}
        if last:
            steps = [r.get("step", 0) for r in last.values()]
            import statistics

            med = statistics.median(steps)
            peaks = [p for p in (heartbeat_mem_peak(r)
                                 for r in last.values()) if p]
            lines.append(
                f"  heartbeats: {len(last)} host file(s), last steps "
                f"median {med:.0f} min {min(steps)} max {max(steps)}"
                + (f", mem peak max {max(peaks) / 2**20:.1f} MiB"
                   if peaks else ""))
            laggards = [(h, r) for h, r in sorted(last.items())
                        if med - r.get("step", 0) >= 1]
            for h, r in laggards[:4]:
                lines.append(
                    f"    host{h}: step {r.get('step')} "
                    f"({med - r.get('step', 0):.0f} behind median, "
                    f"ewma {r.get('step_ewma_ms', 0.0):.1f}ms)")
    return lines
