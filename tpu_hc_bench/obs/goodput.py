"""Wall-clock goodput ledger: every second of a run attributed.

The reference harness reports raw images/sec and nothing else; a run
that spent half its wall compiling, waiting on the input pipeline, or
replaying rewound steps posts the same headline number as a clean one.
This module closes that gap with a *ledger*: the driver emits phase
transitions into the metrics stream as it moves through the run's
lifecycle, and folding those records (plus the resilience events of
``tpu_hc_bench.resilience``) yields a wall-clock account —

- ``init``           backend/layout/model/data construction
- ``compile``        the warmup loop (includes XLA compile; with
                     ``--compile_cache`` warm starts collapse this to
                     trace/lower + cache loads — the AOT cost-analysis
                     probe runs on a background thread and is not
                     billed here)
- ``step``           the timed training loop (the productive part)
- ``data_wait``      host time blocked in ``next(batch_iter)`` inside
                     the timed loop (carved out of ``step``)
- ``checkpoint``     synchronous ``--train_dir`` saves (device-syncing;
                     the full snapshot + write + commit blocks)
- ``checkpoint_async`` the BLOCKING slice of an async save: barrier on
                     the previous write + device→host snapshot; the
                     Orbax write/fsync/commit runs overlapped with the
                     step loop and never enters the ledger as blocking
                     wall (per-save ``checkpoint_commit`` records carry
                     the overlapped write seconds)
- ``rewind_replay``  ``--on_nonfinite=rewind`` restores
- ``emergency_save`` the preemption path's final checkpoint
- ``idle``           anything explicitly marked idle (none in a
                     healthy run)

plus a **goodput fraction**: productive step seconds / wall seconds,
where "productive" additionally *excludes* step time whose work was
thrown away — updates dropped by ``--on_nonfinite=skip`` and steps
lost to a rewind (both folded in from the resilience records, scaled
by the mean step time).

Record shapes (append-only, in ``metrics.jsonl``):

- ``{"kind": "phase", "phase": P, "t": monotonic_s, "step": i|null}``
  — transition INTO phase ``P``; durations come from consecutive
  transitions, so the stream stays O(transitions), not O(steps).
- ``{"kind": "phase_acc", "phase": "data_wait", "seconds": s,
  "step": i}`` — seconds accumulated *inside* the current phase and
  re-attributed to ``phase`` (the driver batches per-step data waits
  and flushes once per sync window, keeping the hot loop write-free).

The fold is pure record processing (no jax), so ``summarize`` works on
artifacts from any machine; ``PhaseTracker`` keeps a local copy of its
emissions so the driver can compute the same ledger at end-of-run
without re-reading the file.
"""

from __future__ import annotations

import dataclasses
import time

from tpu_hc_bench.obs import timeline as timeline_mod

PHASES = ("init", "compile", "step", "data_wait", "checkpoint",
          "checkpoint_async", "rewind_replay", "emergency_save", "idle")
END_PHASE = "end"


class PhaseTracker:
    """Driver-side phase state machine; emits through a MetricsWriter.

    Construction enters ``init`` immediately.  ``note_data_wait`` is a
    float add (safe in the hot loop); ``flush`` writes the accumulated
    wait once per sync window.  ``note_lost_steps`` /
    ``note_skipped_updates`` record wasted work for the local ledger
    (the corresponding resilience events in the stream carry the same
    numbers for the offline fold).
    """

    def __init__(self, writer):
        self._writer = writer
        self.records: list[dict] = []
        self._data_wait_acc = 0.0
        self.lost_steps = 0         # rewind: timed steps whose updates died
        self.skipped_updates = 0    # --on_nonfinite=skip drops
        self.enter("init")

    def _emit(self, kind: str, **fields) -> None:
        rec = {"kind": kind}
        rec.update(fields)
        self.records.append(rec)
        self._writer.event(kind, **fields)

    def enter(self, phase: str, step: int | None = None) -> None:
        self._emit("phase", phase=phase, t=time.monotonic(), step=step)
        # mirror the transition into the flight recorder's coarse lane
        # (obs.timeline): the ledger gets seconds, the timeline gets the
        # same spans per rank — one call site, two consumers
        timeline_mod.transition(phase, step=step)

    def note_data_wait(self, seconds: float) -> None:
        self._data_wait_acc += seconds

    def note_lost_steps(self, n: int) -> None:
        self.lost_steps += max(0, int(n))

    def note_skipped_updates(self, n: int) -> None:
        self.skipped_updates += max(0, int(n))

    def flush(self, step: int | None = None) -> None:
        if self._data_wait_acc > 0.0:
            self._emit("phase_acc", phase="data_wait",
                       seconds=self._data_wait_acc, step=step)
            self._data_wait_acc = 0.0

    def end(self, step: int | None = None) -> None:
        self.flush(step)
        self._emit("phase", phase=END_PHASE, t=time.monotonic(), step=step)
        timeline_mod.transition(END_PHASE, step=step)

    def ledger(self) -> "Ledger | None":
        """The ledger over everything emitted so far (driver-side path;
        resilience waste comes from the ``note_*`` counters)."""
        led = build_ledger(self.records, fold_resilience=False)
        if led is None:
            return None
        return _fold_waste(led, self.lost_steps, self.skipped_updates)


@dataclasses.dataclass
class Ledger:
    """Per-category wall seconds + the goodput account."""

    seconds: dict[str, float]       # category -> seconds (data_wait carved
                                    # out of its enclosing phase)
    wall_s: float                   # first transition -> end (or last seen)
    steps: int                      # timed steps observed (max step field)
    complete: bool                  # an explicit "end" transition was seen
    rewind_lost_s: float = 0.0      # step time replayed after rewinds
    skipped_updates_s: float = 0.0  # step time whose update was dropped

    @property
    def step_s(self) -> float:
        return self.seconds.get("step", 0.0)

    @property
    def mean_step_s(self) -> float:
        return self.step_s / self.steps if self.steps else 0.0

    @property
    def productive_s(self) -> float:
        return max(
            0.0, self.step_s - self.rewind_lost_s - self.skipped_updates_s)

    @property
    def goodput(self) -> float:
        return self.productive_s / self.wall_s if self.wall_s > 0 else 0.0

    def format_lines(self) -> list[str]:
        head = (f"goodput: {self.goodput:.1%} "
                f"(productive {self.productive_s:.1f}s "
                f"of {self.wall_s:.1f}s wall"
                + ("" if self.complete else "; run did not end cleanly")
                + ")")
        parts = [f"{k}={self.seconds[k]:.2f}s"
                 for k in PHASES
                 if self.seconds.get(k, 0.0) > 0.0 and k != "step"]
        if self.rewind_lost_s > 0:
            parts.append(f"rewind_lost={self.rewind_lost_s:.2f}s")
        if self.skipped_updates_s > 0:
            parts.append(f"skipped_updates={self.skipped_updates_s:.2f}s")
        lines = [head]
        if parts:
            lines.append("  non-productive: " + "  ".join(parts))
        return lines


def rewind_lost_steps(i: int, restored_step: int, base_step: int,
                      warmup_steps: int) -> int:
    """Timed steps of THIS run whose work a rewind discarded.

    ``restored_step`` is the checkpoint's absolute step counter, which
    on a ``--resume`` run includes every previous run's steps
    (``base_step``, the counter at this run's start) plus this run's
    warmup; the checkpoint's position in this run's timed loop is
    therefore ``restored_step - base_step - warmup_steps`` — clamped at
    0 for a checkpoint predating this run's timed loop (e.g. the
    resume source itself), where ALL ``i`` timed steps are lost.
    """
    at_save = max(0, restored_step - base_step - warmup_steps)
    return max(0, i - at_save)


def _fold_waste(led: Ledger, lost_steps: int, skipped: int) -> Ledger:
    """Scale wasted step *counts* into seconds by the mean step time and
    fold them into the ledger — replayed/rewound steps burned real step
    time whose work was discarded."""
    led.rewind_lost_s = min(led.step_s, lost_steps * led.mean_step_s)
    led.skipped_updates_s = min(
        max(0.0, led.step_s - led.rewind_lost_s),
        skipped * led.mean_step_s)
    return led


def build_ledger(records: list[dict],
                 fold_resilience: bool = True) -> Ledger | None:
    """Fold a metrics-record stream into a Ledger.

    Returns None when the stream carries no phase transitions (runs
    predating the ledger, or eval runs which emit only ``init``  — a
    ledger needs at least a ``step`` phase to account against).
    """
    transitions: list[tuple[str, float, int | None]] = []
    accs: list[tuple[int, str, float]] = []     # (position, phase, seconds)
    lost_steps = 0
    skipped = 0
    for rec in records:
        kind = rec.get("kind")
        if kind == "phase" and isinstance(rec.get("t"), (int, float)):
            transitions.append(
                (rec.get("phase", "idle"), float(rec["t"]), rec.get("step")))
        elif kind == "phase_acc" and isinstance(
                rec.get("seconds"), (int, float)):
            accs.append((len(transitions), rec.get("phase", "idle"),
                         float(rec["seconds"])))
        elif kind == "rewind":
            lost_steps += int(rec.get("lost_steps", 0) or 0)
        elif kind == "nonfinite_skip":
            skipped += int(rec.get("new_bad", 0) or 0)
    if not any(p == "step" for p, _, _ in transitions):
        return None

    seconds: dict[str, float] = {p: 0.0 for p in PHASES}
    complete = transitions[-1][0] == END_PHASE
    t0 = transitions[0][1]
    t_end = transitions[-1][1]
    for (p, t, _), (_, t_next, _) in zip(transitions, transitions[1:]):
        if p != END_PHASE:
            seconds[p] = seconds.get(p, 0.0) + max(0.0, t_next - t)
    # phase_acc: carve the accumulated seconds out of the phase that was
    # active when the record was appended (position = transitions seen)
    for pos, phase, s in accs:
        if pos > 0:
            host = transitions[pos - 1][0]
            if host != END_PHASE:
                seconds[host] = max(0.0, seconds.get(host, 0.0) - s)
        seconds[phase] = seconds.get(phase, 0.0) + s
    # timed-step count: the largest step stamp anywhere in the stream
    # (phase flushes, window records, resilience events all carry one)
    steps = max((r["step"] for r in records
                 if isinstance(r.get("step"), int)), default=0)
    led = Ledger(seconds=seconds, wall_s=max(0.0, t_end - t0),
                 steps=steps, complete=complete)
    if fold_resilience:
        led = _fold_waste(led, lost_steps, skipped)
    return led
