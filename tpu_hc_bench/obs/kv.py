"""KV-pool utilization ledger: allocation honesty for the serving lane.

Admission is conservative by design (``serve/engine.py`` reserves every
request's worst-case page count, so mid-generation eviction never
happens) — which means the pool underutilizes whenever outputs run
short, and before this ledger the waste was a guess, not a number.
This module is the seventh obs pillar: it folds the engine's KV-pool
bookkeeping into the one figure the on-demand-paging ROADMAP item must
be judged against,

    ``kv_pool_util`` = written-page-seconds / reserved-page-seconds,

plus the per-request **honesty gap** (``pages_reserved`` vs
``pages_final`` at retirement) and the **admission-cause split**: the
r20 ``queue_wait`` component broken into ``pool_starved`` vs
``batch_full`` time, so the tail-attribution line names WHICH resource
gated the p99 (pool-starved ⇒ grow the pool / evict; batch-full ⇒
scale out — the disaggregated-serving scaling-policy input).

Record shapes (round 22, all host counters the engine already holds —
no device round-trips; see ``serve.engine.KVLedger``):

- ``kv_pool`` records: periodic pool snapshots with cumulative
  ``reserved_page_s``/``written_page_s`` integrals, free-list depth,
  pool high-water and recycled-page count;
- ``request`` records grow ``pages_reserved``/``pages_peak_used``/
  ``pages_final`` footprint fields and the ``queue_pool_starved_ms``/
  ``queue_batch_full_ms`` cause split.

Pure record processing by the ``slo.py`` contract: NO jax import.
Pre-round-22 streams (no ``kv_pool`` records, no footprint fields)
fold to ``None``/absent and render labeled, never KeyError — the same
seam discipline as the r20 ``attribution_of`` normalizer.
"""

from __future__ import annotations

from tpu_hc_bench.obs import requests as requests_mod

KV_POOL_KIND = "kv_pool"

#: the per-request footprint fields stamped at retirement.
#: ``pages_peak_used`` equals ``pages_final`` under worst-case
#: reservation (lengths only grow and pages free only at retirement);
#: they diverge once mid-flight page release / on-demand paging lands.
FOOTPRINT_KEYS = ("pages_reserved", "pages_peak_used", "pages_final")

#: round 25 footprint fields: pages grown on demand after admission
#: and page slots admitted pointing at shared prefix-cache pages.
#: Absent on pre-r25 records — normalized to 0 (the r20/r22 seam), so
#: old streams flow through fold_attribution / obs diff / obs regress
#: without KeyError.
GROWTH_KEYS = ("pages_grown", "prefix_pages_shared")

#: queue-wait causes, in render order (and the engine's charge order)
WAIT_CAUSES = ("pool_starved", "batch_full")

#: cause name -> the flat key on the ``request`` record
CAUSE_KEYS = (
    ("pool_starved", "queue_pool_starved_ms"),
    ("batch_full", "queue_batch_full_ms"),
)

#: shed causes, in render order (round 23): the deadline-aware
#: degradation stamps extending the r22 queue-wait split — every
#: rejected or expired request names the policy decision that shed it
SHED_CAUSES = ("deadline_expired", "deadline_predicted",
               "resident_expired")


def footprint_of(record: dict) -> dict | None:
    """One request record's KV footprint, or ``None`` when the record
    predates round 22 or belongs to a pool-free (classify) member —
    the back-compat seam every consumer reads through."""
    res = record.get("pages_reserved")
    peak = record.get("pages_peak_used")
    final = record.get("pages_final")
    if not all(isinstance(v, (int, float)) for v in (res, peak, final)):
        return None
    out = {"pages_reserved": int(res), "pages_peak_used": int(peak),
           "pages_final": int(final)}
    for key in GROWTH_KEYS:
        # round 25 fields: a pre-r25 record simply never grew or
        # shared a page — 0, labeled by key, never a KeyError
        v = record.get(key)
        out[key] = int(v) if isinstance(v, (int, float)) else 0
    return out


def has_footprints(request_records: list[dict]) -> bool:
    return any(footprint_of(r) is not None for r in request_records)


def wait_cause_of(record: dict) -> dict[str, float]:
    """One record's cause split in ms, absent fields normalized to 0.0
    (pre-r22 records carry only the undivided ``queue_ms``)."""
    out = {}
    for name, key in CAUSE_KEYS:
        v = record.get(key)
        out[name] = float(v) if isinstance(v, (int, float)) else 0.0
    return out


def has_causes(request_records: list[dict]) -> bool:
    keys = tuple(key for _, key in CAUSE_KEYS)
    return any(any(k in r for k in keys) for r in request_records)


def fold_wait_causes(request_records: list[dict],
                     tail_frac: float = requests_mod.TAIL_FRAC
                     ) -> dict | None:
    """The cause split aggregated over the slowest ``tail_frac`` of
    requests by e2e — the refinement of the r20 tail attribution that
    names WHICH resource the tail's queue_wait was spent on.

    ``tail_frac`` shares are of the tail's mean queue_wait (the r20
    ``queue_ms`` component), so "100% pool_starved" reads as "every
    waited millisecond in the tail was a full pool".  Returns ``None``
    when no request carries an e2e.
    """
    rows = [(float(r["e2e_ms"]), r) for r in request_records
            if isinstance(r.get("e2e_ms"), (int, float))]
    if not rows:
        return None
    rows.sort(key=lambda x: x[0])
    k = max(1, int(round(len(rows) * tail_frac)))
    tail = [r for _, r in rows[-k:]]
    tail_queue_ms = sum(
        requests_mod.attribution_of(r)["queue_wait"] for r in tail) / k
    tail_ms = {name: sum(wait_cause_of(r)[name] for r in tail) / k
               for name in WAIT_CAUSES}
    denom = tail_queue_ms if tail_queue_ms > 0 else 1.0
    return {
        "n": len(rows),
        "tail_n": k,
        "tail_queue_ms": round(tail_queue_ms, 3),
        "tail_ms": {n: round(v, 3) for n, v in tail_ms.items()},
        "tail_frac": {n: round(v / denom, 4) for n, v in tail_ms.items()},
        "total_ms": {
            name: round(sum(wait_cause_of(r)[name]
                            for _, r in rows), 3)
            for name in WAIT_CAUSES},
        "has_causes": has_causes(request_records),
    }


def fold_ledger(*, reserved_page_s: float, written_page_s: float,
                pages_peak: int | None = None,
                pages_recycled: int | None = None,
                pages_grown: int | None = None,
                cow_copies: int | None = None,
                prefix_hits: int | None = None,
                prefix_lookups: int | None = None,
                prefix_pages_shared: int | None = None,
                request_records: list[dict] = ()) -> dict:
    """The ONE ledger fold (engine-side and offline callers share it,
    so the engine's final print and ``obs summarize`` agree by
    construction): page-seconds integrals -> utilization, request
    footprints -> the mean honesty gap, cause fields -> the tail
    cause split, and (round 25) the growth/sharing counters ->
    ``prefix_hit_frac``.  The r25 kwargs default to ``None`` so a
    pre-r25 caller folds exactly as before."""
    rs = float(reserved_page_s or 0.0)
    ws = float(written_page_s or 0.0)
    out: dict = {
        "util": round(ws / rs, 4) if rs > 0 else None,
        "reserved_page_s": round(rs, 4),
        "written_page_s": round(ws, 4),
        "pages_peak": int(pages_peak) if pages_peak is not None else None,
        "pages_recycled": (int(pages_recycled)
                           if pages_recycled is not None else None),
    }
    if pages_grown is not None:
        out["pages_grown"] = int(pages_grown)
    if cow_copies is not None:
        out["cow_copies"] = int(cow_copies)
    if prefix_pages_shared is not None:
        out["prefix_pages_shared"] = int(prefix_pages_shared)
    if prefix_lookups is not None:
        out["prefix_lookups"] = int(prefix_lookups)
        out["prefix_hits"] = int(prefix_hits or 0)
        # None (not 0.0) when the cache never looked anything up —
        # regress must skip structurally, not gate on a fake zero
        out["prefix_hit_frac"] = (
            round(int(prefix_hits or 0) / int(prefix_lookups), 4)
            if int(prefix_lookups) > 0 else None)
    fps = [f for f in (footprint_of(r) for r in request_records) if f]
    if fps:
        res = sum(f["pages_reserved"] for f in fps)
        fin = sum(f["pages_final"] for f in fps)
        out.update({
            "req_n": len(fps),
            "req_pages_reserved_mean": round(res / len(fps), 3),
            "req_pages_final_mean": round(fin / len(fps), 3),
            "req_gap_frac": round(1.0 - fin / res, 4) if res else None,
        })
    wc = fold_wait_causes(list(request_records))
    if wc is not None:
        out["wait_causes"] = wc
    return out


def fold_kv(records: list[dict]) -> dict | None:
    """The offline ledger fold over one metrics stream: the LAST
    ``kv_pool`` record's cumulative integrals (a truncated stream
    reports the run so far) + the request footprints.  ``None`` when
    the stream carries neither (pre-round-22 serve stream, classify
    member, or a training run) — absent, never a KeyError."""
    pools = [r for r in records if r.get("kind") == KV_POOL_KIND]
    reqs = [r for r in records if r.get("kind") == "request"]
    if not pools and not has_footprints(reqs):
        return None
    last = pools[-1] if pools else {}

    def _num(v):
        return float(v) if isinstance(v, (int, float)) else 0.0

    def _int(key):
        v = last.get(key)
        return int(v) if isinstance(v, (int, float)) else None

    return fold_ledger(
        reserved_page_s=_num(last.get("reserved_page_s")),
        written_page_s=_num(last.get("written_page_s")),
        pages_peak=_int("pages_peak"),
        pages_recycled=_int("pages_recycled"),
        # round 25 counters: absent on pre-r25 kv_pool records, and
        # fold_ledger omits the fields entirely then (no fake zeros)
        pages_grown=_int("pages_grown"),
        cow_copies=_int("pages_cow"),
        prefix_hits=_int("prefix_hits"),
        prefix_lookups=_int("prefix_lookups"),
        prefix_pages_shared=_int("prefix_pages_shared"),
        request_records=reqs)


def flatten_kv(kv_fold: dict | None) -> dict:
    """The regress/BENCH-extra projection: utilization (gated
    direction-aware, down = regression) and the mean per-request
    reservation gap."""
    if not kv_fold:
        return {}
    out = {}
    u = kv_fold.get("util")
    if isinstance(u, (int, float)):
        out["kv_pool_util"] = u
    g = kv_fold.get("req_gap_frac")
    if isinstance(g, (int, float)):
        out["kv_req_gap_frac"] = g
    # round 25: the sharing hit rate (gated: a drop = regression) and
    # the growth count — absent when the run predates round 25 or the
    # cache never looked anything up (regress skips structurally)
    h = kv_fold.get("prefix_hit_frac")
    if isinstance(h, (int, float)):
        out["prefix_hit_frac"] = h
    pg = kv_fold.get("pages_grown")
    if isinstance(pg, (int, float)):
        out["pages_grown_total"] = pg
    return out


def kv_lines(fold: dict) -> list[str]:
    """The summarize KV-pool section: the ``kv_pool_util`` headline,
    the honesty-gap line, the tail cause split, and the configured
    pool geometry (satellite: pool size appeared in no rendered output
    before round 22).  ``fold`` is the whole serve fold — geometry
    keys ride the summary, the ledger rides ``fold["kv_pool"]``."""
    lines: list[str] = []
    kvf = fold.get("kv_pool")
    if kvf:
        util = kvf.get("util")
        if isinstance(util, (int, float)):
            head = (f"  kv_pool_util {util:.1%}  (written-page-s "
                    f"{kvf.get('written_page_s', 0.0):.4g} / "
                    f"reserved-page-s "
                    f"{kvf.get('reserved_page_s', 0.0):.4g})")
            peak = kvf.get("pages_peak")
            if peak is not None:
                head += f"  peak {peak}"
                if fold.get("kv_pages"):
                    # pool high-water against the allocatable pool
                    # (page 0 is the reserved trash page)
                    head += f"/{int(fold['kv_pages']) - 1}"
                head += " pages"
            if kvf.get("pages_recycled") is not None:
                head += f"  recycled {kvf['pages_recycled']}"
            if kvf.get("pages_grown") is not None:
                # round 25 on-demand growth (recycled and COW copies
                # are tracked apart — a copy is not a recycle)
                head += f"  grown {kvf['pages_grown']}"
            lines.append(head)
        if kvf.get("prefix_lookups") is not None:
            hf = kvf.get("prefix_hit_frac")
            cow = kvf.get("cow_copies") or 0
            lines.append(
                "  prefix cache: "
                + (f"{hf:.1%} hit rate"
                   if isinstance(hf, (int, float)) else "no lookups")
                + f" ({kvf.get('prefix_hits', 0)}/"
                  f"{kvf.get('prefix_lookups', 0)}), "
                  f"{kvf.get('prefix_pages_shared', 0)} shared "
                  f"page-slot(s), {cow} COW cop"
                  f"{'y' if cow == 1 else 'ies'}")
        if isinstance(kvf.get("req_gap_frac"), (int, float)):
            lines.append(
                f"  reservation honesty: "
                f"{kvf.get('req_pages_reserved_mean', 0.0):.1f} pages "
                f"reserved vs {kvf.get('req_pages_final_mean', 0.0):.1f} "
                f"written per request — gap "
                f"{kvf['req_gap_frac']:.0%}")
        wc = kvf.get("wait_causes")
        if wc and wc.get("has_causes"):
            fr = wc.get("tail_frac", {})
            lines.append(
                f"  queue_wait cause (slowest decile): "
                + " / ".join(f"{fr.get(name, 0.0):.0%} {name}"
                             for name in WAIT_CAUSES)
                + f"  [of {wc.get('tail_queue_ms', 0.0):.0f}ms tail "
                  f"queue_wait]")
    if fold.get("kv_pool_bytes") is not None:
        geom = (f"  kv pool geometry: {fold.get('kv_pages', '?')} pages "
                f"x {fold.get('kv_page_size', '?')} tokens x "
                f"{fold.get('kv_layers', '?')} layers = "
                f"{fold['kv_pool_bytes'] / 2**20:.2f} MiB")
        sb = fold.get("kv_scale_bytes")
        if sb:
            geom += f" (incl. {sb / 2**10:.1f} KiB int8_kv scales)"
        lines.append(geom)
    return lines


def kv_diff_lines(fold_a: dict | None, fold_b: dict | None) -> list[str]:
    """``obs diff`` rows: utilization / honesty-gap / tail-cause
    deltas in percentage points.  A side without the ledger (pre-r22
    stream) reads as 0 and is labeled, never a KeyError."""
    ka = (fold_a or {}).get("kv_pool")
    kb = (fold_b or {}).get("kv_pool")
    if not ka and not kb:
        return []
    lines = ["  kv pool (written/reserved page-seconds):"]
    rows = [("kv_pool_util", "util"), ("kv req gap", "req_gap_frac"),
            # round 25: sides without a cache (pre-r25 or off) read 0
            ("prefix hits", "prefix_hit_frac")]
    for label, key in rows:
        va = (ka or {}).get(key)
        vb = (kb or {}).get(key)
        va = float(va) if isinstance(va, (int, float)) else 0.0
        vb = float(vb) if isinstance(vb, (int, float)) else 0.0
        if va == 0.0 and vb == 0.0:
            continue
        lines.append(f"  {label:>14s} {va:11.1%} {vb:11.1%} "
                     f"{100.0 * (vb - va):+7.1f}pp")
    for name in WAIT_CAUSES:
        va = float(((ka or {}).get("wait_causes") or {})
                   .get("tail_frac", {}).get(name, 0.0))
        vb = float(((kb or {}).get("wait_causes") or {})
                   .get("tail_frac", {}).get(name, 0.0))
        if va == 0.0 and vb == 0.0:
            continue
        lines.append(f"  {'tail ' + name:>14s} {va:11.1%} {vb:11.1%} "
                     f"{100.0 * (vb - va):+7.1f}pp")
    for side, k in (("a", ka), ("b", kb)):
        if k is None:
            lines.append(f"  note: run {side} predates the KV-pool "
                         "ledger (round 22) — no kv_pool records")
    return lines if len(lines) > 1 else []


# ---------------------------------------------------------------------
# timeline export: pool occupancy as a Chrome-trace counter track


#: synthetic Chrome-trace pid for the pool counter track (beside the
#: per-request lanes at ``requests_mod.REQUEST_LANE_PID``)
KV_COUNTER_PID = (1 << 20) + 1


def kv_counter_events(records: list[dict]) -> list[dict]:
    """Chrome-trace "C"-phase counter samples of pool occupancy
    (written / reserved-but-unwritten / free pages, stacked), one per
    ``kv_pool`` record, merged by ``obs.timeline.merge_chrome_trace``
    beside the per-request lanes — a pool-full stall is visually
    attributable to the admission gap above it.

    Anchored by the run's ``serve_clock`` record exactly like the
    request lanes; without one (pre-r20 stream) or without ``kv_pool``
    records (pre-r22 stream) the track is skipped, never wrong.
    """
    t0_unix = None
    for r in records:
        if r.get("kind") == "serve_clock" and \
                isinstance(r.get("t_unix"), (int, float)):
            t0_unix = float(r["t_unix"])
            break
    if t0_unix is None:
        return []
    events: list[dict] = []
    for r in records:
        if r.get("kind") != KV_POOL_KIND:
            continue
        reserved = int(r.get("pages_reserved") or 0)
        written = int(r.get("pages_written") or 0)
        events.append({
            "name": "kv pool pages", "ph": "C",
            "ts_unix": t0_unix + float(r.get("t") or 0.0),
            "pid": KV_COUNTER_PID, "tid": 0,
            "args": {"written": written,
                     "reserved_unwritten": max(0, reserved - written),
                     "free": int(r.get("free_pages") or 0)}})
    if events:
        events.append({"name": "process_name", "ph": "M",
                       "pid": KV_COUNTER_PID,
                       "args": {"name": "kv pool"}})
    return events
