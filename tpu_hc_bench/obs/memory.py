"""Measured device memory: AOT report, runtime HBM ledger, OOM forensics.

Device memory is the resource that actually walls the zoo (the
accumulation members' batches exceed HBM as one-shot batches; the tune
pruner's whole ``hbm-oom`` class exists because of it), yet until this
module every memory fact the system acted on was a heuristic anchor.
Three measurements replace the guesswork, each mirroring an existing
honesty mechanism:

- **Compile-time memory report** — the AOT path already used by the
  MFU probe (``obs.efficiency.StepFlopsProbe``) also asks
  ``compiled.memory_analysis()`` for the argument/output/temp/
  generated-code bytes of the *exact step program the run executes*.
  ``memory_report`` places the measured argument bytes next to an
  analytic params+optimizer+batch table and flags >10% disagreement —
  the same table-rot tripwire as the measured-vs-analytic MFU
  cross-check.  Temp (activations + workspace) has no honest analytic
  twin, so it is reported measured-only, never guessed.
- **Runtime HBM ledger** — ``MemoryLedger`` polls once per sync window
  (``device.memory_stats()`` where the backend exposes allocator
  peaks; a ``jax.live_arrays()`` byte-sum high-water fallback on CPU,
  which sees only sample-point live bytes, and says so via its
  ``source`` label) and attributes the high-water mark to the goodput
  ledger's phase that set it, so ``obs summarize`` can answer *which
  phase* (compile, step, checkpoint_async, rewind_replay) owns the
  peak.  One ``memory`` record per window in metrics.jsonl; the peak
  also rides every host's fleet heartbeat as ``mem_peak_bytes``.
- **OOM/emergency forensics** — on ``RESOURCE_EXHAUSTED``, a watchdog
  fire, or an emergency save, ``dump_forensics`` writes a top-K
  live-buffer breakdown (shape/dtype/count/bytes, aggregated) as
  ``memory_dump.json`` beside the metrics stream, plus the raw
  ``jax.profiler.device_memory_profile()`` pprof blob (which carries
  source-line attribution) when the backend exposes it.  Best-effort
  by construction: forensics on a dying run must never mask the death.

``--hbm_budget[=auto]`` closes the pre-run gap: the AOT memory report
is compared against the budget (``auto`` = the device's measured
``bytes_limit``) and warns loudly at run start — before the warmup
pays for the full run's compile and OOMs 50 steps in.

The fold/render halves (``fold_memory_records``, ``memory_lines``,
``memory_report_lines``) are pure record processing so ``summarize``/
``diff``/``watch`` work on artifacts from any machine.
"""

from __future__ import annotations

import json
import os
import time

MEMORY_DUMP_NAME = "memory_dump.json"
MEMORY_PROFILE_NAME = "memory_profile.pb"

# measured-vs-analytic argument-byte disagreement threshold — the same
# 10% contract as the MFU cross-check (obs.efficiency.mfu_report)
ARGS_DISAGREE_FRAC = 0.10


# ---------------------------------------------------------------------
# compile-time: AOT memory analysis + the analytic table


def memory_analysis_of_compiled(compiled) -> dict | None:
    """The byte accounting of ``compiled.memory_analysis()``, tolerant
    of cross-version shapes (CompiledMemoryStats attributes on modern
    stacks, a plain dict elsewhere, None/raise where the backend has no
    analysis).  ``total_bytes`` is the program's device footprint:
    args + output + temp + generated code, minus the aliased bytes that
    donation lets outputs share with arguments."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out: dict[str, int] = {}
    for field, attr in (
        ("argument_bytes", "argument_size_in_bytes"),
        ("output_bytes", "output_size_in_bytes"),
        ("temp_bytes", "temp_size_in_bytes"),
        ("alias_bytes", "alias_size_in_bytes"),
        ("generated_code_bytes", "generated_code_size_in_bytes"),
    ):
        v = getattr(ma, attr, None)
        if v is None and isinstance(ma, dict):
            v = ma.get(attr, ma.get(field))
        if v is not None:
            try:
                out[field] = int(v)
            except (TypeError, ValueError):
                continue
    if not out:
        return None
    out["total_bytes"] = max(
        0,
        out.get("argument_bytes", 0) + out.get("output_bytes", 0)
        + out.get("temp_bytes", 0) + out.get("generated_code_bytes", 0)
        - out.get("alias_bytes", 0))
    return out


def _tree_bytes(tree) -> int:
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "size") and hasattr(leaf, "dtype"):
            total += int(leaf.size) * getattr(leaf.dtype, "itemsize", 4)
    return total


def analytic_memory_table(state, batch=None) -> dict:
    """Parameter/optimizer/input bytes from the live state's shapes —
    the analytic half of the cross-check.  ``state`` is a TrainState
    (or the PP ``(params, opt_state)`` tuple); the sums are pure host
    arithmetic over shapes, no device touch.  Activations are
    deliberately absent: they have no honest analytic twin here — the
    AOT report's temp bytes are the measurement."""
    params = getattr(state, "params", None)
    opt = getattr(state, "opt_state", None)
    if params is None and isinstance(state, (tuple, list)) and state:
        params = state[0]
        opt = state[1] if len(state) > 1 else None
    if params is None:
        params = state
    out = {
        "params_bytes": _tree_bytes(params),
        "opt_bytes": _tree_bytes(opt),
        "batch_bytes": _tree_bytes(batch),
    }
    out["state_bytes"] = (out["params_bytes"] + out["opt_bytes"]
                          + out["batch_bytes"])
    return out


def memory_report(measured: dict | None, analytic: dict) -> dict:
    """The honest memory record: AOT bytes source-labeled next to the
    analytic table, with the >10% argument-byte disagreement flag.
    The comparison pairs the AOT ``argument_bytes`` against the
    analytic params+opt+batch sum — the two views of the same thing
    (the step program's inputs ARE the state plus the batch)."""
    out: dict = {"analytic": dict(analytic), "mem_source": "analytic"}
    if measured:
        out["measured"] = dict(measured)
        out["mem_source"] = "measured"
        args_analytic = analytic.get("state_bytes", 0)
        args_measured = measured.get("argument_bytes")
        if args_analytic > 0 and args_measured:
            rel = abs(args_measured - args_analytic) / args_analytic
            out["args_disagreement"] = rel
            out["args_disagree"] = rel > ARGS_DISAGREE_FRAC
    return out


def _mib(n) -> str:
    return f"{(n or 0) / 2**20:.1f}"


def memory_report_lines(rec: dict) -> list[str]:
    """Render a ``memory_report`` record (shared by the driver's final
    print and ``obs summarize``), mirroring ``efficiency.mfu_lines``."""
    if not rec:
        return []
    analytic = rec.get("analytic") or {}
    measured = rec.get("measured")
    if measured:
        head = (f"  memory (AOT): args {_mib(measured.get('argument_bytes'))}"
                f" MiB  temp {_mib(measured.get('temp_bytes'))} MiB  "
                f"output {_mib(measured.get('output_bytes'))} MiB  "
                f"total {_mib(measured.get('total_bytes'))} MiB")
    else:
        head = "  memory (AOT): unavailable on this arm/backend"
    head += (f"  (analytic: params {_mib(analytic.get('params_bytes'))}"
             f" + opt {_mib(analytic.get('opt_bytes'))}"
             f" + batch {_mib(analytic.get('batch_bytes'))}"
             f" = {_mib(analytic.get('state_bytes'))} MiB)")
    lines = [head]
    if rec.get("args_disagree"):
        lines.append(
            f"  WARNING: AOT argument bytes disagree "
            f"{rec.get('args_disagreement', 0.0):.0%} with the analytic "
            f"params+opt+batch table: measured "
            f"{_mib((rec.get('measured') or {}).get('argument_bytes'))} vs "
            f"analytic {_mib(analytic.get('state_bytes'))} MiB — the "
            f"state-layout table may have rotted")
    return lines


# ---------------------------------------------------------------------
# runtime: per-sync-window sampling + phase-attributed high water


def device_memory_sample() -> dict:
    """One capability-gated device-memory poll.

    Where the backend exposes allocator stats (TPU) the sample carries
    true per-device peaks and the HBM limit; on backends that do not
    (the CPU test mesh) it degrades to the ``jax.live_arrays()`` byte
    sum — the live bytes at THIS sample point, labeled ``live_arrays``
    so no consumer mistakes it for an allocator peak."""
    from tpu_hc_bench.obs import metrics as metrics_mod

    stats = metrics_mod.device_memory_stats()
    if stats:
        limits = [v["bytes_limit"] for v in stats.values()
                  if v.get("bytes_limit")]
        return {
            "source": "memory_stats",
            "bytes_in_use": max((v.get("bytes_in_use", 0)
                                 for v in stats.values()), default=0),
            "peak_bytes": max((v.get("peak_bytes_in_use", 0)
                               for v in stats.values()), default=0),
            "bytes_limit": min(limits) if limits else None,
            "devices": stats,
        }
    import jax

    total = 0
    try:
        for a in jax.live_arrays():
            total += int(getattr(a, "nbytes", 0) or 0)
    except Exception:
        total = 0
    return {"source": "live_arrays", "bytes_in_use": total,
            "peak_bytes": None, "bytes_limit": None}


class MemoryLedger:
    """Per-run device-memory high water, attributed to goodput phases.

    The driver calls ``sample(phase, step)`` once per sync window (and
    at checkpoint/rewind/emergency boundaries) and writes the returned
    record into the metrics stream as one ``memory`` record.  The
    ledger keeps the running peak and the phase during which it rose
    (allocator peaks are process-lifetime cumulative, so "the phase
    polled when the peak first read higher" is the honest attribution),
    plus per-phase maxima of the *sampled in-use bytes* — attributing
    the cumulative peak to every later phase would make the per-phase
    table meaningless.  Under the ``live_arrays`` fallback (no
    allocator peaks) the record's ``peak_bytes`` is the ledger's own
    running high water, so the on-disk stream folds identically on
    every backend.

    ``sample_fn`` is injectable for deterministic tests.
    """

    def __init__(self, sample_fn=None):
        self._sample_fn = sample_fn or device_memory_sample
        self.peak_bytes = 0
        self.peak_phase: str | None = None
        self.per_phase: dict[str, int] = {}
        self.source: str | None = None
        self.bytes_limit: int | None = None

    def sample(self, phase: str, step: int | None = None) -> dict:
        s = dict(self._sample_fn())
        # per-window stream records stay lean: no fold/render consumer
        # reads the per-device table (forensics re-reads the allocator
        # stats itself when it needs them)
        s.pop("devices", None)
        self.source = s.get("source") or self.source
        if s.get("bytes_limit"):
            self.bytes_limit = s["bytes_limit"]
        high = s.get("peak_bytes") or s.get("bytes_in_use") or 0
        # per-phase from the sample-point in-use bytes: the allocator
        # peak is cumulative over the process, so using it here would
        # stamp the global high water onto every later phase
        usage = s.get("bytes_in_use") or high
        self.per_phase[phase] = max(self.per_phase.get(phase, 0), usage)
        if high > self.peak_bytes:
            self.peak_bytes = high
            self.peak_phase = phase
        if not s.get("peak_bytes"):
            # live_arrays fallback: the stream carries the running high
            # water so offline folds see the same number the ledger does
            s["peak_bytes"] = self.peak_bytes
        s["phase"] = phase
        s["step"] = step
        return s

    def fold(self) -> dict | None:
        """The ledger's own account in ``fold_memory_records`` shape —
        the driver's end-of-run print and the offline summarize fold
        render through the same ``memory_lines``."""
        if self.peak_bytes <= 0:
            return None
        return {"peak_bytes": self.peak_bytes,
                "peak_phase": self.peak_phase,
                "per_phase": dict(self.per_phase),
                "source": self.source,
                "bytes_limit": self.bytes_limit}


def fold_memory_records(records: list[dict]) -> dict | None:
    """Fold a run's ``memory`` records (pure — the ``summarize``/
    ``diff``/``watch`` half of the ledger).  Tolerates the pre-round-15
    record shape ({"supported": bool, "devices": {...}}, no phase)."""
    peak = 0
    peak_phase: str | None = None
    per_phase: dict[str, int] = {}
    source = None
    limit = None
    seen = False
    for r in records:
        if r.get("kind") != "memory":
            continue
        seen = True
        if "bytes_in_use" in r or "peak_bytes" in r:
            high = r.get("peak_bytes") or r.get("bytes_in_use") or 0
            usage = r.get("bytes_in_use") or high
            phase = r.get("phase")
            source = r.get("source") or source
            if r.get("bytes_limit"):
                limit = r["bytes_limit"]
        else:       # legacy end-of-run record
            devices = r.get("devices") or {}
            high = max((v.get("peak_bytes_in_use", 0)
                        for v in devices.values()), default=0)
            usage = high
            phase = None
            source = source or ("memory_stats" if devices else None)
        if phase:
            # sample-point usage, not the cumulative allocator peak —
            # same attribution rule as MemoryLedger.sample
            per_phase[phase] = max(per_phase.get(phase, 0), usage)
        if high > peak:
            peak, peak_phase = high, phase
    if not seen or peak <= 0:
        return None
    return {"peak_bytes": peak, "peak_phase": peak_phase,
            "per_phase": per_phase, "source": source,
            "bytes_limit": limit}


def memory_lines(fold: dict | None) -> list[str]:
    """Render a ``fold_memory_records`` result (summarize/watch/driver)."""
    if not fold:
        return []
    head = f"  memory: peak {_mib(fold['peak_bytes'])} MiB"
    if fold.get("bytes_limit"):
        head += (f" of {fold['bytes_limit'] / 2**30:.1f} GiB limit "
                 f"({fold['peak_bytes'] / fold['bytes_limit']:.0%})")
    head += f"  (source: {fold.get('source') or '?'}"
    if fold.get("peak_phase"):
        head += f"; high-water set in phase {fold['peak_phase']}"
    head += ")"
    lines = [head]
    per_phase = fold.get("per_phase") or {}
    if per_phase:
        from tpu_hc_bench.obs import goodput as goodput_mod

        order = [p for p in goodput_mod.PHASES if p in per_phase]
        order += [p for p in per_phase if p not in order]
        lines.append("    per-phase peaks (MiB): " + "  ".join(
            f"{p} {_mib(per_phase[p])}" for p in order))
    return lines


# ---------------------------------------------------------------------
# OOM / emergency forensics


def is_oom_error(exc: BaseException | str) -> bool:
    """Device-memory exhaustion, by message: jax surfaces allocator
    failure as XlaRuntimeError('RESOURCE_EXHAUSTED: ...') with
    'Out of memory' / 'failed to allocate' spellings across backends.
    The ONE copy of the spellings — tune.prune's measured-anchor OOM
    classifier calls this too (a string is accepted for that path)."""
    msg = str(exc)
    return any(tok in msg for tok in (
        "RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
        "failed to allocate"))


def live_buffer_breakdown(top_k: int = 24) -> dict:
    """Top-K live device buffers, aggregated by (shape, dtype) — one
    row per distinct buffer shape with count and total bytes, largest
    first.  The aggregation is the point: an OOM'd training step holds
    hundreds of identically-shaped activation blocks, and 'which shape
    class owns the memory' is the actionable fact."""
    import jax

    groups: dict[tuple, dict] = {}
    total = 0
    count = 0
    for a in jax.live_arrays():
        try:
            nbytes = int(a.nbytes)
            key = (tuple(a.shape), str(a.dtype))
        except Exception:
            continue
        count += 1
        total += nbytes
        g = groups.setdefault(key, {"shape": list(key[0]),
                                    "dtype": key[1], "count": 0,
                                    "nbytes": 0})
        g["count"] += 1
        g["nbytes"] += nbytes
    top = sorted(groups.values(), key=lambda g: -g["nbytes"])[:top_k]
    return {"total_live_bytes": total, "buffer_count": count,
            "top_buffers": top}


def dump_forensics(out_dir: str, reason: str, step: int | None = None,
                   top_k: int = 24, error: str | None = None,
                   print_fn=None) -> str | None:
    """Write ``memory_dump.json`` beside the metrics stream.

    Contents: the live-buffer breakdown, the device allocator stats
    where available, and (when the backend exposes it) the raw
    ``jax.profiler.device_memory_profile()`` pprof blob saved as
    ``memory_profile.pb`` next to the dump — that blob carries the
    per-allocation source lines (``pprof -lines memory_profile.pb``).
    Best-effort end to end: this runs on OOM/watchdog/preemption paths
    and must never raise over the death it is documenting.  Returns the
    dump path, or None on any failure."""
    try:
        from tpu_hc_bench.obs import metrics as metrics_mod

        payload: dict = {"reason": reason, "step": step,
                         "t_unix": time.time()}
        if error:
            payload["error"] = str(error)[:2000]
        payload.update(live_buffer_breakdown(top_k))
        payload["device_memory"] = metrics_mod.device_memory_stats() or None
        try:
            import jax

            prof = jax.profiler.device_memory_profile()
            if prof:
                with open(os.path.join(out_dir, MEMORY_PROFILE_NAME),
                          "wb") as f:
                    f.write(prof)
                payload["device_memory_profile"] = MEMORY_PROFILE_NAME
        except Exception:
            pass
        path = os.path.join(out_dir, MEMORY_DUMP_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2, default=str)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        if print_fn is not None:
            print_fn(
                f"memory forensics ({reason}): {path} — "
                f"{payload['buffer_count']} live buffer(s), "
                f"{payload['total_live_bytes'] / 2**20:.1f} MiB")
        return path
    except Exception:
        return None


# ---------------------------------------------------------------------
# --hbm_budget


_BUDGET_SUFFIXES = (
    ("tib", 2**40), ("gib", 2**30), ("mib", 2**20), ("kib", 2**10),
    ("tb", 2**40), ("gb", 2**30), ("mb", 2**20), ("kb", 2**10), ("b", 1),
)


def parse_hbm_budget(spec) -> int | str | None:
    """``--hbm_budget`` → bytes, ``"auto"``, or None (off).

    Accepts a byte count with an optional binary suffix (``16GB``,
    ``900MB``, ``17179869184``), ``auto`` (resolve against the live
    device's measured ``bytes_limit`` at run start), or unset/off.
    Loud on garbage — a typo'd budget must die at flag time."""
    if spec is None:
        return None
    s = str(spec).strip().lower()
    if s in ("", "off", "none", "0"):
        return None
    if s == "auto":
        return "auto"
    mult = 1
    for suf, m in _BUDGET_SUFFIXES:
        if s.endswith(suf):
            s, mult = s[: -len(suf)].strip(), m
            break
    try:
        val = float(s) * mult
    except ValueError:
        raise ValueError(
            f"--hbm_budget must be bytes (suffixes KB/MB/GB/TB), 'auto', "
            f"or unset/off: {spec!r}") from None
    if val <= 0:
        raise ValueError(f"--hbm_budget must be > 0: {spec!r}")
    return int(val)


def resolve_hbm_budget_bytes(parsed) -> tuple[int | None, str | None]:
    """Resolve a parsed budget to bytes at run start.  ``auto`` reads
    the smallest local device's ``bytes_limit``; returns ``(None,
    note)`` when the backend exposes none (the CPU test mesh) — the
    caller prints the note instead of silently skipping the check."""
    if parsed is None:
        return None, None
    if parsed != "auto":
        return int(parsed), None
    sample = device_memory_sample()
    limit = sample.get("bytes_limit")
    if limit:
        return int(limit), None
    return None, ("--hbm_budget=auto: this backend exposes no device "
                  "bytes_limit (memory_stats unavailable) — budget "
                  "check skipped; pass an explicit byte budget")


def budget_lines(measured: dict | None, budget_bytes: int | None,
                 note: str | None = None,
                 advice: str | None = None) -> list[str]:
    """The pre-run budget verdict: loud WARNING when the AOT memory
    report exceeds the budget, one quiet confirmation line otherwise.
    ``advice`` is the lane's shrink-this suggestion (defaults to the
    training knobs)."""
    advice = advice or ("shrink --batch_size or raise "
                        "--gradient_accumulation_steps")
    if note:
        return [f"WARNING: {note}"]
    if budget_bytes is None:
        return []
    if not measured or not measured.get("total_bytes"):
        return ["WARNING: --hbm_budget: no AOT memory report for this "
                "arm/backend — budget unchecked"]
    total = measured["total_bytes"]
    detail = (f"args {_mib(measured.get('argument_bytes'))} + temp "
              f"{_mib(measured.get('temp_bytes'))} + output "
              f"{_mib(measured.get('output_bytes'))} MiB")
    if total > budget_bytes:
        return [
            f"WARNING: --hbm_budget: AOT memory report "
            f"{total / 2**30:.2f} GiB ({detail}) EXCEEDS the budget "
            f"{budget_bytes / 2**30:.2f} GiB — this run is likely to "
            f"OOM; {advice} before paying for the full run"]
    return [f"hbm budget: AOT memory report {total / 2**30:.2f} GiB "
            f"({detail}) fits the budget {budget_bytes / 2**30:.2f} GiB "
            f"({total / budget_bytes:.0%})"]
