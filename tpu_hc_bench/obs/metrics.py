"""Per-run machine-readable artifacts: ``metrics.jsonl`` + ``manifest.json``.

The reference harness's only observable output is the printed
``images/sec`` lines an operator greps from a teed log (SURVEY.md §5);
until this module, our driver inherited that.  A run with
``--metrics_dir`` now leaves two files behind:

- ``manifest.json`` — run identity: the resolved flag set, mesh shape,
  world size, jax/jaxlib versions, git sha, device kind.  Everything a
  regression hunt needs to answer "what exactly was this run?".
- ``metrics.jsonl`` — one record per event, ``kind``-tagged:
  ``window`` (per-display-window rate/step-time/loss), ``memory``
  (one per sync window: the ``obs.memory`` HBM ledger's phase-stamped
  device-memory sample — allocator peaks where the backend exposes
  them, a ``live_arrays`` byte-sum high-water elsewhere),
  ``memory_report`` (the AOT-vs-analytic compile-time byte account),
  ``data`` (host decode-pool counters on real-data runs),
  ``trace_buckets`` (the post-run trace attribution when profiling ran),
  and a final ``summary`` (the BenchmarkResult fields).

Multi-process runs write from process 0 only: the driver's metrics are
already globally aggregated (the loss is psum'd across the mesh, rates
are computed from the global batch — the ``utils/sync`` timeline
observes global step completion), so worker 0's view IS the merged
record and the writer no-ops elsewhere.

``read_run`` / ``summarize_run`` / ``diff_runs`` are pure file
operations (no jax backend touch) so the CLI works on artifacts from
any machine.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time
from typing import Any

SCHEMA_VERSION = 1

METRICS_NAME = "metrics.jsonl"
MANIFEST_NAME = "manifest.json"


# ---------------------------------------------------------------------
# manifest


def _git_sha() -> str:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "-C", repo, "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=5)
        if out.returncode == 0:
            return out.stdout.strip()
    except (OSError, subprocess.TimeoutExpired):
        pass
    return "unknown"


#: the run-identity fields every BENCH-style JSON record embeds — ONE
#: spelling so bench.py's two entries and scripts/bench_serve.py can
#: never drift apart
MANIFEST_IDENTITY_KEYS = (
    "git_sha", "jax_version", "jaxlib_version", "platform",
    "device_kind", "process_count", "device_count", "created_unix")


def manifest_subset(manifest: dict) -> dict:
    """The BENCH-record identity slice of a full run manifest."""
    return {k: manifest.get(k) for k in MANIFEST_IDENTITY_KEYS}


def run_manifest(cfg: Any = None, layout: Any = None, mesh: Any = None,
                 fabric: str | None = None,
                 extra: dict | None = None) -> dict:
    """Assemble the run manifest.  Needs a live jax backend (versions,
    world size); everything is best-effort so a manifest never kills a
    benchmark run."""
    import jax

    m: dict[str, Any] = {
        "schema": SCHEMA_VERSION,
        "created_unix": time.time(),
        "argv": list(sys.argv),
        "git_sha": _git_sha(),
        "jax_version": jax.__version__,
    }
    try:
        import jaxlib

        m["jaxlib_version"] = jaxlib.__version__
    except Exception:
        m["jaxlib_version"] = "unknown"
    try:
        m["process_count"] = jax.process_count()
        m["device_count"] = jax.device_count()
        m["platform"] = jax.devices()[0].platform
        m["device_kind"] = jax.devices()[0].device_kind
    except Exception:
        pass
    if cfg is not None:
        d = dataclasses.asdict(cfg)
        m["config"] = {k: v for k, v in d.items() if k != "translations"}
        m["translations"] = d.get("translations", {})
        m["model"] = getattr(cfg, "model", None)
    if layout is not None:
        m["num_hosts"] = getattr(layout, "num_hosts", None)
        m["total_workers"] = getattr(layout, "total_workers", None)
    if mesh is not None:
        try:
            m["mesh_shape"] = {str(k): int(v)
                               for k, v in dict(mesh.shape).items()}
        except Exception:
            m["mesh_shape"] = None
    if fabric is not None:
        m["fabric"] = fabric
    if extra:
        m.update(extra)
    return m


def device_memory_stats() -> dict:
    """Peak/live HBM bytes per local device, where the backend exposes
    them (TPU does; the CPU test mesh returns nothing)."""
    import jax

    out: dict[str, Any] = {}
    try:
        for d in jax.local_devices():
            stats = d.memory_stats()
            if not stats:
                continue
            out[f"d{d.id}"] = {
                k: stats[k] for k in ("bytes_in_use", "peak_bytes_in_use",
                                      "bytes_limit") if k in stats
            }
    except Exception:
        return {}
    return out


# ---------------------------------------------------------------------
# writer


class MetricsWriter:
    """Append-only JSONL stream + manifest for one run.

    Disabled (every method a no-op) when ``out_dir`` is falsy or this is
    not process 0 — call sites never branch.  The manifest is written
    eagerly at construction so even a crashed run identifies itself.

    Transient write errors retry with bounded backoff
    (``resilience.retry``); a stream that keeps failing disables itself
    with a stderr warning rather than killing a benchmark run over
    telemetry.  ``last_record`` keeps the most recent record in memory —
    the watchdog dumps it alongside the thread stacks when a run hangs.
    """

    def __init__(self, out_dir: str | None, manifest: dict | None = None,
                 primary: bool | None = None):
        self._f = None
        self.last_record: dict | None = None
        if not out_dir:
            return
        if primary is None:
            import jax

            primary = jax.process_index() == 0
        if not primary:
            return
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        if manifest is not None:
            self._write_manifest(manifest)
        self._f = open(os.path.join(out_dir, METRICS_NAME), "w")

    def _write_manifest(self, manifest: dict) -> None:
        # tmp -> fsync -> rename: update_manifest rewrites an already-
        # good manifest, and a crash mid-rewrite must not destroy the
        # identity record the eager construction-time write guaranteed
        path = os.path.join(self.out_dir, MANIFEST_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=2, default=str)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def update_manifest(self, fields: dict) -> None:
        """Merge ``fields`` into the on-disk manifest.

        The manifest is written eagerly at construction (so even a
        crashed run identifies itself), but some identity facts only
        exist later — compile-cache hit/miss is known after warmup.
        Best-effort: a manifest amendment must never kill a run.
        """
        if self._f is None:
            return
        try:
            path = os.path.join(self.out_dir, MANIFEST_NAME)
            with open(path) as f:
                manifest = json.load(f)
            manifest.update(fields)
            self._write_manifest(manifest)
        except (OSError, json.JSONDecodeError) as e:
            sys.stderr.write(f"WARNING: manifest update failed: {e}\n")

    @property
    def enabled(self) -> bool:
        return self._f is not None

    def event(self, kind: str, **fields) -> None:
        rec = {"kind": kind}
        rec.update(fields)
        self.last_record = rec
        if self._f is None:
            return
        from tpu_hc_bench.resilience.retry import retry_io

        line = json.dumps(rec, default=str) + "\n"
        # a failed flush can leave ANY prefix of the line on disk (the
        # rest sat in the userspace buffer), so a blind re-append could
        # produce a corrupt fragment OR a duplicated record; rewinding
        # to the pre-write offset makes the retry idempotent
        pos = self._f.tell()

        def _write():
            self._f.seek(pos)
            self._f.truncate()
            self._f.write(line)
            self._f.flush()

        try:
            retry_io(_write, what=f"metrics write ({kind})",
                     attempts=3, base_delay_s=0.05)
        except OSError as e:
            sys.stderr.write(
                f"WARNING: metrics stream disabled after repeated I/O "
                f"errors: {e}\n")
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None

    def close(self) -> None:
        """Flush AND fsync before closing: the watchdog exit-70 and
        preempt exit-75 paths call this as their very last act, and the
        tail of the stream (the watchdog_dump/preempt record that
        explains the death) must reach the disk, not just the page
        cache, before the process is gone."""
        if self._f is not None:
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            except OSError:
                pass        # closing a dying stream must never raise
            self._f.close()
            self._f = None


# ---------------------------------------------------------------------
# reading / summarize / diff (pure file ops — no jax)


def read_jsonl(path: str) -> list[dict]:
    """Tolerant JSONL read: blank and corrupt lines skipped (a stream
    interrupted by the very death it documents must still render), an
    unreadable file is an empty list.  The ONE copy of this loop —
    heartbeat files, fleet journals, and harvest all read through it.
    """
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    return out


def resolve_run(path: str) -> tuple[str | None, str]:
    """Resolve a run path to ``(manifest_path_or_None, metrics_path)``.

    Accepts the metrics directory or the ``metrics.jsonl`` file itself;
    the manifest is looked up next to the stream.
    """
    if os.path.isdir(path):
        metrics = os.path.join(path, METRICS_NAME)
    else:
        metrics = path
    if not os.path.isfile(metrics):
        raise FileNotFoundError(f"no {METRICS_NAME} at {path}")
    manifest = os.path.join(os.path.dirname(metrics), MANIFEST_NAME)
    return (manifest if os.path.isfile(manifest) else None), metrics


def read_run(path: str,
             problems: list[str] | None = None) -> tuple[dict, list[dict]]:
    """Load ``(manifest, records)`` for a run (manifest {} if absent).

    Tolerant of a degraded run dir — a missing manifest (the writer
    died before its eager manifest landed, or only the jsonl was
    copied), a corrupt manifest, or corrupt/truncated jsonl lines (a
    write interrupted mid-flush, a process killed mid-append).  Each
    degradation is reported as one clear line: appended to
    ``problems`` when the caller passes a list (the CLI turns a
    non-empty list into a nonzero exit), else written to stderr.
    Raises ``FileNotFoundError`` only when there is no metrics stream
    at all — then there is nothing to degrade to.
    """
    def note(msg: str) -> None:
        if problems is not None:
            problems.append(msg)
        else:
            sys.stderr.write(f"WARNING: {msg}\n")

    manifest_path, metrics_path = resolve_run(path)
    manifest = {}
    if manifest_path is None:
        note(f"{os.path.dirname(metrics_path) or '.'}: no "
             f"{MANIFEST_NAME} (crashed before the eager manifest "
             f"write, or a partial copy?)")
    else:
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            note(f"{manifest_path}: unreadable manifest ({e}); "
                 f"rendering records without run identity")
            manifest = {}
    records = []
    corrupt = 0
    with open(metrics_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                corrupt += 1
    if corrupt:
        note(f"{metrics_path}: skipped {corrupt} corrupt/truncated "
             f"line(s) (interrupted write?)")
    return manifest, records


# resilience-event record kinds (tpu_hc_bench.resilience): surfaced by
# summarize_run so a run that skipped/rewound/retried its way to the
# finish line says so instead of passing as clean
RESILIENCE_KINDS = (
    "injected_fault", "nonfinite_skip", "nonfinite_abort", "rewind",
    "emergency_ckpt", "preempt", "watchdog_dump", "io_retry",
    # round 23, serve lane: every shed and quarantined request is a
    # resilience event with a cause — degraded service must be visible
    "shed", "quarantine",
)

#: per-kind cap on detail lines in summarize (an overload run sheds
#: hundreds of requests; the counts line carries the totals)
_RESILIENCE_DETAIL_CAP = 6


def _of_kind(records: list[dict], kind: str) -> list[dict]:
    return [r for r in records if r.get("kind") == kind]


def _last(records: list[dict], kind: str) -> dict | None:
    recs = _of_kind(records, kind)
    return recs[-1] if recs else None


def summarize_run(path: str, fabric_ceiling: str | None = None,
                  problems: list[str] | None = None) -> list[str]:
    """Render one metrics run as text lines.

    ``fabric_ceiling``: path to a ``microbench.osu --json`` sweep
    export; when given, the achieved collective bandwidth (trace
    buckets x wall step time x gradient bytes) is judged against the
    sweep's measured peak.  ``problems`` collects degradation notices
    (see ``read_run``).
    """
    manifest, records = read_run(path, problems=problems)
    lines = [f"run: {path}"]
    if manifest:
        mesh = manifest.get("mesh_shape")
        lines.append(
            f"  model={manifest.get('model')} "
            f"fabric={manifest.get('fabric')} "
            f"world={manifest.get('process_count')}proc/"
            f"{manifest.get('device_count')}dev "
            f"mesh={mesh if mesh else '?'}")
        lines.append(
            f"  jax={manifest.get('jax_version')} "
            f"jaxlib={manifest.get('jaxlib_version')} "
            f"git={str(manifest.get('git_sha', '?'))[:12]} "
            f"platform={manifest.get('platform')}")
    windows = _of_kind(records, "window")
    if windows:
        lines.append(f"  {'step':>6s} {'ex/sec':>10s} {'step_ms':>9s} "
                     f"{'loss':>8s}")
        for w in windows:
            lines.append(
                f"  {w.get('step', '?'):>6} {w.get('rate', 0.0):10.1f} "
                f"{w.get('step_ms', 0.0):9.2f} {w.get('loss', 0.0):8.3f}")
    # serving lane (round 16): request/serve records fold into the SLO
    # section; a stream with ONLY serving records (no step-keyed
    # metrics at all) is a normal serving run, not a degraded training
    # one — label it instead of rendering an empty table
    from tpu_hc_bench.serve import slo as slo_mod

    serve_fold = slo_mod.fold_serve_records(records)
    if serve_fold is not None:
        if not windows and not _last(records, "summary"):
            lines.append("  serving run (request-keyed metrics; no "
                         "step-keyed training records)")
        lines.extend(slo_mod.slo_lines(serve_fold))
    summary = _last(records, "summary")
    if summary:
        lines.append(
            f"  total: {summary.get('total_images_per_sec', 0.0):.2f} "
            f"ex/s  mean {summary.get('mean_step_ms', 0.0):.2f}ms  "
            f"p50 {summary.get('p50_step_ms', 0.0):.2f}ms"
            f" (granularity {summary.get('p50_step_granularity', '?')} "
            f"step)  MFU {100 * summary.get('mfu', 0.0):.1f}%")
        # round 24: the training lane's merged step-time sketch —
        # per-rank window sketches off the stream, merged bucket-wise
        # (a multi-stream concat folds to the true fleet-wide tail)
        from tpu_hc_bench.obs import sketch as sketch_mod

        step_sk = sketch_mod.merge_records(
            (r.get("fields") or {}).get("step_ms")
            for r in records if r.get("kind") == "latency_sketch")
        if step_sk is not None and step_sk.count:
            lines.append(
                f"  step ms [sketch, merged] "
                f"p50 {step_sk.quantile(50):.2f}  "
                f"p95 {step_sk.quantile(95):.2f}  "
                f"p99 {step_sk.quantile(99):.2f}")
        from tpu_hc_bench.obs import efficiency as eff_mod

        lines.extend(eff_mod.mfu_lines(summary))
    # goodput ledger: fold the phase transitions + resilience events
    # into the wall-clock account (runs predating the ledger render
    # without it)
    from tpu_hc_bench.obs import fleet as fleet_mod
    from tpu_hc_bench.obs import goodput as goodput_mod

    ledger = goodput_mod.build_ledger(records)
    if ledger is not None:
        lines.extend("  " + ln for ln in ledger.format_lines())
    commits = _of_kind(records, "checkpoint_commit")
    if commits:
        total_w = sum(float(c.get("write_s", 0) or 0) for c in commits)
        lines.append(f"  async checkpoints: {len(commits)} landed, "
                     f"{total_w:.2f}s of writes overlapped with the "
                     f"step loop")
    run_dir = None
    try:
        run_dir = os.path.dirname(resolve_run(path)[1])
        lines.extend(fleet_mod.straggler_lines(run_dir, records))
    except FileNotFoundError:
        pass
    data = _last(records, "data")
    if data:
        lines.append(
            f"  data: {data.get('examples', 0)} examples decoded, "
            f"{data.get('decode_workers', '?')} workers, "
            f"{data.get('decode_wall_s', 0.0):.1f}s decode wall")
    # input plane (real-data runs): data_wait fraction + service ring
    # backpressure — the "is the host keeping the chips fed" line
    lines.extend(fleet_mod.input_lines(run_dir, records, ledger))
    # measured memory (obs.memory): the runtime HBM ledger's per-phase
    # peaks + the AOT-vs-analytic compile-time report, and any OOM/
    # emergency forensics dump the run left behind
    from tpu_hc_bench.obs import memory as mem_mod

    lines.extend(mem_mod.memory_lines(
        mem_mod.fold_memory_records(records)))
    mem_rep = _last(records, "memory_report")
    if mem_rep:
        lines.extend(mem_mod.memory_report_lines(mem_rep))
    budget = _last(records, "hbm_budget")
    if budget:
        lines.append(
            f"  hbm budget: {'EXCEEDED' if budget.get('exceeded') else 'ok'}"
            f" (AOT {budget.get('total_bytes', 0) / 2**30:.2f} GiB vs "
            f"budget {budget.get('budget_bytes', 0) / 2**30:.2f} GiB)")
    dump = _last(records, "memory_dump")
    if dump:
        lines.append(
            f"  memory dump: {dump.get('path')} "
            f"(reason {dump.get('reason')}, step {dump.get('step')})")
    # flight-recorder timeline (obs.timeline): per-rank span totals with
    # the dominant waits, the cross-rank bubble, and any
    # timeline_dump.json forensics the run left behind
    from tpu_hc_bench.obs import timeline as timeline_mod

    lines.extend(timeline_mod.timeline_lines(run_dir))
    resume = _last(records, "resume")
    if resume:
        # elastic-resume identity: a post-resume throughput shift with a
        # world-size change is a different experiment, not a regression
        lines.append(
            f"  resume: step {resume.get('restored_step')}  world "
            f"{resume.get('saved_world')}->{resume.get('live_world')}  "
            f"arm={resume.get('arm')}"
            + (" (elastic reshard)" if resume.get("elastic") else ""))
    res = [r for r in records if r.get("kind") in RESILIENCE_KINDS]
    if res:
        counts: dict[str, int] = {}
        for r in res:
            counts[r["kind"]] = counts.get(r["kind"], 0) + 1
        lines.append("  resilience: " + "  ".join(
            f"{k}x{counts[k]}" for k in RESILIENCE_KINDS if k in counts))
        shown: dict[str, int] = {}
        for r in res:
            shown[r["kind"]] = shown.get(r["kind"], 0) + 1
            if shown[r["kind"]] > _RESILIENCE_DETAIL_CAP:
                continue
            detail = " ".join(f"{k}={v}" for k, v in r.items()
                              if k != "kind")
            lines.append(f"    {r['kind']}: {detail}")
        for kind, n in shown.items():
            if n > _RESILIENCE_DETAIL_CAP:
                lines.append(f"    {kind}: ... "
                             f"+{n - _RESILIENCE_DETAIL_CAP} more")
    tb = _last(records, "trace_buckets")
    if tb and tb.get("buckets"):
        total = sum(tb["buckets"].values()) or 1.0
        parts = ", ".join(f"{k} {v / total:.1%}"
                          for k, v in sorted(tb["buckets"].items(),
                                             key=lambda kv: -kv[1]))
        lines.append(f"  trace buckets: {parts}")
    from tpu_hc_bench.obs import efficiency as eff_mod

    if tb and tb.get("overlap"):
        # --overlap_grad_comm attribution: how much of the collective
        # wall ran exposed vs hidden behind concurrent compute
        lines.extend(eff_mod.overlap_lines(tb["overlap"]))
    if fabric_ceiling:
        ceiling = eff_mod.load_fabric_ceiling(fabric_ceiling)
        lines.extend(eff_mod.ceiling_utilization_lines(
            summary or {}, tb, ceiling))
    else:
        # no sweep supplied: still report the achieved gradient-
        # collective bandwidth in absolute GB/s (previously this line
        # was ceiling-gated and a sweep-less run printed nothing)
        lines.extend(eff_mod.collective_busbw_lines(summary or {}, tb))
    return lines


def _pct(a: float, b: float) -> str:
    if a:
        return f"{(b - a) / a:+.1%}"
    return "new" if b else "-"


def diff_runs(path_a: str, path_b: str,
              problems: list[str] | None = None) -> list[str]:
    """Compare two metrics runs: headline metrics, per-bucket trace
    deltas, and any resolved-flag differences."""
    from tpu_hc_bench.obs import trace as trace_mod

    man_a, recs_a = read_run(path_a, problems=problems)
    man_b, recs_b = read_run(path_b, problems=problems)
    lines = [f"diff: {path_a} -> {path_b}"]

    # resolved-flag drift: a perf delta with a config delta is not a
    # regression, it is a different experiment — say so first.  For
    # output-LOCATION flags only presence matters: two clean A/B runs
    # necessarily write to different paths (noise on every diff), but
    # set-vs-unset IS behavioral drift (checkpoint saves sync the
    # device, profiling perturbs the window)
    path_flags = {"metrics_dir", "trace_dir", "train_dir",
                  "fabric_ceiling"}
    cfg_a, cfg_b = man_a.get("config", {}), man_b.get("config", {})

    def _cmp(cfg, k):
        v = cfg.get(k)
        return (v is not None) if k in path_flags else v

    changed = {k for k in set(cfg_a) | set(cfg_b)
               if _cmp(cfg_a, k) != _cmp(cfg_b, k)}
    for k in sorted(changed):
        lines.append(f"  config: {k}: {cfg_a.get(k)!r} -> {cfg_b.get(k)!r}")
    for k in ("jax_version", "jaxlib_version", "git_sha", "device_kind",
              "process_count", "device_count"):
        if man_a.get(k) != man_b.get(k):
            lines.append(f"  env: {k}: {man_a.get(k)} -> {man_b.get(k)}")

    sum_a = _last(recs_a, "summary") or {}
    sum_b = _last(recs_b, "summary") or {}
    metrics = (
        ("total ex/s", "total_images_per_sec"),
        ("ex/s/chip", "images_per_sec_per_chip"),
        ("mean step ms", "mean_step_ms"),
        ("p50 step ms", "p50_step_ms"),
        ("mfu", "mfu"),
        ("goodput", "goodput"),
        ("final loss", "final_loss"),
    )
    rows = [(label, key) for label, key in metrics
            if key in sum_a or key in sum_b]
    if rows:
        lines.append(f"  {'metric':>14s} {'a':>12s} {'b':>12s} "
                     f"{'delta':>8s}")
        for label, key in rows:
            va, vb = sum_a.get(key, 0.0), sum_b.get(key, 0.0)
            lines.append(f"  {label:>14s} {va:12.4g} {vb:12.4g} "
                         f"{_pct(va, vb):>8s}")
    # serving lane: p99/goodput/tokens-per-s deltas when both runs
    # carry request-keyed records (step-free serving runs diff cleanly
    # instead of rendering an empty training table)
    from tpu_hc_bench.serve import slo as slo_mod

    lines.extend(slo_mod.serve_diff_lines(
        slo_mod.fold_serve_records(recs_a),
        slo_mod.fold_serve_records(recs_b)))
    src_a = sum_a.get("mfu_source")
    src_b = sum_b.get("mfu_source")
    if (src_a or src_b) and src_a != src_b:
        # measured-vs-analytic MFUs are different quantities; say so
        # before anyone reads the delta row as a regression
        lines.append(f"  note: MFU flops source differs: "
                     f"{src_a or '?'} -> {src_b or '?'}")

    # ledger phase deltas: where the non-productive wall moved — a warm
    # compile cache shows up as the compile row collapsing, async
    # checkpointing as checkpoint(blocking) -> checkpoint_async(small)
    from tpu_hc_bench.obs import goodput as goodput_mod

    led_a = goodput_mod.build_ledger(recs_a)
    led_b = goodput_mod.build_ledger(recs_b)
    if led_a is not None and led_b is not None:
        rows = [p for p in goodput_mod.PHASES
                if (led_a.seconds.get(p, 0.0) > 0.0
                    or led_b.seconds.get(p, 0.0) > 0.0)]
        if rows:
            lines.append("  ledger phases (wall s):")
            for p in rows:
                va = led_a.seconds.get(p, 0.0)
                vb = led_b.seconds.get(p, 0.0)
                lines.append(f"  {p:>14s} {va:12.2f} {vb:12.2f} "
                             f"{_pct(va, vb):>8s}")
        # input-plane delta: the fraction of wall blocked on the input
        # pipeline — the input-service A/B's headline row
        fa = (led_a.seconds.get("data_wait", 0.0) / led_a.wall_s
              if led_a.wall_s > 0 else 0.0)
        fb = (led_b.seconds.get("data_wait", 0.0) / led_b.wall_s
              if led_b.wall_s > 0 else 0.0)
        if fa > 0.0 or fb > 0.0:
            lines.append(f"  {'data_wait frac':>14s} {fa:12.4f} "
                         f"{fb:12.4f} {_pct(fa, fb):>8s}")

    tb_a = _last(recs_a, "trace_buckets")
    tb_b = _last(recs_b, "trace_buckets")
    if tb_a and tb_b and tb_a.get("buckets") and tb_b.get("buckets"):
        lines.append("  trace buckets (device us):")
        lines.extend("  " + ln for ln in trace_mod.diff_buckets(
            tb_a["buckets"], tb_b["buckets"], label_a="a", label_b="b"))
    # memory deltas (obs.memory): runtime high-water + the AOT report's
    # byte classes — a batch/accum change shows up here as temp bytes
    # moving while args stay flat, BEFORE anything OOMs
    from tpu_hc_bench.obs import memory as mem_mod

    fold_a = mem_mod.fold_memory_records(recs_a)
    fold_b = mem_mod.fold_memory_records(recs_b)
    if fold_a and fold_b:
        pa, pb = fold_a["peak_bytes"], fold_b["peak_bytes"]
        lines.append(f"  {'peak HBM MiB':>14s} {pa / 2**20:12.1f} "
                     f"{pb / 2**20:12.1f} {_pct(pa, pb):>8s}")
        if (fold_a.get("peak_phase") != fold_b.get("peak_phase")
                and (fold_a.get("peak_phase") or fold_b.get("peak_phase"))):
            lines.append(f"  note: memory high-water phase differs: "
                         f"{fold_a.get('peak_phase') or '?'} -> "
                         f"{fold_b.get('peak_phase') or '?'}")
    rep_a = _last(recs_a, "memory_report") or {}
    rep_b = _last(recs_b, "memory_report") or {}
    ma, mb = rep_a.get("measured") or {}, rep_b.get("measured") or {}
    if ma and mb:
        for label, key in (("aot args MiB", "argument_bytes"),
                           ("aot temp MiB", "temp_bytes"),
                           ("aot out MiB", "output_bytes")):
            va, vb = ma.get(key, 0), mb.get(key, 0)
            if va or vb:
                lines.append(
                    f"  {label:>14s} {va / 2**20:12.1f} "
                    f"{vb / 2**20:12.1f} {_pct(va, vb):>8s}")
    return lines
