"""Noise-aware regression gate over the BENCH-json history.

``obs diff`` answers "what changed between these two runs"; this module
answers the CI question "is this fresh number a *real* regression
against everything we have measured before" — without a human picking
the comparison run, and without a fixed percentage threshold that
either cries wolf on a noisy metric or sleeps through a drift on a
quiet one.

Mechanism: prior BENCH records (the one-line JSON ``bench.py`` prints,
or the ``{"parsed": ...}`` wrapper the driver harness saves as
``BENCH_*.json``) are grouped by **config fingerprint** — metric name
plus the identity fields that make numbers comparable (global batch,
chips, dtype, gradient arm, device kind).  For each checked metric the
history's **median** is the center and its **MAD** (median absolute
deviation, scaled by 1.4826 to a sigma equivalent) is the noise scale;
a fresh value regresses when it is worse than the median by more than
``max(mad_k * sigma, rel_floor * |median|)`` — the MAD term adapts to
each metric's own run-to-run noise, the relative floor keeps a
perfectly-quiet history (MAD 0) from flagging measurement jitter.
Direction is per metric: throughput/goodput regress DOWN, latency
p99s and HBM peaks regress UP.  An unchanged rerun always passes
(delta 0 < any threshold); improvements never flag.

Wire-in: ``python -m tpu_hc_bench.obs regress fresh.json --history
'BENCH_*.json'`` (exit 0 pass / 1 regression / 2 unusable input), and
``BENCH_REGRESS=1`` makes ``bench.py`` gate its own exit code on the
check after printing the JSON line.
"""

from __future__ import annotations

import glob
import json
import os
import statistics

#: metric spec: (record path, direction, label).  "higher" = regression
#: is a DROP below the history median; "lower" = a RISE above it.
CHECKS = (
    (("value",), "higher", "headline"),
    (("extra", "goodput"), "higher", "goodput"),
    (("extra", "tokens_per_s"), "higher", "tokens/s"),
    (("extra", "p99_ms"), "lower", "p99 e2e ms"),
    (("extra", "p99_ttft_ms"), "lower", "p99 ttft ms"),
    (("extra", "peak_hbm_bytes"), "lower", "peak HBM bytes"),
    # round 18: the decode-kernel win — a rise in the worst decode
    # bucket's AOT temp bytes means the paged arm regressed toward the
    # dense-gather temporaries it exists to eliminate
    (("extra", "aot_decode_temp_bytes"), "lower", "aot decode temp B"),
    # round 19: the fleet soak — goodput-weighted chip-seconds over
    # pool chip-seconds under churn; a drop means the scheduler started
    # wasting the pool (thrash, slow readmission, orphaned capacity)
    (("extra", "fleet_goodput"), "higher", "fleet goodput"),
    # round 20: attribution shift — the slowest decile's e2e share
    # spent waiting (admission queue / resident-but-starved).  A rise
    # means the tail moved from compute to waiting even if p99 itself
    # sits inside the noise band; pre-r20 serve history simply lacks
    # the fields and the checks skip (never KeyError)
    (("extra", "tail_queue_wait_frac"), "lower", "tail queue_wait frac"),
    (("extra", "tail_decode_stall_frac"), "lower",
     "tail decode_stall frac"),
    # round 22 (obs.kv): allocation honesty — written-page-seconds over
    # reserved-page-seconds.  A DROP means admission got more
    # pessimistic (or outputs shortened against a fixed reservation)
    # and the pool wastes more of its bytes; pre-r22 serve history
    # lacks the field and the check skips (never KeyError)
    (("extra", "kv_pool_util"), "higher", "kv pool util"),
    # round 23: overload degradation — the fraction of the trace shed
    # by deadline policy.  A RISE means the engine keeps capacity by
    # refusing more work (capacity regression or an over-eager shed
    # heuristic); pre-r23 history lacks the field and the check skips
    (("extra", "shed_frac"), "lower", "shed frac"),
    # round 24: the fleet-wide merged-sketch tail (the per-window
    # sketches merged bucket-wise — exact across ranks, not an average
    # of per-host p99s) and the health-signal count.  Pre-r24 history
    # lacks both fields and the checks skip structurally (never
    # KeyError), the kv_pool_util precedent.
    (("extra", "p99_merged_ms"), "lower", "p99 merged ms"),
    (("extra", "signals_fired_total"), "lower", "signals fired"),
    # round 25 (obs.kv): the shared-prefix hit rate.  A DROP means the
    # cache stopped matching traffic it used to match (an eviction
    # policy regression, a trie keying bug, or admission bypassing the
    # cache) and the pool re-pays prefill writes it had been sharing;
    # pre-r25 history (and cache-off runs) lack the field and the
    # check skips structurally (never KeyError)
    (("extra", "prefix_hit_frac"), "higher", "prefix hit frac"),
)

#: identity fields folded into the fingerprint (record path order)
FINGERPRINT_KEYS = (
    ("metric",), ("unit",),
    ("extra", "global_batch"), ("extra", "chips"), ("extra", "dtype"),
    ("extra", "variable_update"), ("extra", "batching"),
    ("extra", "arrival_rate"),
    # round 18: the kernel/quant arms are config identity, not noise —
    # a gather-vs-paged pair must never share a history fingerprint
    ("extra", "decode_attention"), ("extra", "quant"),
    # round 25: the reservation/sharing arms likewise — a lazy+prefix
    # run must never gate against worst-case-reservation history
    ("extra", "kv_reserve"), ("extra", "prefix_cache"),
    ("manifest", "device_kind"), ("manifest", "process_count"),
)

# absent fingerprint keys normalize to the value older records
# effectively ran with, so pre-round-18 serve history keeps comparing
# against fresh default-arm runs instead of being silently orphaned
_FINGERPRINT_DEFAULTS = {
    ("extra", "decode_attention"): "gather",
    ("extra", "quant"): "off",
    # pre-round-25 serve history effectively ran worst-case
    # reservation with no prefix cache
    ("extra", "kv_reserve"): "worst",
    ("extra", "prefix_cache"): "off",
}

DEFAULT_MAD_K = 4.0
DEFAULT_REL_FLOOR = 0.03

#: absolute noise floors by metric label.  The relative floor protects
#: quiet histories only when the median is nonzero — a FRACTION metric
#: (round 20's attribution shares) legitimately sits at exactly 0.0 in
#: a well-provisioned config's history, where rel_floor*|0| = 0 would
#: flag any positive jitter; 5pp is the smallest shift worth a human.
ABS_FLOORS = {
    "tail queue_wait frac": 0.05,
    "tail decode_stall frac": 0.05,
    # round 22: utilization is a fraction with the same jitter shape
    "kv pool util": 0.05,
    # round 23: shed fraction is 0.0 in any well-provisioned history
    "shed frac": 0.05,
    # round 24: fired-signal counts sit at exactly 0 in a healthy
    # history — ONE fire is the smallest shift worth a human, so the
    # floor sits just under it (worse must EXCEED the threshold)
    "signals fired": 0.5,
    # round 25: the hit rate is a fraction with the same jitter shape
    # as the r20/r22 shares
    "prefix hit frac": 0.05,
}


def _get(rec: dict, path: tuple[str, ...]):
    cur = rec
    for k in path:
        if not isinstance(cur, dict):
            return None
        cur = cur.get(k)
    return cur


def load_bench_record(path: str) -> dict | None:
    """A BENCH record from any of its on-disk shapes: the bare JSON
    line, the harness wrapper (``{"parsed": {...}, "tail": "..."}``), or
    a tail whose last JSON-looking line is the record.  None when
    nothing parses — the caller reports, never raises."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(data, dict):
        return None
    if "metric" in data and "value" in data:
        return data
    parsed = data.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed:
        return parsed
    tail = data.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and "metric" in rec:
                    return rec
    return None


def fingerprint(rec: dict) -> tuple:
    return tuple(
        _FINGERPRINT_DEFAULTS.get(path) if _get(rec, path) is None
        else _get(rec, path)
        for path in FINGERPRINT_KEYS)


def load_history(specs: list[str],
                 exclude: str | None = None) -> list[tuple[str, dict]]:
    """Expand history specs (files, dirs, globs) into parsed records.
    A dir means every ``*.json`` directly under it; ``exclude`` drops
    the fresh record's own path so a gate never compares a file against
    itself."""
    paths: list[str] = []
    for spec in specs:
        if os.path.isdir(spec):
            paths.extend(sorted(glob.glob(os.path.join(spec, "*.json"))))
        elif any(c in spec for c in "*?["):
            paths.extend(sorted(glob.glob(spec)))
        elif os.path.isfile(spec):
            paths.append(spec)
    out = []
    seen = set()
    excl = os.path.abspath(exclude) if exclude else None
    for p in paths:
        ap = os.path.abspath(p)
        if ap in seen or ap == excl:
            continue
        seen.add(ap)
        rec = load_bench_record(p)
        if rec is not None:
            out.append((p, rec))
    return out


def regress_check(fresh: dict, history: list[dict],
                  mad_k: float = DEFAULT_MAD_K,
                  rel_floor: float = DEFAULT_REL_FLOOR) -> dict:
    """The verdict: compare ``fresh`` against same-fingerprint history.

    Returns ``{"checked": [...], "regressions": [...], "history_n": N,
    "lines": [...]}`` — ``regressions`` non-empty means the gate fails.
    """
    fp = fingerprint(fresh)
    matched = [h for h in history if fingerprint(h) == fp]
    lines: list[str] = []
    checked: list[dict] = []
    regressions: list[dict] = []
    if not matched:
        lines.append(
            f"regress: no history for fingerprint {fresh.get('metric')} "
            f"(of {len(history)} record(s)) — nothing to gate against")
        return {"checked": checked, "regressions": regressions,
                "history_n": 0, "lines": lines}
    for path, direction, label in CHECKS:
        v = _get(fresh, path)
        if not isinstance(v, (int, float)):
            continue
        hist = [_get(h, path) for h in matched]
        hist = [float(x) for x in hist if isinstance(x, (int, float))]
        if not hist:
            continue
        med = statistics.median(hist)
        sigma = 1.4826 * statistics.median(abs(x - med) for x in hist)
        threshold = max(mad_k * sigma, rel_floor * abs(med),
                        ABS_FLOORS.get(label, 0.0))
        worse = (med - float(v)) if direction == "higher" \
            else (float(v) - med)
        entry = {"metric": label, "value": float(v), "median": med,
                 "sigma": round(sigma, 6), "threshold": round(threshold, 6),
                 "delta_worse": round(worse, 6), "n": len(hist),
                 "direction": direction}
        checked.append(entry)
        verdict = "REGRESSION" if worse > threshold else "ok"
        rel = (worse / abs(med)) if med else 0.0
        lines.append(
            f"regress: {label}: {v:.6g} vs median {med:.6g} "
            f"(n={len(hist)}, sigma {sigma:.3g}, threshold "
            f"{threshold:.3g}) -> {verdict}"
            + (f" ({rel:+.1%} worse)" if verdict == "REGRESSION" else ""))
        if verdict == "REGRESSION":
            regressions.append(entry)
    if not checked:
        lines.append("regress: matched history carries none of the "
                     "checked metrics — nothing to gate against")
    return {"checked": checked, "regressions": regressions,
            "history_n": len(matched), "lines": lines}


def run_regress(fresh_path_or_rec, history_specs: list[str] | None,
                out=None, mad_k: float = DEFAULT_MAD_K,
                rel_floor: float = DEFAULT_REL_FLOOR) -> int:
    """CLI/bench entry.  Exit codes: 0 pass (including no-history),
    1 significant regression, 2 unusable fresh record."""
    import sys

    out = out or sys.stdout
    if isinstance(fresh_path_or_rec, dict):
        fresh, fresh_path = fresh_path_or_rec, None
    else:
        fresh_path = fresh_path_or_rec
        fresh = load_bench_record(fresh_path)
    if fresh is None:
        print(f"error: no BENCH record parseable at {fresh_path}",
              file=out)
        return 2
    specs = history_specs or ["BENCH_*.json", "artifacts"]
    history = [rec for _, rec in load_history(specs, exclude=fresh_path)]
    verdict = regress_check(fresh, history, mad_k=mad_k,
                            rel_floor=rel_floor)
    for ln in verdict["lines"]:
        print(ln, file=out)
    if verdict["regressions"]:
        names = ", ".join(r["metric"] for r in verdict["regressions"])
        print(f"regress: FAIL — significant regression in: {names}",
              file=out)
        return 1
    print(f"regress: pass ({len(verdict['checked'])} metric(s) against "
          f"{verdict['history_n']} matching record(s))", file=out)
    return 0
