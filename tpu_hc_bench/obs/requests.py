"""Per-request lifecycle ledger: tail-latency attribution for serving.

The serving lane's SLO report (``serve/slo.py``) folds endpoint
percentiles — it can say *that* the p99 is 412ms but not *why*.  The
production answer (Orca/vLLM-class systems treat it as table stakes) is
a per-request decomposition of end-to-end wall into named, **conserved**
components, stamped by the engine from bookkeeping it already tracks:

- ``queue_wait``    — arrival -> admission (backpressure; the cheapest
  leading indicator of overload);
- ``prefill``       — admission -> first token (the request's own
  prompt pass);
- ``decode_active`` — decode-step wall while this request was resident
  AND the step produced it a token (useful work);
- ``decode_stall``  — resident but starved: batch-mate prefills,
  scheduler gaps between steps, bucket bookkeeping (the batching-
  interference component endpoint percentiles cannot see);
- ``retire_overhead`` — last token -> retirement record.

**Conservation invariant**: the five components sum to the measured
e2e wall per request — exactly under ``VirtualClock`` (``decode_stall``
is computed as the measured remainder of measured instants, so the
identity holds by arithmetic, not by hope) and within rounding on a
real clock.  Pinned by test.

Pure record processing by the ``slo.py`` contract: NO jax import —
``obs summarize``/``diff``/``timeline`` render artifacts copied off a
TPU VM on a laptop.  Pre-round-20 streams (no component fields)
normalize to zero components and render labeled, never KeyError.
"""

from __future__ import annotations

#: component name -> the flat key on the ``request`` metrics record.
#: ``queue_wait`` reuses the round-16 ``queue_ms`` field (it has been on
#: every record since the lane opened — same instant pair).
COMPONENTS = (
    ("queue_wait", "queue_ms"),
    ("prefill", "prefill_ms"),
    ("decode_active", "decode_active_ms"),
    ("decode_stall", "decode_stall_ms"),
    ("retire_overhead", "retire_ms"),
)

COMPONENT_NAMES = tuple(name for name, _ in COMPONENTS)

#: fields that only round-20+ records carry (``queue_ms`` predates the
#: ledger, so it cannot witness component support)
_R20_KEYS = tuple(key for name, key in COMPONENTS if name != "queue_wait")

#: the tail the attribution fold aggregates: slowest decile by e2e
TAIL_FRAC = 0.10

#: synthetic Chrome-trace pid for the per-request lanes (far above any
#: real process index; one tid per request id)
REQUEST_LANE_PID = 1 << 20


def components_ms(arrival_s: float, t_admit: float, t_first: float,
                  t_last: float, t_done: float,
                  active_s: float) -> dict[str, float]:
    """The engine-side stamp: measured instants -> conserved ms fields.

    All instants share one engine clock (relative seconds).  ``t_first``
    is the end of the request's own prefill (classify members pass
    ``t_admit`` — they have no prompt pass, so the whole resident window
    is the decode lane's); ``t_last`` is the end of its last decode
    step; ``active_s`` is the summed wall of decode steps it was
    resident for.  ``decode_stall`` is the *remainder after rounding*,
    so the rounded components sum to the rounded e2e to float precision
    — the conservation invariant is arithmetic, not measurement.
    """
    out = {
        "queue_ms": round(1e3 * (t_admit - arrival_s), 3),
        "prefill_ms": round(1e3 * (t_first - t_admit), 3),
        "decode_active_ms": round(1e3 * active_s, 3),
        "retire_ms": round(1e3 * (t_done - t_last), 3),
    }
    e2e_ms = round(1e3 * (t_done - arrival_s), 3)
    out["decode_stall_ms"] = round(e2e_ms - sum(out.values()), 3)
    return out


def attribution_of(record: dict) -> dict[str, float]:
    """One record's component ms, absent fields normalized to 0.0 —
    the pre-round-20 back-compat seam every consumer reads through."""
    out = {}
    for name, key in COMPONENTS:
        v = record.get(key)
        out[name] = float(v) if isinstance(v, (int, float)) else 0.0
    return out


def has_components(records: list[dict]) -> bool:
    """Whether any record carries round-20 attribution fields (a
    pre-r20 stream folds to all-zero components and must say so
    instead of rendering a confidently-zero decomposition)."""
    return any(any(k in r for k in _R20_KEYS) for r in records)


def fold_attribution(request_records: list[dict],
                     tail_frac: float = TAIL_FRAC) -> dict | None:
    """Aggregate the decomposition over the slowest ``tail_frac`` of
    requests by e2e — "where does the p99 live".

    Returns ``None`` when no request carries an e2e (nothing to fold).
    ``tail_frac`` fractions are of the tail's mean e2e, so they are the
    conserved components' shares (pre-r20 records: all zeros,
    ``has_components`` False).
    """
    rows = [(float(r["e2e_ms"]), attribution_of(r))
            for r in request_records
            if isinstance(r.get("e2e_ms"), (int, float))]
    if not rows:
        return None
    k = max(1, int(round(len(rows) * tail_frac)))
    # round 24: sketch-guided tail selection — a quantile sketch names
    # a guaranteed under-estimate of the true cut (quantile is within
    # alpha relative error, deflated by 2*alpha), so only the
    # candidate superset gets sorted: O(n + tail log tail) instead of
    # O(n log n), and the selected tail is IDENTICAL (Python's stable
    # sort keeps equal-e2e rows in input order either way; the exact
    # full sort remains the fallback when the guard over-prunes)
    from tpu_hc_bench.obs import sketch as sketch_mod

    sk = sketch_mod.QuantileSketch()
    for e, _ in rows:
        sk.add(e)
    guard = sk.quantile(100.0 * (1.0 - k / len(rows))) \
        * (1.0 - 2.0 * sk.alpha)
    cand = [row for row in rows if row[0] >= guard]
    if len(cand) < k:
        cand = list(rows)
    cand.sort(key=lambda x: x[0])
    tail = cand[-k:]
    tail_e2e = sum(e for e, _ in tail) / k
    tail_ms = {name: sum(a[name] for _, a in tail) / k
               for name in COMPONENT_NAMES}
    denom = tail_e2e if tail_e2e > 0 else 1.0
    total_ms = {name: round(sum(a[name] for _, a in rows), 3)
                for name in COMPONENT_NAMES}
    return {
        "n": len(rows),
        "tail_n": k,
        "tail_cut_ms": round(tail[0][0], 3),
        "tail_e2e_ms": round(tail_e2e, 3),
        "tail_ms": {n: round(v, 3) for n, v in tail_ms.items()},
        "tail_frac": {n: round(v / denom, 4) for n, v in tail_ms.items()},
        "total_ms": total_ms,
        "has_components": has_components(request_records),
    }


def flatten_attribution(fold: dict | None) -> dict:
    """The regress/BENCH-extra projection: the two tail fractions the
    noise gate tracks (a rise in either means the tail shifted toward
    waiting — the attribution regression signal)."""
    if not fold:
        return {}
    fr = fold.get("tail_frac", {})
    return {
        "tail_queue_wait_frac": fr.get("queue_wait", 0.0),
        "tail_decode_stall_frac": fr.get("decode_stall", 0.0),
    }


def attribution_lines(fold: dict | None,
                      p99_e2e_ms: float | None = None) -> list[str]:
    """The one summarize line naming where the p99 lives."""
    if not fold:
        return []
    if not fold.get("has_components"):
        return ["  attribution: records carry no component fields "
                "(pre-round-20 stream) — components normalized to 0"]
    parts = sorted(fold["tail_frac"].items(), key=lambda kv: -kv[1])
    shown = [f"{v:.0%} {n}" for n, v in parts if v >= 0.005]
    head = (f"p99 e2e {p99_e2e_ms:.0f}ms"
            if isinstance(p99_e2e_ms, (int, float))
            else f"tail e2e {fold['tail_e2e_ms']:.0f}ms")
    return [
        f"  {head}: " + ", ".join(shown or ["(all components < 0.5%)"])
        + f"   [slowest {fold['tail_n']}/{fold['n']} requests, "
          f"e2e >= {fold['tail_cut_ms']:.0f}ms]"
    ]


def attribution_diff_lines(fold_a: dict | None,
                           fold_b: dict | None) -> list[str]:
    """``obs diff`` rows: per-component tail-fraction deltas in
    percentage points.  A side without attribution (pre-r20 history)
    normalizes to zero components and is labeled, never a KeyError."""
    if not fold_a and not fold_b:
        return []
    fa = (fold_a or {}).get("tail_frac", {})
    fb = (fold_b or {}).get("tail_frac", {})
    lines = ["  tail attribution (% of slowest-decile e2e):"]
    for name in COMPONENT_NAMES:
        va = float(fa.get(name, 0.0))
        vb = float(fb.get(name, 0.0))
        if va == 0.0 and vb == 0.0:
            continue
        lines.append(f"  {name:>14s} {va:11.1%} {vb:11.1%} "
                     f"{100.0 * (vb - va):+7.1f}pp")
    for side, fold in (("a", fold_a), ("b", fold_b)):
        if fold is not None and not fold.get("has_components"):
            lines.append(f"  note: run {side} predates request "
                         "attribution (components read as 0)")
    return lines if len(lines) > 1 else []


# ---------------------------------------------------------------------
# per-bucket utilization


def fold_bucket_util(bucket_util: dict | None) -> list[tuple]:
    """Sorted render rows from the engine's ``bucket_util`` summary
    field: (key, steps, occupancy, wall_s), decode buckets numerically
    ordered within each program kind."""
    if not bucket_util:
        return []

    def _order(item):
        key = item[0]
        kind, _, size = key.partition("@")
        try:
            return (kind, int(size))
        except ValueError:
            return (kind, 0)

    rows = []
    for key, u in sorted(bucket_util.items(), key=_order):
        occ = u.get("occupancy")
        if occ is None:
            rows_total = u.get("rows") or 0
            occ = (u.get("active_rows", 0) / rows_total) if rows_total \
                else 0.0
        rows.append((key, u.get("steps", 0), float(occ),
                     float(u.get("wall_s", 0.0))))
    return rows


def bucket_util_lines(bucket_util: dict | None) -> list[str]:
    """The summarize heatmap table: occupancy (active rows / bucket
    rows) per warmed (kind, size) bucket — padding waste and ladder
    sizing read directly off it."""
    rows = fold_bucket_util(bucket_util)
    if not rows:
        return []
    lines = ["  bucket util (active rows / bucket rows per step):"]
    for key, steps, occ, wall in rows:
        bar = "#" * int(round(10 * min(1.0, occ)))
        lines.append(f"    {key:>12s} {bar:<10s} {occ:6.1%}  "
                     f"{steps:5d} step(s)  {wall:7.3f}s wall")
    return lines


# ---------------------------------------------------------------------
# timeline export: one async lane per request


def request_trace_events(records: list[dict]) -> list[dict]:
    """Chrome-trace events rendering every request as its own lane
    (pid ``REQUEST_LANE_PID``, tid = request id): ``queue_wait`` ->
    ``prefill`` -> ``decode`` slices in absolute unix time, merged by
    ``obs.timeline.merge_chrome_trace`` beside the cross-rank span
    view — a single slow request is visually traceable through the
    engine.

    Needs the run's ``serve_clock`` record (round 20) to place the
    engine-relative instants on the wall; without one the lanes are
    skipped (pre-r20 stream), never wrong.
    """
    t0_unix = None
    for r in records:
        if r.get("kind") == "serve_clock" and \
                isinstance(r.get("t_unix"), (int, float)):
            t0_unix = float(r["t_unix"])
            break
    if t0_unix is None:
        return []
    events: list[dict] = []
    seen = False
    for r in records:
        if r.get("kind") != "request" or not \
                isinstance(r.get("e2e_ms"), (int, float)):
            continue
        seen = True
        rid = r.get("id", "?")
        attr = attribution_of(r)
        t_arr = t0_unix + float(r.get("arrival_s", 0.0))
        t_admit = t_arr + attr["queue_wait"] / 1e3
        t_first = t_admit + attr["prefill"] / 1e3
        t_done = t_arr + float(r["e2e_ms"]) / 1e3

        def _slice(name, t0, t1, **args):
            ev = {"name": name, "ph": "X", "ts_unix": t0,
                  "dur": round(max(0.0, t1 - t0) * 1e6, 1),
                  "pid": REQUEST_LANE_PID, "tid": rid}
            if args:
                ev["args"] = args
            events.append(ev)

        _slice("queue_wait", t_arr, t_admit, rid=rid,
               prompt_len=r.get("prompt_len"))
        _slice("prefill", t_admit, t_first, rid=rid)
        if t_done > t_first:
            _slice("decode", t_first, t_done, rid=rid,
                   active_ms=attr["decode_active"],
                   stall_ms=attr["decode_stall"],
                   retire_ms=attr["retire_overhead"],
                   output_len=r.get("output_len"))
    if seen:
        events.append({"name": "process_name", "ph": "M",
                       "pid": REQUEST_LANE_PID,
                       "args": {"name": "requests"}})
    return events
