"""Typed health signals over the obs streams — the autoscaler's
sensing half.

Pure record processing — NO jax import, by contract (the obs CLI and
the fleet controller both read this module without a backend).

A *signal* is a named, hysteresis-gated judgment over a windowed
measure: it **fires** after ``fire_windows`` consecutive windows past
the fire threshold (a one-window spike is not overload) and **clears**
after ``clear_windows`` consecutive windows past the (stricter) clear
threshold (so a measure oscillating around the line does not flap).
A window with no evidence (measure ``None``) holds every streak —
silence is not health.

Events land append-only in ``signals.jsonl`` beside the metrics
stream, each with the measure, threshold, and a cause payload naming
the evidence — the contract consumers (``obs watch``'s live column,
``fleet/supervisor``'s advisory journal, the bench verdicts) rely on.

Every signal name must be in ``KNOWN_SIGNALS``: a typo'd name fires
fine and then silently vanishes from every fold, which is why the
``signal-name-registry`` analysis lint checks literal names at call
sites against this registry.
"""

from __future__ import annotations

import dataclasses
import json
import os

SIGNAL_KIND = "signal"
SIGNALS_FILENAME = "signals.jsonl"

# the registry the signal-name-registry lint checks literals against
KNOWN_SIGNALS = (
    "SUSTAINED_OVERLOAD",   # SLO-violation share of completions, sustained
    "KV_PRESSURE",          # pool_starved share of admission-blocked time
    "STRAGGLER",            # fleet step skew (slowest vs median rank)
    "DATA_STARVED",         # data_wait share of goodput wall
    "GOODPUT_COLLAPSE",     # useful-compute share under a live backlog
)


@dataclasses.dataclass(frozen=True)
class SignalSpec:
    """One signal's thresholds: ``direction`` is the breach side
    ("above": measure >= fire_threshold breaches), and the clear
    threshold is strictly inside the fire threshold so the engine has
    a dead band to debounce across."""

    name: str
    doc: str
    direction: str = "above"
    fire_threshold: float = 0.5
    clear_threshold: float = 0.25
    fire_windows: int = 2
    clear_windows: int = 2


SPECS: dict[str, SignalSpec] = {s.name: s for s in (
    SignalSpec(
        "SUSTAINED_OVERLOAD",
        "share of window completions violating the e2e target",
        fire_threshold=0.5, clear_threshold=0.25,
        fire_windows=2, clear_windows=2),
    SignalSpec(
        "KV_PRESSURE",
        "pool_starved share of the window's admission-blocked time",
        fire_threshold=0.5, clear_threshold=0.25,
        fire_windows=2, clear_windows=2),
    SignalSpec(
        "STRAGGLER",
        "fleet step skew (slowest rank behind the median, steps)",
        fire_threshold=2.0, clear_threshold=1.0,
        fire_windows=2, clear_windows=2),
    SignalSpec(
        "DATA_STARVED",
        "data_wait share of the goodput ledger's wall",
        fire_threshold=0.3, clear_threshold=0.15,
        # the ledger is run-scoped (one observation), so the offline
        # evaluator fires on a single breach of a whole-run measure
        fire_windows=1, clear_windows=1),
    SignalSpec(
        "GOODPUT_COLLAPSE",
        "useful-compute share of window wall while a backlog exists",
        direction="below",
        fire_threshold=0.05, clear_threshold=0.15,
        fire_windows=3, clear_windows=2),
)}

# the log-only actuation hints the fleet controller journals next to a
# fired signal — what the ROADMAP autoscaler will someday DO, today
# stated as advice so operators (and the bench verdicts) can audit the
# policy before it holds any levers
_ADVICE = {
    "SUSTAINED_OVERLOAD": "scale out serve replicas",
    "KV_PRESSURE": "grow KV pool or enable --kv_preempt",
    "STRAGGLER": "replace or restart the lagging rank",
    "DATA_STARVED": "scale the input service / raise prefetch",
    "GOODPUT_COLLAPSE": "inspect padding/idle waste (bucket ladder)",
}


def spec_of(name: str) -> SignalSpec:
    """Registry lookup; unknown names raise — the runtime twin of the
    signal-name-registry lint."""
    try:
        return SPECS[name]
    except KeyError:
        raise ValueError(f"unknown signal {name!r}; known: "
                         f"{', '.join(KNOWN_SIGNALS)}") from None


def advice_for(name: str) -> str:
    spec_of(name)
    return _ADVICE[name]


class SignalEngine:
    """Streaming evaluator: feed one ``observe`` per window; fired and
    cleared transitions come back as event dicts (and accumulate on
    ``.events``).  State is per-signal consecutive-window streaks —
    O(signals), no sample retention."""

    def __init__(self, specs: dict[str, SignalSpec] | None = None):
        self.specs = dict(specs if specs is not None else SPECS)
        self.active: dict[str, float] = {}      # name -> fire t
        self._streak: dict[str, int] = {}
        self.events: list[dict] = []
        self.fired: dict[str, int] = {}

    def observe(self, t: float, measures: dict,
                causes: dict | None = None) -> list[dict]:
        """One window at time ``t``: ``measures[name]`` is the
        window's measure (None = no evidence this window; streaks and
        active state hold).  ``causes[name]`` rides the emitted event
        verbatim as its evidence payload."""
        out: list[dict] = []
        for name, spec in self.specs.items():
            m = measures.get(name)
            if m is None:
                continue
            m = float(m)
            above = spec.direction == "above"
            breach = m >= spec.fire_threshold if above \
                else m <= spec.fire_threshold
            recovered = m < spec.clear_threshold if above \
                else m > spec.clear_threshold
            if name not in self.active:
                self._streak[name] = self._streak.get(name, 0) + 1 \
                    if breach else 0
                if self._streak[name] >= spec.fire_windows:
                    self.active[name] = t
                    self.fired[name] = self.fired.get(name, 0) + 1
                    out.append(self._event(
                        t, name, "fire", m, spec.fire_threshold,
                        self._streak[name], causes))
                    self._streak[name] = 0
            else:
                self._streak[name] = self._streak.get(name, 0) + 1 \
                    if recovered else 0
                if self._streak[name] >= spec.clear_windows:
                    out.append(self._event(
                        t, name, "clear", m, spec.clear_threshold,
                        self._streak[name], causes,
                        since=self.active.pop(name)))
                    self._streak[name] = 0
        self.events.extend(out)
        return out

    def _event(self, t, name, state, measure, threshold, windows,
               causes, since=None) -> dict:
        ev = {"kind": SIGNAL_KIND, "t": round(float(t), 4),
              "signal": name, "state": state,
              "measure": round(float(measure), 4),
              "threshold": threshold, "windows": windows}
        if since is not None:
            ev["since"] = round(float(since), 4)
        cause = (causes or {}).get(name)
        if cause:
            ev["cause"] = cause
        return ev


def append_events(path: str, events: list[dict]) -> None:
    """Append-only jsonl — the same one-line-per-event contract as the
    metrics stream, so a crashed run keeps every fired signal."""
    if not events:
        return
    with open(path, "a") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def signals_path(run_dir: str) -> str:
    return os.path.join(run_dir, SIGNALS_FILENAME)


def read_signals(run_dir: str) -> list[dict]:
    """The run's signal events (empty when none fired — absence of the
    file is a clean run, not an error)."""
    from tpu_hc_bench.obs import metrics as metrics_mod

    path = run_dir
    if os.path.isdir(run_dir):
        path = signals_path(run_dir)
    if not os.path.exists(path):
        return []
    return metrics_mod.read_jsonl(path)


def active_of(events: list[dict]) -> dict[str, float]:
    """Replay fire/clear transitions -> {name: fire t} still active."""
    active: dict[str, float] = {}
    for ev in events:
        name = ev.get("signal")
        if name not in SPECS:
            continue
        if ev.get("state") == "fire":
            active[name] = float(ev.get("t") or 0.0)
        elif ev.get("state") == "clear":
            active.pop(name, None)
    return active


def fired_count(events: list[dict], name: str) -> int:
    """How many times one signal fired in an event list (bench
    verdicts and tests); the name must be registered."""
    spec_of(name)
    return sum(1 for ev in events
               if ev.get("signal") == name and ev.get("state") == "fire")


def fired_counts(events: list[dict]) -> dict[str, int]:
    out: dict[str, int] = {}
    for ev in events:
        if ev.get("state") == "fire" and ev.get("signal") in SPECS:
            out[ev["signal"]] = out.get(ev["signal"], 0) + 1
    return dict(sorted(out.items()))


def signal_lines(events: list[dict]) -> list[str]:
    """The ``obs signals`` report body (two-space indent matches the
    other summarize sections)."""
    if not events:
        return ["  signals: none fired"]
    lines = [f"  signals: {len(events)} transition(s), "
             f"{sum(fired_counts(events).values())} fire(s)"]
    for ev in events:
        cmp_ = (">=" if spec_of(ev["signal"]).direction == "above"
                else "<=") if ev.get("state") == "fire" else "->"
        lines.append(
            f"  {ev.get('state', '?'):>5s} {ev.get('signal', '?')} "
            f"@ t={ev.get('t', 0.0):.2f}s  measure "
            f"{ev.get('measure', 0.0):.3f} {cmp_} "
            f"{ev.get('threshold', 0.0):g} "
            f"({ev.get('windows', '?')} window(s))")
        if ev.get("cause"):
            detail = " ".join(f"{k}={v}" for k, v in ev["cause"].items())
            lines.append(f"        cause: {detail}")
    act = active_of(events)
    if act:
        lines.append("  still active: " + "  ".join(
            f"{n} (since t={t:.2f}s)" for n, t in sorted(act.items())))
    return lines


def watch_lines(run_dir: str) -> list[str]:
    """The live ``obs watch`` signals column: currently-active signals
    off the append-only file; silent when the run never signalled."""
    events = read_signals(run_dir)
    if not events:
        return []
    act = active_of(events)
    if not act:
        return [f"  signals: clear ({len(events)} past transition(s))"]
    return ["  signals: " + "  ".join(
        f"{n}@t={t:.1f}s" for n, t in sorted(act.items()))]


def evaluate_records(records: list[dict],
                     run_dir: str | None = None,
                     window_s: float | None = None) -> list[dict]:
    """Offline signal evaluation over one metrics stream — the same
    hysteresis engine the serve lane runs live, replayed over the
    stream's request/serve records, plus the training-lane measures
    (heartbeat skew, goodput-ledger data_wait) the engine cannot see.

    Windows follow the burn-rate fold's convention: completion-time
    span / ``DEFAULT_BURN_WINDOWS`` unless ``window_s`` is given.
    """
    from tpu_hc_bench.obs import fleet as fleet_mod
    from tpu_hc_bench.obs import goodput as goodput_mod
    from tpu_hc_bench.obs import kv as kv_mod
    from tpu_hc_bench.serve import slo as slo_mod

    engine = SignalEngine()
    reqs = [r for r in records if r.get("kind") == "request"]
    summary = next((r for r in reversed(records)
                    if r.get("kind") == slo_mod.SERVE_SUMMARY_KIND), None)
    target_ms = None
    if summary:
        slo = summary.get("slo")
        if isinstance(slo, dict):
            target_ms = slo.get("slo_e2e_ms")
        target_ms = target_ms or summary.get("deadline_ms")
    done = []
    for r in reqs:
        e2e, arr = r.get("e2e_ms"), r.get("arrival_s")
        if isinstance(e2e, (int, float)) and isinstance(arr, (int, float)):
            done.append((float(arr) + float(e2e) / 1e3, r))
    if done:
        done.sort(key=lambda x: x[0])
        t_lo, t_hi = done[0][0], done[-1][0]
        span = max(t_hi - t_lo, 1e-9)
        w = window_s if window_s and window_s > 0 \
            else span / slo_mod.DEFAULT_BURN_WINDOWS
        n_win = max(1, int(-(-span // w)))
        wins: list[list[dict]] = [[] for _ in range(n_win)]
        for t, r in done:
            wins[min(int((t - t_lo) / w), n_win - 1)].append(r)
        for i, rows in enumerate(wins):
            measures: dict = {}
            causes: dict = {}
            if rows and target_ms:
                viol = sum(1 for r in rows
                           if float(r.get("e2e_ms") or 0.0) > target_ms)
                measures["SUSTAINED_OVERLOAD"] = viol / len(rows)
                causes["SUSTAINED_OVERLOAD"] = {
                    "violations": viol, "completed": len(rows),
                    "target_ms": target_ms}
            blocked = [0.0, 0.0]
            for r in rows:
                c = kv_mod.wait_cause_of(r)
                blocked[0] += c.get("pool_starved", 0.0)
                blocked[1] += c.get("batch_full", 0.0)
            tot = blocked[0] + blocked[1]
            if tot > 1e-9:
                measures["KV_PRESSURE"] = blocked[0] / tot
                causes["KV_PRESSURE"] = {
                    "pool_starved_ms": round(blocked[0], 3),
                    "batch_full_ms": round(blocked[1], 3)}
            engine.observe(t_lo + (i + 1) * w, measures, causes)
    # training lane: per-beat fleet skew windows (the STRAGGLER
    # measure) off the heartbeat files beside the stream
    if run_dir:
        beats = fleet_mod.read_heartbeats(run_dir)
        if len(beats) > 1:
            depth = min(len(v) for v in beats.values() if v)
            for k in range(depth):
                host_steps = [recs[k].get("step", 0)
                              for _, recs in sorted(beats.items())
                              if recs]
                ewmas = [recs[k].get("step_ewma_ms", 0.0)
                         for _, recs in sorted(beats.items()) if recs]
                skew = fleet_mod.compute_skew(host_steps, ewmas)
                t = max((recs[k].get("t_mono") or 0.0)
                        for recs in beats.values() if recs)
                engine.observe(t, {"STRAGGLER": skew["skew_steps"]},
                               {"STRAGGLER": {
                                   "skew_steps": skew["skew_steps"],
                                   "skew_ms": skew["skew_ms"]}})
    # run-scoped data starvation off the goodput ledger (one
    # observation; the spec's fire_windows is 1 for exactly this)
    ledger = goodput_mod.build_ledger(records)
    if ledger is not None and ledger.wall_s:
        frac = ledger.seconds.get("data_wait", 0.0) / ledger.wall_s
        engine.observe(ledger.wall_s, {"DATA_STARVED": frac},
                       {"DATA_STARVED": {
                           "data_wait_s": round(
                               ledger.seconds.get("data_wait", 0.0), 3),
                           "wall_s": round(ledger.wall_s, 3)}})
    return engine.events


def evaluate_run(path: str, window_s: float | None = None) -> dict:
    """``obs signals`` body: the run's recorded (live) events plus an
    offline re-evaluation of the stream.  Returns a report dict; the
    CLI renders ``lines`` and exits 1 when anything fired."""
    from tpu_hc_bench.obs import metrics as metrics_mod

    problems: list[str] = []
    manifest, records = metrics_mod.read_run(path, problems=problems)
    run_dir = os.path.dirname(metrics_mod.resolve_run(path)[1])
    recorded = read_signals(run_dir)
    evaluated = evaluate_records(records, run_dir=run_dir,
                                 window_s=window_s)
    lines = [f"signals {path} — model={manifest.get('model', '?')}"]
    if recorded:
        lines.append(f"  recorded (live, {SIGNALS_FILENAME}):")
        lines.extend(signal_lines(recorded))
    lines.append("  offline re-evaluation:")
    lines.extend(signal_lines(evaluated))
    for p in problems:
        lines.append(f"  WARNING: {p}")
    fired = fired_counts(recorded) or fired_counts(evaluated)
    return {"recorded": recorded, "evaluated": evaluated,
            "fired": fired, "lines": lines, "problems": problems}
