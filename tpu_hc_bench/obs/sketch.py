"""Mergeable relative-error quantile sketch (DDSketch-style).

Pure record processing — NO jax import, by contract: every percentile
fold in the repo (serve summary, tail-attribution cut, watch live
percentiles, driver step p50, service stall histogram) routes through
this module, and the obs CLI must keep rendering artifacts copied off
a TPU VM on a laptop without a backend.

The sketch is a log-bucketed histogram: a positive value ``v`` lands in
bucket ``ceil(log_gamma(v))`` with ``gamma = (1+alpha)/(1-alpha)``, so
every bucket's representative value is within ``alpha`` *relative*
error of every sample it holds — 1% by default, at any quantile, over
any value range, in O(log range) buckets.  Two properties the repo's
stored-sample folds could never offer:

- **Bounded memory.**  A week-long serve adds samples forever; the
  sketch stays under ``max_buckets`` entries (the lowest buckets
  collapse first, degrading only the smallest-value quantiles — the
  tail the SLO reads is never the collapsed end).
- **Mergeable.**  ``merge`` is bucket-wise addition, so per-rank
  per-window sketches compose into *exact* fleet-wide percentiles —
  the merged sketch is byte-identical to the sketch of the
  concatenated streams, which per-host p99s averaged together are not.

Quantile convention matches ``serve.slo.percentile`` (q in 0..100,
rank ``q/100 * (count-1)``); exact min/max are tracked on the side so
the edge quantiles and the single-sample case are exact, not bucket
representatives.
"""

from __future__ import annotations

import math

DEFAULT_ALPHA = 0.01
DEFAULT_MAX_BUCKETS = 2048
# values at or below this land in the exact zero bucket (log of 0 is
# the alternative)
_ZERO_EPS = 1e-9


class QuantileSketch:
    """Sparse DDSketch over non-negative values with per-sample
    weights (negative inputs clamp to 0 — latency folds must never
    raise over a float-jitter -0.0)."""

    __slots__ = ("alpha", "gamma", "_log_gamma", "max_buckets",
                 "buckets", "zero_count", "count", "vmin", "vmax",
                 "total")

    def __init__(self, alpha: float = DEFAULT_ALPHA,
                 max_buckets: int = DEFAULT_MAX_BUCKETS):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1): {alpha}")
        if max_buckets < 2:
            raise ValueError(f"max_buckets must be >= 2: {max_buckets}")
        self.alpha = float(alpha)
        self.gamma = (1.0 + alpha) / (1.0 - alpha)
        self._log_gamma = math.log(self.gamma)
        self.max_buckets = int(max_buckets)
        self.buckets: dict[int, float] = {}
        self.zero_count = 0.0
        self.count = 0.0
        self.vmin: float | None = None
        self.vmax: float | None = None
        self.total = 0.0

    def add(self, value: float, weight: float = 1.0) -> None:
        w = float(weight)
        if w <= 0.0:
            return
        v = max(0.0, float(value))
        self.vmin = v if self.vmin is None else min(self.vmin, v)
        self.vmax = v if self.vmax is None else max(self.vmax, v)
        self.count += w
        self.total += v * w
        if v <= _ZERO_EPS:
            self.zero_count += w
            return
        i = math.ceil(math.log(v) / self._log_gamma)
        self.buckets[i] = self.buckets.get(i, 0.0) + w
        if len(self.buckets) > self.max_buckets:
            self._collapse()

    def _collapse(self) -> None:
        """Fold the lowest buckets together until under the cap — the
        cheap end to degrade: SLO reads live in the upper tail."""
        keys = sorted(self.buckets)
        while len(keys) > self.max_buckets:
            lo = keys.pop(0)
            self.buckets[keys[0]] = (self.buckets.get(keys[0], 0.0)
                                     + self.buckets.pop(lo))

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Bucket-wise add; both sketches must share gamma (the bucket
        boundaries) or indices mean different values."""
        if abs(other.gamma - self.gamma) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with different alpha: "
                f"{self.alpha} vs {other.alpha}")
        for i, w in other.buckets.items():
            self.buckets[i] = self.buckets.get(i, 0.0) + w
        self.zero_count += other.zero_count
        self.count += other.count
        self.total += other.total
        if other.vmin is not None:
            self.vmin = (other.vmin if self.vmin is None
                         else min(self.vmin, other.vmin))
        if other.vmax is not None:
            self.vmax = (other.vmax if self.vmax is None
                         else max(self.vmax, other.vmax))
        if len(self.buckets) > self.max_buckets:
            self._collapse()
        return self

    def quantile(self, q: float) -> float:
        """q in 0..100 (the ``serve.slo.percentile`` convention).
        Returns the bucket representative of the sample at rank
        ``q/100 * (count-1)``, clamped into [min, max] — within
        ``alpha`` relative error of that order statistic."""
        if self.count <= 0.0:
            return 0.0
        if self.vmin == self.vmax:
            return float(self.vmin)
        rank = max(0.0, min(q, 100.0)) / 100.0 * (self.count - 1.0)
        acc = self.zero_count
        if acc > rank:
            return float(self.vmin)
        for i in sorted(self.buckets):
            acc += self.buckets[i]
            if acc > rank:
                rep = 2.0 * self.gamma ** i / (self.gamma + 1.0)
                return float(min(max(rep, self.vmin), self.vmax))
        return float(self.vmax)

    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def to_record(self) -> dict:
        """JSON-serializable form (bucket keys become strings; weights
        round to micro-counts so the stream stays compact)."""
        return {
            "alpha": self.alpha,
            "max_buckets": self.max_buckets,
            "count": round(self.count, 6),
            "zero": round(self.zero_count, 6),
            "min": self.vmin,
            "max": self.vmax,
            "total": round(self.total, 6),
            "buckets": {str(i): round(w, 6)
                        for i, w in sorted(self.buckets.items())},
        }

    @classmethod
    def from_record(cls, rec: dict) -> "QuantileSketch":
        sk = cls(alpha=float(rec.get("alpha", DEFAULT_ALPHA)),
                 max_buckets=int(rec.get("max_buckets",
                                         DEFAULT_MAX_BUCKETS)))
        sk.count = float(rec.get("count", 0.0))
        sk.zero_count = float(rec.get("zero", 0.0))
        sk.vmin = rec.get("min")
        sk.vmax = rec.get("max")
        sk.total = float(rec.get("total", 0.0))
        sk.buckets = {int(k): float(w)
                      for k, w in (rec.get("buckets") or {}).items()}
        return sk

    @classmethod
    def from_counts(cls, counts, alpha: float = DEFAULT_ALPHA,
                    max_buckets: int = DEFAULT_MAX_BUCKETS
                    ) -> "QuantileSketch":
        """Sketch of an integer-indexed histogram (``counts[v]`` =
        occurrences of value ``v``) — the service stall/occupancy
        histograms' shape."""
        sk = cls(alpha=alpha, max_buckets=max_buckets)
        for v, n in enumerate(counts):
            if n:
                sk.add(float(v), float(n))
        return sk


def sketch_of(values, alpha: float = DEFAULT_ALPHA) -> QuantileSketch:
    sk = QuantileSketch(alpha=alpha)
    for v in values:
        sk.add(float(v))
    return sk


def merge_records(records) -> QuantileSketch | None:
    """Merge an iterable of ``to_record`` payloads (per-rank
    per-window sketches off the stream) into one sketch, or None when
    the iterable is empty — absent history folds to absent, labeled,
    never a KeyError."""
    merged: QuantileSketch | None = None
    for rec in records:
        if not isinstance(rec, dict):
            continue
        sk = QuantileSketch.from_record(rec)
        merged = sk if merged is None else merged.merge(sk)
    return merged
