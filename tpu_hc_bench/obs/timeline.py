"""Always-on host flight recorder: bounded span timeline per rank.

The windowed device profile (``--profile_steps`` -> ``jax.profiler``) is
off in steady state, expensive to turn on, and absent exactly when runs
hang or die.  This module is its always-on host-side complement: every
lane (train driver, data service, serve engine, checkpoint, resilience)
records *spans* — named ``(t_start, t_end)`` intervals on the process's
monotonic clock — into a preallocated ring buffer at near-zero cost
(one lock + one tuple store per span; the bounded-overhead guard test
asserts < 1% of a measured steady-state step).  Like ``FleetWriter``,
recording is telemetry and NEVER fatal: any persistence failure
disables the writer, not the run.

Three consumers:

- **Per-rank persistence**: at every sync window the driver flushes the
  ring's new spans to ``spans.<process_index>.jsonl`` beside the
  heartbeat files (append-only, so an elastic resume into the same run
  dir extends the history).  Spans that rolled off the bounded ring
  before a flush are counted, never silently lost.
- **Cross-rank merge** (``python -m tpu_hc_bench.obs timeline <dir>``):
  per-rank monotonic clocks are aligned through the heartbeat records'
  ``(t_mono, t_unix)`` pairs (``obs.fleet`` — median offset per rank,
  NTP-trust-free within a host and honest about skew across hosts; each
  spans file also carries its own ``clock`` records as a fallback), and
  the merged timeline exports Chrome-trace/Perfetto JSON (one ``pid``
  per rank, one ``tid`` per recording thread) plus the
  straggler/bubble attribution lines ``summarize`` renders.
- **Hang/crash forensics**: the watchdog, OOM, and emergency-save paths
  call ``dump_timeline`` to drop ``timeline_dump.json`` — the last-K
  spans per rank (this rank's from the live ring including unflushed
  spans, other ranks' from their flushed files) — beside
  ``memory_dump.json``, so "what phase was every rank in when it died"
  survives the death.

Recorder calls are host-side by contract: the ``span-in-compiled-fn``
analysis lint rejects any recorder call inside traced code (it would
bake one constant timestamp into the compiled program and recompile or
lie forever after).
"""

from __future__ import annotations

import json
import os
import threading
import time

SPANS_RE_FMT = "spans.{rank}.jsonl"
TIMELINE_DUMP_NAME = "timeline_dump.json"
DEFAULT_CAPACITY = 4096
DUMP_LAST_K = 64

#: coarse goodput-lane phases (mirrored from obs.goodput.PHASES without
#: the import — timeline must stay import-light); summarize's span
#: attribution ranks the FINE spans and leaves these to the ledger
_PHASE_LANE_NAMES = frozenset((
    "init", "compile", "step", "data_wait", "checkpoint",
    "checkpoint_async", "rewind_replay", "emergency_save", "idle", "end",
))

#: the span-name registry (round 20): every literal name the
#: instrumented lanes record.  A typo'd name silently vanishes from
#: every fold — the ``span-name-registry`` analysis lint checks literal
#: names at ``timeline.span``/``record_span``/``instant`` call sites
#: against this set, so a new span name is a one-line registration
#: here, not an unfindable hole in the timeline.
KNOWN_SPANS = frozenset((
    # train driver
    "input_wait", "step_dispatch", "device_step", "eval_dispatch",
    # data service
    "svc_decode", "ring_put", "ring_get",
    # serve engine
    "prefill", "decode", "classify", "admit", "retire",
    # serve admission forensics (round 22): edge-triggered instants the
    # moment the queue blocks on a resource
    "pool_starved", "batch_full",
    # serve degradation (round 23): every load-shed, KV-pressure
    # preemption/requeue, poisoned-request quarantine, and SIGTERM
    # drain leaves an instant — failure forensics read the timeline
    "shed", "preempt", "requeue", "quarantine", "drain",
    # checkpoint
    "ckpt_snapshot", "ckpt_write", "ckpt_restore",
)) | _PHASE_LANE_NAMES


def _to_record(item: tuple) -> dict:
    """Ring tuple -> the ONE on-disk/dump record shape (flush and
    tail must never diverge on the span format)."""
    name, t0, t1, step, tid, meta = item
    rec = {"name": name, "t0": round(t0, 6), "t1": round(t1, 6)}
    if step is not None:
        rec["step"] = step
    if tid and tid != "MainThread":
        rec["tid"] = tid
    if meta:
        rec.update(meta)
    return rec


class SpanRecorder:
    """Preallocated ring of spans for THIS process.

    ``record`` is the hot-path primitive: one lock acquire, one tuple
    store, two integer bumps — no allocation beyond the tuple, no I/O.
    Persistence (``flush``) and forensics (``dump``) are separate,
    cold-path, best-effort operations.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._ring: list = [None] * self.capacity
        self._n = 0                 # spans recorded ever
        self._flushed = 0           # watermark: spans persisted so far
        self.dropped = 0            # rolled off the ring before a flush
        self._lock = threading.Lock()
        self.enabled = True
        self.rank = 0
        self._f = None              # open spans.<rank>.jsonl handle
        self._run_dir: str | None = None
        # the open coarse phase (the goodput lane rides transition());
        # (name, t0, step) or None
        self._open_phase: tuple[str, float, int | None] | None = None
        self.last_name: str | None = None

    # -- hot path ------------------------------------------------------

    def record(self, name: str, t0: float, t1: float,
               step: int | None = None, **meta) -> None:
        if not self.enabled:
            return
        tid = threading.current_thread().name
        with self._lock:
            self._ring[self._n % self.capacity] = (
                name, t0, t1, step, tid, meta or None)
            self._n += 1
            self.last_name = name

    def instant(self, name: str, step: int | None = None, **meta) -> None:
        t = time.monotonic()
        self.record(name, t, t, step=step, **meta)

    def span(self, name: str, step: int | None = None, **meta) -> "_Span":
        return _Span(self, name, step, meta)

    # -- coarse phase lane (goodput transitions) -----------------------

    def transition(self, phase: str, step: int | None = None) -> None:
        """Close the open coarse-phase span and (unless ``phase`` is the
        terminal ``"end"``) open the next — the goodput ledger's
        transitions mirrored into the span timeline."""
        now = time.monotonic()
        if self._open_phase is not None:
            pname, pt0, pstep = self._open_phase
            self.record(pname, pt0, now, step=step if step is not None
                        else pstep)
        self._open_phase = (None if phase == "end"
                            else (phase, now, step))

    def current_phase(self) -> str | None:
        """The open coarse phase, else the newest recorded span's name —
        the heartbeat's "where is this rank right now" field."""
        if self._open_phase is not None:
            return self._open_phase[0]
        return self.last_name

    # -- persistence (cold path, never fatal) --------------------------

    def attach(self, run_dir: str | None, rank: int | None = None) -> None:
        """Point persistence at ``run_dir`` (``spans.<rank>.jsonl``,
        append mode).  ``None`` detaches.  Opening is lazy — the file is
        created at the first flush, so a bare run never touches disk."""
        if rank is not None:
            self.rank = int(rank)
        if self._f is not None and run_dir != self._run_dir:
            self.detach()
        self._run_dir = run_dir

    def _spans_path(self) -> str | None:
        if not self._run_dir:
            return None
        return os.path.join(self._run_dir,
                            SPANS_RE_FMT.format(rank=self.rank))

    def _ensure_file(self):
        if self._f is None and self._run_dir:
            os.makedirs(self._run_dir, exist_ok=True)
            self._f = open(self._spans_path(), "a")
            self._write_clock()
        return self._f

    def _write_clock(self) -> None:
        # one (monotonic, unix) pair per flush: the merge's per-rank
        # clock-alignment fallback when no heartbeats exist
        self._f.write(json.dumps(
            {"clock": {"t_mono": time.monotonic(),
                       "t_unix": time.time()}}) + "\n")

    def flush(self) -> int:
        """Persist spans recorded since the last flush; returns how many
        were written.  Best-effort: an I/O failure closes the writer
        (the ring keeps recording for forensics)."""
        if not self._run_dir or not self.enabled:
            return 0
        with self._lock:
            n = self._n
            start = self._flushed
            if n - start > self.capacity:
                self.dropped += (n - start) - self.capacity
                start = n - self.capacity
            batch = [self._ring[i % self.capacity] for i in range(start, n)]
            self._flushed = n
        if not batch:
            return 0
        try:
            f = self._ensure_file()
            if f is None:
                return 0
            self._write_clock()
            for item in batch:
                f.write(json.dumps(_to_record(item), default=str) + "\n")
            f.flush()
            return len(batch)
        except OSError:
            try:
                if self._f is not None:
                    self._f.close()
            except OSError:
                pass
            self._f = None
            self._run_dir = None    # spans are telemetry, never fatal
            return 0

    def detach(self) -> None:
        """Flush and close the spans file (run end); recording stays on."""
        try:
            self.flush()
        except Exception:
            pass
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None

    def tail(self, k: int = DUMP_LAST_K) -> list[dict]:
        """The newest ``k`` spans from the live ring (flushed or not) as
        record dicts — the forensics view."""
        with self._lock:
            n = self._n
            start = max(0, n - min(k, self.capacity))
            batch = [self._ring[i % self.capacity] for i in range(start, n)]
        return [_to_record(item) for item in batch if item is not None]


class _Span:
    """Tiny context manager: ``with recorder.span("ckpt_save"): ...``."""

    __slots__ = ("_rec", "_name", "_step", "_meta", "_t0")

    def __init__(self, rec: SpanRecorder, name: str, step, meta):
        self._rec = rec
        self._name = name
        self._step = step
        self._meta = meta

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._rec.record(self._name, self._t0, time.monotonic(),
                         step=self._step, **(self._meta or {}))
        return False


# ---------------------------------------------------------------------
# module-level singleton: the ONE recorder per process, shared by every
# instrumented lane (driver, data service, serve engine, checkpoint)

_RECORDER = SpanRecorder()


def get_recorder() -> SpanRecorder:
    return _RECORDER


def configure(enabled: bool = True, run_dir: str | None = None,
              rank: int | None = None) -> SpanRecorder:
    """Driver entry: set the on/off switch (``--flight_recorder``) and
    the persistence target for this process's recorder."""
    _RECORDER.enabled = bool(enabled)
    try:
        _RECORDER.attach(run_dir, rank=rank)
    except Exception:
        pass
    return _RECORDER


def record_span(name: str, t0: float, t1: float,
                step: int | None = None, **meta) -> None:
    _RECORDER.record(name, t0, t1, step=step, **meta)


def span(name: str, step: int | None = None, **meta) -> _Span:
    return _RECORDER.span(name, step=step, **meta)


def instant(name: str, step: int | None = None, **meta) -> None:
    _RECORDER.instant(name, step=step, **meta)


def transition(phase: str, step: int | None = None) -> None:
    try:
        _RECORDER.transition(phase, step=step)
    except Exception:
        pass


def current_phase() -> str | None:
    return _RECORDER.current_phase()


def flush() -> int:
    try:
        return _RECORDER.flush()
    except Exception:
        return 0


def detach() -> None:
    _RECORDER.detach()


# ---------------------------------------------------------------------
# forensics: timeline_dump.json beside memory_dump.json


def dump_timeline(out_dir: str | None, reason: str,
                  step: int | None = None,
                  last_k: int = DUMP_LAST_K) -> str | None:
    """Write ``timeline_dump.json``: the last-K spans per rank.

    This rank's spans come from the live ring (including anything not
    yet flushed — a hang usually wedges BEFORE the next sync-window
    flush); other ranks' come from their flushed ``spans.<k>.jsonl``
    files in the run dir.  Best-effort end to end: this runs on the
    watchdog/OOM/preemption paths and must never raise over the death
    it documents.  Returns the dump path, or None on any failure."""
    if not out_dir:
        return None
    try:
        ranks: dict[str, list[dict]] = {}
        for rank, spans in read_spans(out_dir).items():
            ranks[str(rank)] = spans[-last_k:]
        # the live ring wins for THIS rank (it has the unflushed tail)
        ranks[str(_RECORDER.rank)] = _RECORDER.tail(last_k)
        payload = {"reason": reason, "step": step, "t_unix": time.time(),
                   "last_k": last_k, "dropped": _RECORDER.dropped,
                   "current_phase": _RECORDER.current_phase(),
                   "ranks": ranks}
        path = os.path.join(out_dir, TIMELINE_DUMP_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2, default=str)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path
    except Exception:
        return None


# ---------------------------------------------------------------------
# reading / merge / export (pure file ops — no jax, render anywhere)


def read_spans(run_dir: str) -> dict[int, list[dict]]:
    """All ranks' flushed spans keyed by process index; corrupt lines
    (a flush interrupted by the death it documents) skipped silently."""
    import re

    out: dict[int, list[dict]] = {}
    pat = re.compile(r"^spans\.(\d+)\.jsonl$")
    try:
        names = os.listdir(run_dir)
    except OSError:
        return out
    for fname in sorted(names):
        m = pat.match(fname)
        if not m:
            continue
        spans: list[dict] = []
        with open(os.path.join(run_dir, fname)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "clock" not in rec:
                    spans.append(rec)
        out[int(m.group(1))] = spans
    return out


def _clock_pairs(run_dir: str) -> dict[int, list[tuple[float, float]]]:
    """Per-rank ``(t_mono, t_unix)`` samples: heartbeat records first
    (``obs.fleet`` — the richer source: one pair per sync window), the
    spans files' own ``clock`` records folded in as the fallback."""
    import re

    pairs: dict[int, list[tuple[float, float]]] = {}
    from tpu_hc_bench.obs import fleet as fleet_mod

    for host, recs in fleet_mod.read_heartbeats(run_dir).items():
        for r in recs:
            tm, tu = r.get("t_mono"), r.get("t_unix")
            if isinstance(tm, (int, float)) and isinstance(tu, (int, float)):
                pairs.setdefault(host, []).append((float(tm), float(tu)))
    pat = re.compile(r"^spans\.(\d+)\.jsonl$")
    try:
        names = os.listdir(run_dir)
    except OSError:
        names = []
    for fname in names:
        m = pat.match(fname)
        if not m:
            continue
        rank = int(m.group(1))
        with open(os.path.join(run_dir, fname)) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                c = rec.get("clock")
                if isinstance(c, dict) and "t_mono" in c and "t_unix" in c:
                    pairs.setdefault(rank, []).append(
                        (float(c["t_mono"]), float(c["t_unix"])))
    return pairs


class RankClock:
    """One rank's monotonic->unix mapping, incarnation-aware.

    A rank's spans file can span several LIVES of the process (the
    append-mode heartbeats/spans of elastic resume), and a relaunch on
    a rebooted or replacement host restarts CLOCK_MONOTONIC — one
    pooled median offset would confidently misplace the minority
    life's spans by hours.  So alignment is per-sample: ``offset_at``
    returns the offset of the clock pair NEAREST in monotonic time to
    the span being aligned (pairs within one life agree to
    microseconds; across lives the monotonic ranges are disjoint, so
    nearest-in-t_mono selects the right life).
    """

    def __init__(self, pairs: list[tuple[float, float]]):
        import statistics

        self._samples = sorted((m, u - m) for m, u in pairs)
        self._monos = [m for m, _ in self._samples]
        self.median_offset = statistics.median(
            off for _, off in self._samples)

    def offset_at(self, t_mono: float) -> float:
        import bisect

        i = bisect.bisect_left(self._monos, t_mono)
        if i <= 0:
            return self._samples[0][1]
        if i >= len(self._samples):
            return self._samples[-1][1]
        before, after = self._samples[i - 1], self._samples[i]
        return (before if t_mono - before[0] <= after[0] - t_mono
                else after)[1]


def rank_clocks(run_dir: str) -> dict[int, RankClock]:
    """Per-rank clock mapping from every ``(t_mono, t_unix)`` sample
    (heartbeats preferred, spans-file ``clock`` records folded in)."""
    return {rank: RankClock(samples)
            for rank, samples in _clock_pairs(run_dir).items() if samples}


def rank_clock_offsets(run_dir: str) -> dict[int, float]:
    """Per-rank MEDIAN monotonic->unix offset — the summary figure
    (``aligned_ranks`` metadata); span placement uses the
    incarnation-aware ``RankClock.offset_at`` instead.  Median, not
    mean — one paused-VM outlier pair must not skew a whole rank."""
    return {rank: clock.median_offset
            for rank, clock in rank_clocks(run_dir).items()}


def merge_chrome_trace(run_dir: str) -> dict:
    """Merge every rank's spans into one aligned Chrome-trace JSON
    (``chrome://tracing`` / Perfetto ``traceEvents`` format): one pid
    per rank, one tid per recording thread, timestamps aligned through
    the heartbeat clock pairs and rebased to the earliest span.  A rank
    with NO clock source anywhere (no heartbeats, no spans-file
    ``clock`` records) still merges — identity offset, a loud entry in
    ``metadata["warnings"]``, and a marked process name — instead of
    silently landing hours off or being dropped.

    Serving runs (round 20): the run dir's ``metrics.jsonl`` request
    records additionally render as per-request lanes
    (``obs.requests.request_trace_events``) beside the rank spans, so a
    single slow request is traceable through the engine.  Round 22 adds
    the KV-pool occupancy counter track (``obs.kv.kv_counter_events``,
    "C"-phase stacked written/reserved/free pages), so a pool-full
    admission stall is visually attributable.

    Raises FileNotFoundError when the run dir has no spans files."""
    per_rank = read_spans(run_dir)
    if not per_rank:
        raise FileNotFoundError(
            f"no spans.<rank>.jsonl under {run_dir} — was the run's "
            f"--flight_recorder off, or --metrics_dir unset?")
    clocks = rank_clocks(run_dir)
    offsets = {rank: c.median_offset for rank, c in clocks.items()}
    warnings = [
        f"rank{rank}: no clock records in its spans file and no "
        f"heartbeats in {run_dir} — merged with IDENTITY offset "
        f"(timestamps are raw monotonic; cross-rank alignment for "
        f"this rank is meaningless)"
        for rank in sorted(per_rank) if rank not in clocks]
    aligned: list[tuple[int, dict, float]] = []
    for rank, spans in per_rank.items():
        clock = clocks.get(rank)
        for s in spans:
            t0 = float(s["t0"])
            aligned.append(
                (rank, s, t0 + (clock.offset_at(t0) if clock else 0.0)))
    # per-request lanes + the KV-pool counter track from the metrics
    # stream (serving runs; a training run simply has neither record
    # kind here)
    from tpu_hc_bench.obs import kv as kv_mod
    from tpu_hc_bench.obs import requests as requests_mod

    metrics_records = _metrics_records(run_dir)
    req_events = requests_mod.request_trace_events(metrics_records)
    req_events.extend(kv_mod.kv_counter_events(metrics_records))
    t_base = min(t for _, _, t in aligned)
    if req_events:
        t_base = min(t_base, min(e["ts_unix"] for e in req_events
                                 if "ts_unix" in e))
    events = []
    for rank, s, t0 in aligned:
        dur_us = max(0.0, (float(s["t1"]) - float(s["t0"])) * 1e6)
        args = {k: v for k, v in s.items()
                if k not in ("name", "t0", "t1", "tid")}
        ev = {"name": s["name"], "ph": "X",
              "ts": round((t0 - t_base) * 1e6, 1),
              "dur": round(dur_us, 1),
              "pid": rank, "tid": s.get("tid", "main")}
        if args:
            ev["args"] = args
        events.append(ev)
    for ev in req_events:
        if "ts_unix" in ev:
            ev["ts"] = round((ev.pop("ts_unix") - t_base) * 1e6, 1)
        events.append(ev)
    for rank in per_rank:
        events.append({"name": "process_name", "ph": "M", "pid": rank,
                       "args": {"name": f"rank{rank}"
                                + ("" if rank in offsets
                                   else " (unaligned clock)")}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": {"run_dir": run_dir,
                         "ranks": sorted(per_rank),
                         "aligned_ranks": sorted(offsets),
                         "warnings": warnings,
                         "request_lanes": sum(
                             1 for e in req_events
                             if e.get("name") == "queue_wait"),
                         # round 22: pool-occupancy counter samples
                         # ("C"-phase events on the kv-pool track)
                         "kv_counter_samples": sum(
                             1 for e in req_events
                             if e.get("ph") == "C"),
                         "t_base_unix": t_base}}


def _metrics_records(run_dir: str) -> list[dict]:
    """Tolerant read of the run dir's metrics stream (the request-lane
    source); missing/corrupt files are an empty list, never an error —
    spans dirs without a metrics stream are normal."""
    from tpu_hc_bench.obs import metrics as metrics_mod

    return metrics_mod.read_jsonl(
        os.path.join(run_dir, metrics_mod.METRICS_NAME))


def write_trace_json(trace: dict, out_path: str) -> str:
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(trace, f, default=str)
        f.write("\n")
    os.replace(tmp, out_path)
    return out_path


def write_chrome_trace(run_dir: str, out_path: str | None = None) -> str:
    trace = merge_chrome_trace(run_dir)
    out_path = out_path or os.path.join(run_dir, "timeline.trace.json")
    return write_trace_json(trace, out_path)


# ---------------------------------------------------------------------
# summarize attribution: straggler/bubble lines from the merged spans


def _fold_rank(spans: list[dict]) -> dict[str, float]:
    """name -> total seconds, fine spans only (the coarse goodput-lane
    phases already render in the ledger — repeating them here would
    double-count the same wall)."""
    out: dict[str, float] = {}
    for s in spans:
        name = s.get("name")
        if name in _PHASE_LANE_NAMES:
            continue
        try:
            dt = float(s["t1"]) - float(s["t0"])
        except (KeyError, TypeError, ValueError):
            continue
        out[name] = out.get(name, 0.0) + max(0.0, dt)
    return out


def timeline_lines(run_dir: str | None) -> list[str]:
    """The ``summarize`` timeline section: per-rank span totals with
    the dominant waits, plus the cross-rank bubble (which rank's
    timeline ends earliest after clock alignment, and in what span) —
    pure file ops, renders anywhere."""
    if not run_dir:
        return []
    per_rank = read_spans(run_dir)
    if not per_rank:
        return []
    total = sum(len(s) for s in per_rank.values())
    lines = [f"  timeline: {len(per_rank)} rank(s), {total} span(s) "
             f"(chrome trace: python -m tpu_hc_bench.obs timeline "
             f"{run_dir})"]
    for rank in sorted(per_rank):
        fold = _fold_rank(per_rank[rank])
        top = sorted(fold.items(), key=lambda kv: -kv[1])[:3]
        if top:
            lines.append(
                f"    rank{rank}: "
                + "  ".join(f"{n} {s:.2f}s" for n, s in top))
    if len(per_rank) > 1:
        clocks = rank_clocks(run_dir)
        offsets = {rank: c.median_offset for rank, c in clocks.items()}
        ends = {}
        for rank, spans in per_rank.items():
            if spans:
                t_end = max(float(s["t1"]) for s in spans)
                clock = clocks.get(rank)
                ends[rank] = t_end + (clock.offset_at(t_end)
                                      if clock else 0.0)
        if len(ends) > 1:
            lead = max(ends, key=ends.get)
            lag = min(ends, key=ends.get)
            gap = ends[lead] - ends[lag]
            last = per_rank[lag][-1].get("name", "?")
            lines.append(
                f"    bubble: rank{lag} timeline ends {gap:.2f}s before "
                f"rank{lead}'s (rank{lag} last span: {last})"
                + ("" if lag in offsets and lead in offsets
                   else " [clock alignment unavailable — skew approximate]"))
    dump_path = os.path.join(run_dir, TIMELINE_DUMP_NAME)
    if os.path.isfile(dump_path):
        try:
            with open(dump_path) as f:
                d = json.load(f)
            lines.append(
                f"  timeline dump: {TIMELINE_DUMP_NAME} (reason "
                f"{d.get('reason')}, step {d.get('step')}, "
                f"{len(d.get('ranks', {}))} rank(s), last phase "
                f"{d.get('current_phase')})")
        except (OSError, json.JSONDecodeError):
            pass
    return lines
