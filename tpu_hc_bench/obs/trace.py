"""Reusable perfetto-trace analysis for ``jax.profiler`` traces.

Promoted out of ``scripts/exp_vit_trace.py`` / ``exp_moe_trace_r05.py``
(rounds 4-6), where the parsing lived as one-off experiment code.  The
load-bearing pieces and their history:

- **Leaf-op extraction with same-tid containment** (``leaf_device_ops``):
  an X event that strictly contains >= 2 other X events *on its own
  (pid, tid) track* is a container (step marker, jit program envelope,
  region) and would double-count its children — attribution wants leaf
  ops only.  Containment is tested WITHIN one track on purpose (round
  6, ADVICE r5): a genuinely long leaf on one track merely
  *overlapping* short ops on a sibling track (a concurrent DMA/stream
  track) is real device time, not a container, and a cross-tid test
  silently dropped it.  The >= 2 threshold keeps identical-interval op
  pairs, which "contain" each other once.
- **Op classification** (``classify``): substring rules whose ORDER
  matters (collectives before "reduce", casts before "conv", ...), each
  ordering forced by a real miscount — see the inline comments.
- **Step reconstruction + bucket attribution** (``summarize_trace``):
  new here.  Steps come from the profiler's step track when present,
  else from top-level container envelopes; each step's device time is
  attributed into compute / collective / host-transfer buckets, and
  idle-bubble is the wall span no device track covers.

Absolute device durations are NOT trusted on tunneled platforms (the
axon bridge reports them scaled by a constant ~0.31 vs wall —
BASELINE.md); every consumer interprets the numbers as RATIOS (bucket
fractions within a trace, per-example ratios between runs), where the
unknown scale cancels.
"""

from __future__ import annotations

import dataclasses
import glob
import gzip
import json
import os
from collections import defaultdict

# The step-attribution buckets, in display order.  "host-transfer" is
# host<->device traffic (infeed/outfeed and host-named DMA); on-device
# data movement (copies, transposes, relayouts) is device work and
# stays in "compute".  "idle-bubble" is wall time inside a step that NO
# device track covers — the device waiting on the host, the tunnel, or
# a dependency stall.
BUCKETS = ("compute", "collective", "host-transfer", "idle-bubble")


# ---------------------------------------------------------------------
# loading


def find_trace_file(path: str) -> str:
    """Resolve a trace dir (or direct file path) to the newest
    ``*.trace.json.gz`` under it."""
    if os.path.isfile(path):
        return path
    paths = glob.glob(f"{path}/**/*.trace.json.gz", recursive=True)
    if not paths:
        raise FileNotFoundError(f"no trace.json.gz under {path}")
    return sorted(paths)[-1]


def load_events(path: str) -> list[dict]:
    """Load the perfetto ``traceEvents`` list from a trace dir or file."""
    f = find_trace_file(path)
    opener = gzip.open if f.endswith(".gz") else open
    with opener(f, "rt") as fh:
        return json.load(fh)["traceEvents"]


def device_pids(events: list[dict]) -> set:
    """Pids whose process_name marks a device (TPU/GPU) track."""
    return {
        e["pid"] for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and any(k in str(e.get("args", {}).get("name", ""))
                for k in ("TPU", "GPU", "/device:"))
    }


def _device_tracks(events: list[dict]) -> dict[tuple, list[dict]]:
    """Positive-duration X events on device pids, grouped per (pid, tid)
    track and start-sorted (ties broken longest-first so containers sort
    before the children they start with)."""
    pids = device_pids(events)
    if not pids:
        # fail as loudly as a missing trace: an attribution table
        # silently built from zero device events reads as "no hot ops"
        raise RuntimeError(
            "trace has no TPU/GPU device track — did the run fall back "
            "to CPU?")
    by_track: dict[tuple, list] = defaultdict(list)
    for e in events:
        if (e.get("ph") == "X" and e.get("pid") in pids
                and e.get("dur", 0) > 0):
            by_track[(e["pid"], e.get("tid", 0))].append(e)
    for evs in by_track.values():
        evs.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
    return by_track


def _is_container(evs: list[dict], i: int) -> bool:
    """Does start-sorted ``evs[i]`` strictly contain >= 2 later events on
    its own track?  (The same-tid containment rule — module docstring.)"""
    e = evs[i]
    end = e["ts"] + e["dur"]
    contained = 0
    j = i + 1
    n = len(evs)
    # events are start-sorted: scan candidates starting inside
    # [ts, end) — leaves exit immediately, containers after 2
    while j < n and evs[j]["ts"] < end and contained < 2:
        if evs[j]["ts"] + evs[j].get("dur", 0) <= end:
            contained += 1
        j += 1
    return contained >= 2


def _split_tracks(
    tracks: dict[tuple, list[dict]], skip_tracks: set | None = None,
) -> tuple[list[dict], dict[tuple, list[dict]]]:
    """ONE containment scan over all tracks: ``(leaves,
    containers_by_track)``.  Every consumer (op aggregation, step
    reconstruction, bucket attribution) shares this split — on a real
    trace the scan is the dominant cost and must not run twice."""
    leaves: list[dict] = []
    containers: dict[tuple, list[dict]] = {}
    for key, evs in tracks.items():
        if skip_tracks and key in skip_tracks:
            continue
        cs: list[dict] = []
        for i, e in enumerate(evs):
            (cs if _is_container(evs, i) else leaves).append(e)
        containers[key] = cs
    return leaves, containers


def _aggregate(leaves: list[dict]) -> tuple[dict[str, float],
                                            dict[str, int]]:
    ops: dict[str, float] = defaultdict(float)
    counts: dict[str, int] = defaultdict(int)
    for e in leaves:
        ops[e["name"]] += e["dur"]
        counts[e["name"]] += 1
    return dict(ops), dict(counts)


def leaf_device_ops(
    events: list[dict], skip_tracks: set | None = None,
) -> tuple[dict[str, float], dict[str, int]]:
    """Aggregate leaf device-op durations (us) + raw event counts.

    Containers (same-tid containment rule) are excluded; counts are raw
    event counts over all traced steps and device pids — divide by the
    traced-step count for per-step instruction counts.
    ``skip_tracks``: (pid, tid) keys to ignore entirely (the step-marker
    track, whose envelopes live alone on their own track and would
    otherwise be kept as giant "leaves").
    """
    leaves, _ = _split_tracks(_device_tracks(events), skip_tracks)
    return _aggregate(leaves)


def leaf_intervals(events: list[dict]) -> list[tuple[str, float, float]]:
    """``(name, start_us, end_us)`` for every leaf device op, the
    step-marker track excluded — the interval-level view
    ``obs.efficiency.collective_overlap`` needs to tell an *exposed*
    collective (device otherwise idle) from one hidden behind concurrent
    compute on a sibling track."""
    tracks = _device_tracks(events)
    st = _step_track(events, tracks)
    leaves, _ = _split_tracks(tracks, {st} if st is not None else None)
    return [(e["name"], e["ts"], e["ts"] + e["dur"]) for e in leaves]


def device_op_times(trace_dir: str) -> tuple[dict[str, float],
                                             dict[str, int]]:
    """Aggregate device-track op durations (us) + event counts from the
    newest perfetto trace under ``trace_dir`` — the experiment scripts'
    entry point (exp_vit_trace / exp_moe_trace_r05 call exactly this).

    The profiler's step-marker track (when present) is excluded: its
    digit-named envelopes each span a whole step and would otherwise
    land in the attribution table as giant "elementwise/other" leaves.
    """
    events = load_events(trace_dir)
    tracks = _device_tracks(events)
    st = _step_track(events, tracks)
    leaves, _ = _split_tracks(tracks, {st} if st is not None else None)
    return _aggregate(leaves)


# ---------------------------------------------------------------------
# op classification


def classify(name: str) -> str:
    """Op class from the trace event name (XLA instruction name)."""
    n = name.lower()
    # order matters — later checks use substrings the earlier classes
    # also contain:
    #   collectives first ("all-reduce" would otherwise hit "reduce");
    #   reductions before conv ("convert_reduce_fusion" contains "conv"
    #   but its work is the reduction, the cast is fused in);
    #   casts/relayouts before conv ("bitcast_convert"/"convert" contain
    #   "conv" but move/cast bytes, no MXU work)
    if any(k in n for k in ("all-reduce", "allreduce", "all-gather",
                            "allgather", "reduce-scatter", "all-to-all",
                            "collective", "permute", "psum")):
        return "collective"
    if any(k in n for k in ("reduce", "norm", "softmax")):
        return "reduce/norm"
    # select-and-scatter is max-pool BACKWARD (a windowed reduction, not
    # routing) — must be caught before the gather/sort class below would
    # claim its "scatter" substring
    if "select-and-scatter" in n:
        return "pool-bwd"
    # routing/permutation work (MoE dispatch, embedding lookups): sorts,
    # gathers, scatters — split out from elementwise/other so the ragged
    # MoE and ncf attributions can see it (plain "gather" lands here;
    # "all-gather" was already caught by the collective class above)
    if any(k in n for k in ("sort", "gather", "scatter", "cumsum", "iota")):
        return "gather/sort"
    if any(k in n for k in ("copy", "transpose", "reshape", "bitcast",
                            "convert", "concatenate", "slice", "pad")):
        return "data-movement"
    if "conv" in n:
        return "conv"
    if "dot" in n or "matmul" in n or "einsum" in n:
        return "matmul"
    if any(k in n for k in ("infeed", "outfeed", "barrier", "sync")):
        return "infra"
    return "elementwise/other"


def bucket_of(name: str) -> str:
    """Step-attribution bucket for one leaf op (see ``BUCKETS``)."""
    cls = classify(name)
    if cls == "collective":
        return "collective"
    if cls == "infra" or "host" in name.lower():
        return "host-transfer"
    return "compute"


# ---------------------------------------------------------------------
# step reconstruction + bucket attribution


@dataclasses.dataclass
class StepBuckets:
    """One reconstructed step: wall span + per-bucket device time (us).

    Bucket sums can exceed ``dur_us`` when several device tracks run
    concurrently (compute overlapping a DMA stream is real device time
    on both); ``idle_us`` is the part of the span NO track covers.
    """

    index: int
    start_us: float
    dur_us: float
    buckets: dict[str, float]

    @property
    def idle_us(self) -> float:
        return self.buckets.get("idle-bubble", 0.0)


@dataclasses.dataclass
class TraceSummary:
    steps: list[StepBuckets]
    totals: dict[str, float]        # per-bucket us summed over steps
    step_source: str                # "step-track" | "envelopes" | "span"

    def fractions(self) -> dict[str, float]:
        total = sum(self.totals.values())
        if not total:
            return {b: 0.0 for b in self.totals}
        return {b: v / total for b, v in self.totals.items()}


def _step_track(events: list[dict],
                tracks: dict[tuple, list[dict]]) -> tuple | None:
    """The profiler's step-marker track, if one exists.

    Preferred: a device-pid track whose thread_name metadata says
    "Steps" (the XLA profiler convention).  Fallback: a track whose
    events are ALL digit-named (step numbers) — some converter versions
    drop the thread_name record.
    """
    named = {
        (e["pid"], e.get("tid", 0))
        for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
        and "step" in str(e.get("args", {}).get("name", "")).lower()
    }
    for key in tracks:
        if key in named:
            return key
    digit_tracks = [
        key for key, evs in tracks.items()
        if len(evs) >= 1 and all(e["name"].strip().isdigit() for e in evs)
    ]
    if digit_tracks:
        return max(digit_tracks, key=lambda k: len(tracks[k]))
    return None


def _interval_union(intervals: list[tuple[float, float]]) -> float:
    """Total length covered by a set of [start, end) intervals."""
    total = 0.0
    cur_s = cur_e = None
    for s, e in sorted(intervals):
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    if cur_e is not None:
        total += cur_e - cur_s
    return total


def _spans_from(
    tracks: dict[tuple, list[dict]], st: tuple | None,
    containers_by_track: dict[tuple, list[dict]],
) -> tuple[list[tuple[float, float]], str]:
    """Per-step [start, end) wall spans from an already-split trace.

    Source is one of:
      - ``"step-track"``: the profiler's dedicated step-number track;
      - ``"envelopes"``: top-level same-tid container events on the
        busiest track (jit program envelopes — one per dispatched step);
      - ``"span"``: no structure found; one span covering all device
        activity (bucket totals stay right, per-step resolution is lost).
    """
    if st is not None:
        spans = [(e["ts"], e["ts"] + e["dur"]) for e in tracks[st]]
        return sorted(spans), "step-track"
    # envelope fallback: top-level containers on the track holding them
    best: list[tuple[float, float]] = []
    for cs in containers_by_track.values():
        # top-level only: drop containers nested inside an earlier one
        # (cs is start-sorted because the track was)
        spans, covered_end = [], -float("inf")
        for e in cs:
            ts, end = e["ts"], e["ts"] + e["dur"]
            if ts >= covered_end:
                spans.append((ts, end))
                covered_end = end
        if len(spans) > len(best):
            best = spans
    if best:
        return best, "envelopes"
    lo = min(e["ts"] for evs in tracks.values() for e in evs)
    hi = max(e["ts"] + e["dur"] for evs in tracks.values() for e in evs)
    return [(lo, hi)], "span"


def step_spans(events: list[dict]) -> tuple[list[tuple[float, float]], str]:
    """Reconstruct per-step [start, end) wall spans from a trace."""
    tracks = _device_tracks(events)
    st = _step_track(events, tracks)
    _, containers = _split_tracks(tracks,
                                  {st} if st is not None else None)
    return _spans_from(tracks, st, containers)


def summarize_trace(events: list[dict]) -> TraceSummary:
    """Per-step bucket attribution for a loaded trace.

    Each leaf op's duration is clipped to the step spans it overlaps and
    summed into its bucket; idle-bubble is each span's wall time no
    device track covers.  The step-marker track (when present) defines
    the spans and is excluded from attribution — its envelopes are not
    device work.  One track split serves leaves and spans alike.
    """
    tracks = _device_tracks(events)
    st = _step_track(events, tracks)
    leaves, containers = _split_tracks(tracks,
                                       {st} if st is not None else None)
    spans, source = _spans_from(tracks, st, containers)
    # one start-sorted sweep instead of re-scanning every leaf per span
    # (spans are sorted and disjoint by construction): j tracks the
    # first leaf not entirely before the current span; real traces hold
    # ~1e5 leaves over tens of spans, where O(steps x leaves) hurts
    leaves.sort(key=lambda e: e["ts"])
    n = len(leaves)
    j = 0
    steps: list[StepBuckets] = []
    for idx, (lo, hi) in enumerate(spans):
        while j < n and leaves[j]["ts"] + leaves[j]["dur"] <= lo:
            j += 1
        buckets = {b: 0.0 for b in BUCKETS}
        busy: list[tuple[float, float]] = []
        k = j
        while k < n and leaves[k]["ts"] < hi:
            e = leaves[k]
            k += 1
            s, t = max(e["ts"], lo), min(e["ts"] + e["dur"], hi)
            if t <= s:
                continue
            buckets[bucket_of(e["name"])] += t - s
            busy.append((s, t))
        buckets["idle-bubble"] = max(0.0, (hi - lo) - _interval_union(busy))
        steps.append(StepBuckets(index=idx, start_us=lo, dur_us=hi - lo,
                                 buckets=buckets))
    totals = {b: sum(s.buckets[b] for s in steps) for b in BUCKETS}
    return TraceSummary(steps=steps, totals=totals, step_source=source)


def summarize_trace_dir(trace_dir: str) -> TraceSummary:
    return summarize_trace(load_events(trace_dir))


# ---------------------------------------------------------------------
# formatting — shared by the driver's post-run summary and the CLI


def format_summary(summary: TraceSummary, per_step: bool = True,
                   title: str = "trace summary") -> list[str]:
    """Human-readable bucket table (device us are RATIO-grade only on
    tunneled platforms — module docstring)."""
    lines = [f"{title}: {len(summary.steps)} step(s) "
             f"(boundaries: {summary.step_source})"]
    frac = summary.fractions()
    total = sum(summary.totals.values())
    lines.append(f"{'bucket':>15s} {'us':>12s} {'frac':>7s}")
    for b in BUCKETS:
        lines.append(f"{b:>15s} {summary.totals[b]:12.0f} "
                     f"{frac.get(b, 0.0):6.1%}")
    lines.append(f"{'total':>15s} {total:12.0f}")
    if per_step and len(summary.steps) > 1:
        lines.append("per-step (us): "
                     + " ".join(f"{s.dur_us:.0f}" for s in summary.steps))
    return lines


def diff_buckets(a: dict[str, float], b: dict[str, float],
                 label_a: str = "a", label_b: str = "b") -> list[str]:
    """Bucket-level delta table: the "collective +40%, compute flat" view.

    Deltas compare bucket magnitudes directly; because tunneled-platform
    device times carry one unknown constant scale, ratios between two
    traces from the same box remain meaningful.
    """
    lines = [f"{'bucket':>15s} {label_a:>12s} {label_b:>12s} {'delta':>8s}"]
    for bucket in sorted(set(a) | set(b),
                         key=lambda k: -(b.get(k, 0.0) + a.get(k, 0.0))):
        va, vb = a.get(bucket, 0.0), b.get(bucket, 0.0)
        if va:
            delta = f"{(vb - va) / va:+7.1%}"
        elif vb:
            delta = "    new"
        else:
            delta = "      -"
        lines.append(f"{bucket:>15s} {va:12.0f} {vb:12.0f} {delta:>8s}")
    return lines
