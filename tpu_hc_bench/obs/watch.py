"""``python -m tpu_hc_bench.obs watch <dir>`` — live run tail.

The reference's live view is ``tail -f`` on a teed log; this renders
the structured stream instead: step progress + rate + loss (last
``window`` record), the goodput account so far (ledger fold over the
records read to this point), the MFU line once the summary lands, the
last resilience event, and fleet skew when heartbeat files exist.

The panel refreshes in place on a TTY (cursor-up redraw); on a pipe it
prints one compact status line per change, so ``watch`` stays usable
under ``nohup``/CI.  Exits 0 as soon as the run is complete (a
``summary`` record is present — including when it already was at
startup), 1 on ``--timeout`` expiry, and the stream keeps being
re-read from disk each poll, so a watcher started mid-run or attached
to an NFS mirror behaves identically.
"""

from __future__ import annotations

import sys
import time

from tpu_hc_bench.obs import fleet as fleet_mod
from tpu_hc_bench.obs import goodput as goodput_mod
from tpu_hc_bench.obs import metrics as metrics_mod


# the reversed-scan "newest record of kind" helper lives in obs.metrics
_last = metrics_mod._last


def render(path: str, manifest: dict, records: list[dict],
           problems: list[str] | None = None) -> list[str]:
    """The watch panel for one snapshot of the stream."""
    import os

    run_dir = os.path.dirname(metrics_mod.resolve_run(path)[1])
    lines = [f"watch {path} — model={manifest.get('model', '?')} "
             f"world={manifest.get('process_count', '?')}proc/"
             f"{manifest.get('device_count', '?')}dev"]
    w = _last(records, "window")
    beats = fleet_mod.read_heartbeats(run_dir)
    total = (manifest.get("config") or {}).get("num_batches")
    if w:
        lines.append(
            f"  step {w.get('step', '?')}"
            + (f"/{total}" if total else "")
            + f"   {w.get('rate', 0.0):.1f} ex/s   "
            f"step {w.get('step_ms', 0.0):.1f}ms   "
            f"loss {w.get('loss', float('nan')):.3f}")
    elif beats:
        # mid-run: window records only land at the end of the timed
        # loop, but every host's heartbeat file advances per sync
        # window — the live progress signal
        last = max((recs[-1] for recs in beats.values() if recs),
                   key=lambda r: r.get("step", 0), default=None)
        if last is not None:
            mem = fleet_mod.heartbeat_mem_peak(last)
            lines.append(
                f"  step {last.get('step', '?')}"
                + (f"/{total}" if total else "")
                + f" (heartbeat)   step ~"
                f"{last.get('step_ewma_ms', 0.0):.1f}ms ewma"
                + (f"   mem peak {mem / 2**20:.1f} MiB" if mem else ""))
    elif not any(r.get("kind") in ("serve", "serve_summary", "request")
                 for r in records):
        lines.append("  (no progress records yet)")
    # fleet memory: the heartbeat mem_peak_bytes field, max across the
    # hosts' freshest beats (previously received and dropped)
    mem_peaks = [p for p in (
        fleet_mod.heartbeat_mem_peak(recs[-1])
        for recs in beats.values() if recs) if p]
    if len(mem_peaks) > 1:
        lines.append(f"  fleet mem peak: {max(mem_peaks) / 2**20:.1f} MiB "
                     f"max across {len(mem_peaks)} host(s)")
    # fleet KV pressure (round 22): the serve lane's pool high-water
    # off each host's freshest beat — reader lands with the writer
    kv_peaks = [(h, p) for h, p in (
        (h, fleet_mod.heartbeat_kv_peak(recs[-1]))
        for h, recs in sorted(beats.items()) if recs) if p]
    if kv_peaks:
        lines.append("  kv peak pages: " + "  ".join(
            f"rank{h} {p}" for h, p in kv_peaks[:8]))
    # per-rank current phase (round 17): the newest flight-recorder span
    # each rank stamped into its heartbeat — a hung fleet shows WHERE
    # each rank is stuck, not just that its step counter stopped
    last_beats = {h: recs[-1] for h, recs in sorted(beats.items()) if recs}
    if any(r.get("phase") for r in last_beats.values()):
        # liveness column (round 19): ALIVE/STALE/DEAD from the newest
        # beat's wall age — a wedged rank says so instead of silently
        # showing its last good numbers forever
        for h, r in list(last_beats.items())[:8]:
            live = fleet_mod.classify_liveness([r])
            age = live["age_s"] or 0.0
            lines.append(
                f"  rank{h}: {live['status']:<5}  "
                f"step {r.get('step', '?')}  "
                f"phase {r.get('phase') or '?'}  "
                f"beat {age:.0f}s ago"
                + (f"  (incarnation {r['incarnation']})"
                   if r.get("incarnation") else ""))
        if len(last_beats) > 8:
            lines.append(f"  ... {len(last_beats) - 8} more rank(s)")
    ledger = goodput_mod.build_ledger(records)
    if ledger is not None:
        lines.extend("  " + ln for ln in ledger.format_lines())
    from tpu_hc_bench.obs import memory as memory_mod

    lines.extend(memory_mod.memory_lines(
        memory_mod.fold_memory_records(records))[:1])
    summary = _last(records, "summary")
    if summary:
        from tpu_hc_bench.obs import efficiency as eff_mod

        lines.append(
            f"  DONE: total {summary.get('total_images_per_sec', 0.0):.2f} "
            f"ex/s  mean step {summary.get('mean_step_ms', 0.0):.2f}ms")
        lines.extend(eff_mod.mfu_lines(summary))
    # serving lane (round 16): live queue/in-flight panel + completed-
    # request percentiles (serve/request records; training runs skip
    # this in one list scan)
    from tpu_hc_bench.serve import slo as slo_mod

    lines.extend(slo_mod.watch_lines(records))
    # live health signals (round 24): currently-active signals off the
    # append-only signals.jsonl beside the stream
    from tpu_hc_bench.obs import signals as signals_mod

    lines.extend(signals_mod.watch_lines(run_dir))
    res = [r for r in records
           if r.get("kind") in metrics_mod.RESILIENCE_KINDS]
    if res:
        r = res[-1]
        detail = " ".join(f"{k}={v}" for k, v in r.items() if k != "kind")
        lines.append(f"  last resilience event: {r['kind']} {detail}")
    lines.extend(fleet_mod.straggler_lines(run_dir, records))
    for p in problems or ():
        lines.append(f"  WARNING: {p}")
    return lines


def watch(path: str, out=None, interval: float = 1.0,
          timeout_s: float | None = None, follow: bool = True) -> int:
    """Tail a metrics run until it completes.  Returns 0 once a
    ``summary`` record is seen (completed run), 1 on timeout."""
    out = out or sys.stdout
    tty = bool(getattr(out, "isatty", lambda: False)())
    deadline = (time.monotonic() + timeout_s) if timeout_s else None
    prev_height = 0
    prev_panel: list[str] | None = None
    while True:
        # degradations render inside the panel (a live stream's partial
        # final line is NORMAL here) — stderr stays quiet, so the
        # in-place TTY redraw never gets interleaved warnings
        problems: list[str] = []
        manifest, records = metrics_mod.read_run(path, problems=problems)
        panel = render(path, manifest, records, problems=problems)
        # a serving run's terminal record is serve_summary (the lane
        # never emits step-keyed summaries) — either one ends the watch
        done = any(r.get("kind") in ("summary", "serve_summary")
                   for r in records)
        if tty:
            if prev_height:
                out.write(f"\x1b[{prev_height}A")
            out.write("".join(f"\x1b[2K{ln}\n" for ln in panel))
            # a shrinking panel (a warning cleared, a laggard caught
            # up) must not leave its stale bottom lines on screen
            extra = prev_height - len(panel)
            if extra > 0:
                out.write("\x1b[2K\n" * extra + f"\x1b[{extra}A")
            prev_height = len(panel)
        elif panel != prev_panel or done or not follow:
            out.write("\n".join(panel) + "\n")
            prev_panel = panel
        out.flush()
        if done:
            return 0
        if not follow:
            return 0
        if deadline is not None and time.monotonic() >= deadline:
            out.write("watch: timeout waiting for run to complete\n")
            return 1
        time.sleep(interval)
