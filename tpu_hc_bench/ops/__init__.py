"""Hand-written TPU kernels (Pallas) for ops where stock XLA underperforms.

The reference delegates all kernels to MKL-DNN (SURVEY.md §2b #21); this
framework delegates to XLA:TPU and drops to Pallas only where fusion
opportunities exceed what the compiler does — currently the large-vocab
softmax cross-entropy of the BERT MLM head (``ops.xent``).
"""

from tpu_hc_bench.ops.flash_attention import flash_attention  # noqa: F401
from tpu_hc_bench.ops.xent import softmax_xent, softmax_xent_reference  # noqa: F401
