"""Hand-written TPU kernels (Pallas) for ops where stock XLA underperforms.

The reference delegates all kernels to MKL-DNN (SURVEY.md §2b #21); this
framework delegates to XLA:TPU and drops to Pallas only where measurement
shows a win.  The record (BASELINE.md):

- ``flash_attention`` — WINS from seq 512 up (50x at seq 8k): the
  production long-context path.
- ``xent`` — demoted: slower-or-parity at every measured vocab/seq
  (bert/gpt2/llama); kept as an experimental knob.
- ``fused_conv`` — whole-model parity (isolated-segment wins don't
  transfer); kept flag-gated as the recorded measurement apparatus.
- ``pool_bwd`` — recorded NULL (round 5): 1.6-4.4x slower than XLA's
  select-and-scatter on googlenet's pool shapes (the 9-tap VPU loop
  loses to the hardware window scan); kept as parity-tested apparatus,
  not wired into any model.
- ``paged_decode_attention`` — round 18: the serving lane's flash-decode
  kernel, K/V read directly through the int32 page tables (scalar
  prefetch + table-resolved block index maps, online softmax over
  pages, optional int8 pool with in-kernel per-page dequant).  Wired
  as ``--decode_attention=paged`` (serve lane).
- ``fused_residual_norm`` — round 18: fused residual-add + Layer/RMS
  norm used by both paged decode families (one VMEM round-trip where
  the unfused form pays three HBM trips per layer).
"""

from tpu_hc_bench.ops.flash_attention import flash_attention  # noqa: F401
from tpu_hc_bench.ops.fused_conv import fused_bn_relu_conv  # noqa: F401
from tpu_hc_bench.ops.fused_residual_ln import fused_residual_norm  # noqa: F401
from tpu_hc_bench.ops.paged_attention import paged_decode_attention  # noqa: F401
from tpu_hc_bench.ops.pool_bwd import max_pool as pallas_max_pool  # noqa: F401
from tpu_hc_bench.ops.xent import softmax_xent, softmax_xent_reference  # noqa: F401
