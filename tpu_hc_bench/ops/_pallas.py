"""Shared plumbing for the Pallas kernel modules.

Every kernel in ``tpu_hc_bench.ops`` runs as a real Mosaic program on
TPU and in Pallas *interpreter* mode everywhere else — that is how the
unit tests exercise the kernels bit-for-bit on the virtual CPU mesh.
Before round 18 each module carried its own copy of the backend probe;
this is the one shared copy (plus the tiny shape helpers that were
growing copies of their own).
"""

from __future__ import annotations

import jax

__all__ = ["interpret", "pad_up"]


def interpret() -> bool:
    """True when the Pallas kernels must run in interpreter mode (any
    non-TPU backend — the CPU test mesh, debugging on GPU hosts)."""
    return jax.default_backend() != "tpu"


def pad_up(x: int, m: int) -> int:
    """``x`` rounded up to the next multiple of ``m``."""
    return (x + m - 1) // m * m
