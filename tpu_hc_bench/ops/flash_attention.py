"""Flash attention (blocked online-softmax) as a Pallas TPU kernel.

The reference's compute engine delegates its hot kernels to MKL-DNN
(SURVEY.md §2b #21); the TPU-native analog is XLA plus Pallas where manual
blocking beats the compiler.  Attention is the canonical case: the naive
``softmax(QK^T)V`` materializes an [S, S] score matrix in HBM per head,
while this kernel streams K/V blocks through VMEM with the online-softmax
recurrence, so scores never leave the chip:

    m' = max(m, rowmax(S_blk));   l' = l*e^(m-m') + rowsum(e^(S_blk - m'))
    acc' = acc*e^(m-m') + e^(S_blk - m') @ V_blk

The backward pass (custom VJP) recomputes probabilities blockwise from the
saved per-row logsumexp — the standard flash-attention backward:

    D_i  = rowsum(dO_i * O_i)
    P    = exp(S - lse)
    dV  += P^T dO;   dS = P * (dO V^T - D);   dQ += dS K;   dK += dS^T Q

Accumulation is always float32 regardless of input dtype (bf16-safe).  On
non-TPU backends the kernels run in Pallas interpreter mode, which is how
the unit tests exercise them on the virtual CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_hc_bench.ops._pallas import interpret as _interpret
from tpu_hc_bench.ops._pallas import pad_up as _pad_up

# Default blocks: 1024x1024, confirmed by a round-2 back-to-back A/B
# inside the FULL gpt2 train step (162.0 ms vs 175.8 ms for 512x512 at
# seq 1024 bs 16 — +8.5%).  NOTE the *isolated-kernel* microbench says
# the opposite (512x512 wins by 10-13% when the attention grad runs
# alone): in context the rest of the layer competes for VMEM and the
# scheduler hides the big tiles' latency, so only whole-model A/Bs are
# trusted for this knob.  Working set at d=64 is ~9 MB of VMEM (f32
# score/prob tiles dominate); callers with head_dim > 128 get block_k
# halved below.  Overridable per call for small test shapes.
_BLOCK_Q = 1024
_BLOCK_K = 1024
_NEG_INF = -1e30


# batch*heads and the outer block dim are embarrassingly parallel; only the
# innermost (accumulating) grid dim carries loop state
_PARAMS = pltpu.CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary")
)


def _mask(i, j, bq, bk, seq_k, causal):
    """[bq, bk] bool: key in-range (< seq_k) and causally visible."""
    kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    m = kpos < seq_k
    if causal:
        qpos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        m = jnp.logical_and(m, qpos >= kpos)
    return m


def _tile_live(i, j, bq, bk):
    """Scalar bool: causal tile (i, j) has at least one visible element.

    A tile is fully above the diagonal — every qpos < kpos — iff its max
    qpos ((i+1)*bq - 1) is below its min kpos (j*bk).  Skipping those
    tiles halves the work at long sequence lengths; the K/V block DMAs
    still run (rectangular grid), but both MXU matmuls are elided."""
    return (i + 1) * bq > j * bk


# ---------------------------------------------------------------------------
# forward: grid (batch*heads, q_blocks, k_blocks), k innermost
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
                *, scale, causal, seq_k):
    i, j = pl.program_id(1), pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def tile_body():
        # matmuls run in the input dtype (bf16 native on the MXU), f32 accum
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                      # [BQ, BK] f32
        visible = _mask(i, j, *s.shape, seq_k, causal)
        s = jnp.where(visible, s, _NEG_INF)

        m_old = m_ref[:]                               # [BQ, 1]
        m_new = jnp.maximum(m_old, jnp.max(s, axis=1, keepdims=True))
        # fully-masked rows keep m == _NEG_INF; exp(s-m)=1 there, so re-mask
        p = jnp.where(visible, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_old - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = m_new
        acc_ref[:] = acc_ref[:] * corr + jnp.dot(
            p.astype(v_ref.dtype), v_ref[0],
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(_tile_live(i, j, q_ref.shape[1], k_ref.shape[1]))(tile_body)
    else:
        tile_body()

    @pl.when(j == nj - 1)
    def _():
        l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:] + jnp.log(l)


def _fwd_call(q, k, v, scale, causal, seq_k, block_q, block_k):
    bh, sq, d = q.shape
    sk = k.shape[1]
    grid = (bh, sq // block_q, sk // block_k)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, seq_k=seq_k
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),       # o
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),   # lse residual
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),            # running max
            pltpu.VMEM((block_q, 1), jnp.float32),            # running sum
            pltpu.VMEM((block_q, d), jnp.float32),            # output acc
        ],
        interpret=_interpret(),
        compiler_params=_PARAMS,
    )(q, k, v)


# ---------------------------------------------------------------------------
# backward: dq over (bh, i, j) with j innermost; dk/dv over (bh, j, i)
# ---------------------------------------------------------------------------


def _p_and_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, i, j,
              scale, causal, seq_k):
    """Shared recompute: probabilities P and score-grad dS for one tile."""
    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    visible = _mask(i, j, *s.shape, seq_k, causal)
    # explicit mask (not just -inf) so rows whose lse ~ -inf stay zero
    p = jnp.where(visible, jnp.exp(s - lse_ref[0]), 0.0)     # [BQ, BK] f32
    dp = jax.lax.dot_general(
        do_ref[0], v_ref[0],
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )                                                         # [BQ, BK] f32
    # ds drops to the param dtype for its matmuls (bf16 MXU-native)
    ds = (p * (dp - delta_ref[0]) * scale).astype(q_ref.dtype)
    return p.astype(q_ref.dtype), ds, do_ref[0]


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_ref, *, scale, causal, seq_k):
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def tile_body():
        _, ds, _ = _p_and_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                             i, j, scale, causal, seq_k)
        acc_ref[:] += jnp.dot(ds, k_ref[0],
                              preferred_element_type=jnp.float32)

    if causal:
        pl.when(_tile_live(i, j, q_ref.shape[1], k_ref.shape[1]))(tile_body)
    else:
        tile_body()

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal, seq_k):
    j, i = pl.program_id(1), pl.program_id(2)

    @pl.when(i == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def tile_body():
        p, ds, do = _p_and_ds(q_ref, k_ref, v_ref, do_ref, lse_ref,
                              delta_ref, i, j, scale, causal, seq_k)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )
        dk_acc[:] += jax.lax.dot_general(
            ds, q_ref[0],
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(_tile_live(i, j, q_ref.shape[1], k_ref.shape[1]))(tile_body)
    else:
        tile_body()

    @pl.when(i == pl.num_programs(2) - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_call(q, k, v, o, lse, do, scale, causal, seq_k, block_q, block_k):
    bh, sq, d = q.shape
    sk = k.shape[1]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)                   # [bh, sq, 1]

    qi_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kj_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
    row_i = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          seq_k=seq_k),
        grid=(bh, sq // block_q, sk // block_k),
        in_specs=[qi_spec, kj_spec, kj_spec, qi_spec, row_i, row_i],
        out_specs=qi_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
        compiler_params=_PARAMS,
    )(q, k, v, do, lse, delta)

    # same specs with the (j, i) grid order: i is now the innermost dim
    qi_spec2 = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
    kj_spec2 = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
    row_i2 = pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          seq_k=seq_k),
        grid=(bh, sk // block_k, sq // block_q),
        in_specs=[qi_spec2, kj_spec2, kj_spec2, qi_spec2, row_i2, row_i2],
        out_specs=[kj_spec2, kj_spec2],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
        compiler_params=_PARAMS,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op: [batch, seq, heads, head_dim] with padding + custom VJP
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, seq_k, block_q, block_k):
    o, _ = _fwd_call(q, k, v, scale, causal, seq_k, block_q, block_k)
    return o


def _flash_fwd(q, k, v, scale, causal, seq_k, block_q, block_k):
    o, lse = _fwd_call(q, k, v, scale, causal, seq_k, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, seq_k, block_q, block_k, res, g):
    q, k, v, o, lse = res
    return _bwd_call(q, k, v, o, lse, g, scale, causal, seq_k,
                     block_q, block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _fold_heads(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unfold_heads(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def flash_attention(q, k, v, causal: bool = False,
                    scale: float | None = None,
                    block_q: int = _BLOCK_Q, block_k: int = _BLOCK_K):
    """Memory-efficient attention; drop-in for ``dense_attention``.

    Args:
      q: [batch, seq_q, heads, head_dim].
      k, v: [batch, seq_k, heads, head_dim].
      causal: mask key positions above the query's global position.
      scale: score scale; default 1/sqrt(head_dim).
      block_q, block_k: kernel tile sizes (tune per hardware; defaults
        1024x1024 — see the module-top sizing note).
    Returns:
      [batch, seq_q, heads, head_dim] in q's dtype.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = (1.0 / d ** 0.5) if scale is None else float(scale)
    if d > 128:                  # keep the VMEM working set bounded
        block_k = min(block_k, 512)
    block_q = min(block_q, _pad_up(sq, 8))
    block_k = min(block_k, _pad_up(sk, 8))

    qf, kf, vf = _fold_heads(q), _fold_heads(k), _fold_heads(v)
    sq_p, sk_p = _pad_up(sq, block_q), _pad_up(sk, block_k)
    # query padding: rows are sliced off below and receive zero cotangents
    # in the VJP; key padding is masked inside the kernel (kpos >= seq_k)
    qf = jnp.pad(qf, ((0, 0), (0, sq_p - sq), (0, 0)))
    kf = jnp.pad(kf, ((0, 0), (0, sk_p - sk), (0, 0)))
    vf = jnp.pad(vf, ((0, 0), (0, sk_p - sk), (0, 0)))

    o = _flash(qf, kf, vf, scale, causal, sk, block_q, block_k)
    return _unfold_heads(o[:, :sq], b, h)
