"""Fused BN-apply + relu + 3x3 conv + BN-stats Pallas kernel (round 3).

The ResNet bottleneck's hot pattern is ``conv -> BN -> relu -> conv``:
in training, the producer conv's raw output must be materialized (its BN
statistics aren't ready until the whole tensor exists), but the
*normalize + relu + next conv* consumption can run in one pass.  The
round-3 measurement (`scripts/exp_fused_conv.py`, closing VERDICT item
 #1's conv question left open by the round-2 matmul proxy) showed XLA
fuses this well at stage-1 shapes (56x56x64: fused/xla = 1.07, no win)
but NOT at wider channels:

    [128, 28, 28, 128]: fused/xla = 0.65   (35% faster)
    [128, 14, 14, 256]: fused/xla = 0.64
    [128,  7,  7, 512]: see BASELINE.md round-3 table

This kernel is the production form of that experiment:

    y2, s1, s2 = fused_bn_relu_conv(y1_raw, a, b, w)

      prologue   xn = relu(y1_raw * a + b)   (BN folded to scale/shift,
                 computed into a padded VMEM halo buffer — y1_raw is read
                 from HBM exactly once)
      body       9 shifted [rows, Cin] x [Cin, Cout] MXU taps, f32 acc
      epilogue   y2 streamed out in the model dtype; per-channel
                 sum / sum-of-squares accumulated across the grid so the
                 NEXT BatchNorm needs no pass over y2

Backward (custom_vjp) runs on XLA: the cotangent folds the stats terms
into g_y2, the conv transposes come from ``jax.linear_transpose`` (no
forward re-execution), and the BN-apply/relu backward is elementwise.

Grid: ``G`` images per program (G chosen so each program's matmul has
>=~784 rows even at 7x7), one pass over the batch; the running-stat
scratch accumulates across sequential grid steps ("arbitrary" dimension
semantics) exactly like `ops/xent.py`.

Reference provenance: the reference's compute engine delegates conv+BN
fusion to MKL-DNN (SURVEY.md §2b #21); this is the TPU counterpart,
Pallas-where-XLA-underperforms per the same survey row.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_hc_bench.ops._pallas import interpret as _interpret


def _pick_group(batch: int, rows: int, target: int = 784) -> int:
    """Largest divisor of ``batch`` keeping ~``target`` matmul rows per
    program (small feature maps pack several images per grid step)."""
    want = max(1, target // max(rows, 1))
    g = 1
    for d in range(1, min(batch, want) + 1):
        if batch % d == 0:
            g = d
    return g


def _kernel(x_ref, w_ref, a_ref, b_ref, y_ref, s1_ref, s2_ref,
            xn_ref, sacc1, sacc2, *, gh, hw, cin, cout, out_dtype):
    i = pl.program_id(0)
    g, h, w = gh, hw, hw

    @pl.when(i == 0)
    def _init():
        sacc1[...] = jnp.zeros_like(sacc1)
        sacc2[...] = jnp.zeros_like(sacc2)

    x = x_ref[...].astype(jnp.float32)                    # [G, H, W, Ci]
    xn = jnp.maximum(x * a_ref[...] + b_ref[...], 0.0)
    xn_ref[...] = jnp.zeros_like(xn_ref)
    xn_ref[:, 1:h + 1, 1:w + 1, :] = xn.astype(xn_ref.dtype)

    acc = jnp.zeros((g * h * w, cout), jnp.float32)
    for dh in range(3):
        for dw in range(3):
            patch = xn_ref[:, dh:dh + h, dw:dw + w, :].reshape(
                g * h * w, cin)
            acc += jnp.dot(patch, w_ref[dh, dw],
                           preferred_element_type=jnp.float32)

    y_ref[...] = acc.reshape(g, h, w, cout).astype(out_dtype)
    sacc1[...] += acc.sum(axis=0, keepdims=True)
    sacc2[...] += (acc * acc).sum(axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _flush():
        s1_ref[...] = sacc1[...]
        s2_ref[...] = sacc2[...]


def _fused_fwd_impl(y1, a, b, w):
    """Raw forward: (y2, s1, s2) with s1/s2 the per-channel sum/sumsq."""
    batch, h, width, cin = y1.shape
    assert h == width, "square feature maps only (ResNet pattern)"
    cout = w.shape[-1]
    g = _pick_group(batch, h * h)
    out_dtype = y1.dtype
    kern = functools.partial(
        _kernel, gh=g, hw=h, cin=cin, cout=cout, out_dtype=out_dtype)
    y, s1, s2 = pl.pallas_call(
        kern,
        grid=(batch // g,),
        in_specs=[
            pl.BlockSpec((g, h, h, cin), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((3, 3, cin, cout), lambda i: (0, 0, 0, 0)),
            pl.BlockSpec((1, cin), lambda i: (0, 0)),
            pl.BlockSpec((1, cin), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((g, h, h, cout), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, h, h, cout), out_dtype),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, h + 2, h + 2, cin), out_dtype),
            pltpu.VMEM((1, cout), jnp.float32),
            pltpu.VMEM((1, cout), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(y1, w, a, b)
    return y, s1[0], s2[0]


def _conv(xn, w):
    return jax.lax.conv_general_dilated(
        xn, w, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    )


@jax.custom_vjp
def fused_bn_relu_conv(y1, a, b, w):
    """``relu(y1 * a + b)`` convolved with ``w`` (3x3, SAME, stride 1).

    ``a``/``b`` are the folded BN scale/shift (f32, shape ``[Cin]``);
    returns ``(y2, s1, s2)`` where ``s1``/``s2`` are y2's per-channel
    sum / sum-of-squares (f32, ``[Cout]``) for the next BatchNorm.
    """
    return _fused_fwd_impl(y1, a[None], b[None], w)


def _fwd(y1, a, b, w):
    y2, s1, s2 = _fused_fwd_impl(y1, a[None], b[None], w)
    return (y2, s1, s2), (y1, a, b, w, y2)


def _bwd(res, cts):
    y1, a, b, w, y2 = res
    g_y, g_s1, g_s2 = cts
    # fold the stats cotangents into the output cotangent:
    #   s1 = sum(y2), s2 = sum(y2^2)  =>  dy2 += g_s1 + 2*y2*g_s2
    geff = (g_y.astype(jnp.float32)
            + g_s1[None, None, None, :]
            + 2.0 * y2.astype(jnp.float32) * g_s2[None, None, None, :])
    xn_f = jnp.maximum(y1.astype(jnp.float32) * a + b, 0.0)
    xn = xn_f.astype(y1.dtype)
    geff_c = geff.astype(y1.dtype)

    # linear_transpose: the conv's transposes without re-running a forward.
    # The transposed primitive requires operand dtypes to MATCH, so the
    # function transposed here is the same-dtype conv (bf16 in -> bf16
    # out; the MXU still accumulates in f32 internally), with the
    # cotangent cast to that dtype.
    def conv_same(x, k):
        return jax.lax.conv_general_dilated(
            x, k, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    dxn, = jax.linear_transpose(lambda t: conv_same(t, w), xn)(geff_c)
    dw, = jax.linear_transpose(lambda t: conv_same(xn, t), w)(geff_c)
    t = dxn.astype(jnp.float32) * (xn_f > 0)
    dy1 = (t * a).astype(y1.dtype)
    da = jnp.sum(t * y1.astype(jnp.float32), axis=(0, 1, 2))
    db = jnp.sum(t, axis=(0, 1, 2))
    return dy1, da, db, dw.astype(w.dtype)


fused_bn_relu_conv.defvjp(_fwd, _bwd)


def eligible(shape: tuple, kernel: tuple, strides, cin: int) -> bool:
    """Where the kernel beats XLA — the measured win region (round-3
    A/B, `scripts/exp_fused_conv.py` at bs=128):

        56x56x 64: 1.07x (XLA already fuses; stays on XLA)
        28x28x128: 0.65x  WIN
        14x14x256: 0.64x  WIN
         7x7x512: 1.06x (tiny maps; stays on XLA)

    => 3x3 stride-1 square maps, >=128 input channels, >=14 spatial."""
    if tuple(kernel) != (3, 3):
        return False
    s = strides if isinstance(strides, int) else max(strides)
    if s != 1:
        return False
    if len(shape) != 4 or shape[1] != shape[2]:
        return False
    return cin >= 128 and shape[1] >= 14
