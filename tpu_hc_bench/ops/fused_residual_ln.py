"""Fused residual-add + layer normalization as a Pallas TPU kernel.

Every pre-LN decoder layer does ``x = x + branch; h = norm(x)`` twice
(attention and FFN).  Unfused, that is three HBM round-trips of the
``[rows, hidden]`` activation (write the sum, read it for the stats,
read it again for the normalize); fused, the sum is computed once in
VMEM and both the new residual stream *and* its normalized view leave
the kernel together — one read of each input, one write of each
output.  Both serving decode families consume it (``serve.decode``):
GPT's ``LayerNorm`` (mean/variance, scale+bias) and Llama's ``RMSNorm``
(root-mean-square, scale only).

Numerics match the Flax modules they replace (``nn.LayerNorm`` fast
variance ``E[x^2] - E[x]^2`` clamped at 0; ``models.llama.RMSNorm``'s
f32 stats) — pinned by ``tests/test_zz_decode_kernels.py``.  Stats always
accumulate in float32.  Non-TPU backends run the Pallas interpreter
(``ops._pallas.interpret``), same as every kernel in this package.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tpu_hc_bench.ops._pallas import interpret as _interpret
from tpu_hc_bench.ops._pallas import pad_up as _pad_up

_BLOCK_ROWS = 256


def _kernel(res_ref, x_ref, gamma_ref, beta_ref, y_ref, o_ref, *,
            eps, kind):
    y = res_ref[...] + x_ref[...]
    y_ref[...] = y
    f = y.astype(jnp.float32)
    gamma = gamma_ref[0].astype(jnp.float32)
    if kind == "layernorm":
        mu = jnp.mean(f, axis=-1, keepdims=True)
        # flax fast variance: E[x^2] - E[x]^2, clamped at 0
        var = jnp.maximum(
            jnp.mean(f * f, axis=-1, keepdims=True) - mu * mu, 0.0)
        o = (f - mu) * jax.lax.rsqrt(var + eps) * gamma
        o = o + beta_ref[0].astype(jnp.float32)
    else:                                   # rmsnorm
        var = jnp.mean(f * f, axis=-1, keepdims=True)
        o = f * jax.lax.rsqrt(var + eps) * gamma
    o_ref[...] = o.astype(o_ref.dtype)


def fused_residual_norm(res, x, gamma, beta=None, *,
                        kind: str = "layernorm",
                        eps: float | None = None,
                        block_rows: int = _BLOCK_ROWS):
    """``y = res + x``; ``out = norm(y)`` — one fused kernel.

    Args:
      res: the residual stream, ``[..., hidden]``.
      x: the branch output to add, same shape.
      gamma: ``[hidden]`` norm scale.
      beta: ``[hidden]`` bias (layernorm only; None for rmsnorm).
      kind: ``"layernorm"`` (flax ``nn.LayerNorm`` numerics, eps 1e-6)
        or ``"rmsnorm"`` (``models.llama.RMSNorm`` numerics, eps 1e-5).
      eps: override the kind's default epsilon.
      block_rows: rows per grid step (clipped to the padded row count).
    Returns:
      ``(y, out)`` — the new residual stream and its normalized view,
      both in ``res``'s dtype and shape.
    """
    if kind not in ("layernorm", "rmsnorm"):
        raise ValueError(f"kind must be layernorm|rmsnorm: {kind!r}")
    if kind == "layernorm" and beta is None:
        raise ValueError("layernorm needs beta (bias); rmsnorm is the "
                         "scale-only form")
    eps = (1e-6 if kind == "layernorm" else 1e-5) if eps is None else eps
    shape = res.shape
    h = shape[-1]
    rf = res.reshape(-1, h)
    xf = x.reshape(-1, h)
    n = rf.shape[0]
    block_rows = min(block_rows, _pad_up(n, 8))
    n_pad = _pad_up(n, block_rows)
    if n_pad != n:
        rf = jnp.pad(rf, ((0, n_pad - n), (0, 0)))
        xf = jnp.pad(xf, ((0, n_pad - n), (0, 0)))
    if beta is None:
        beta = jnp.zeros((h,), gamma.dtype)     # never read (rmsnorm)

    row_spec = pl.BlockSpec((block_rows, h), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, h), lambda i: (0, 0))
    y, o = pl.pallas_call(
        functools.partial(_kernel, eps=eps, kind=kind),
        grid=(n_pad // block_rows,),
        in_specs=[row_spec, row_spec, vec_spec, vec_spec],
        out_specs=[row_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, h), res.dtype),
            jax.ShapeDtypeStruct((n_pad, h), res.dtype),
        ],
        interpret=_interpret(),
    )(rf, xf, gamma.reshape(1, h), beta.reshape(1, h))
    return y[:n].reshape(shape), o[:n].reshape(shape)
