"""Paged single-query decode attention as a Pallas TPU kernel.

The serving lane's decode step attends one fresh query token per
request over that request's KV cache, which lives in a shared *paged*
pool (``serve.decode``: ``[layers, pages, page_size, kv_heads,
head_dim]`` plus an int32 page table per request).  The round-16
reference path gathers every request's pages into a dense
worst-case-length ``[b, S, heads, d]`` temporary and runs a plain
softmax — the single hottest per-token cost in the lane, and all of it
HBM traffic for buffers that never needed to exist.

This kernel is the PagedAttention/flash-decode analog:

- The page *tables* ride the grid as scalar-prefetch operands; the
  K/V pools stay in ``ANY`` memory (HBM) and each grid step DMAs
  exactly the pages its table slots name into VMEM scratch — no dense
  gather, no per-layer pool slice, nothing pool-sized is ever copied.
  The per-page copies are all started before the first wait, so the
  fetches overlap each other (a revolving next-block prefetch is the
  deferred follow-up).
- The softmax is the same online recurrence as ``ops.flash_attention``:

    m' = max(m, rowmax(S_blk));  l' = l*e^(m-m') + rowsum(e^(S_blk-m'))
    acc' = acc*e^(m-m') + e^(S_blk - m') @ V_blk

Grid is (batch, kv_heads, page_blocks): batch and heads are
embarrassingly parallel, the page-block dim carries the recurrence.
``pages_per_block`` is the kernel's block-size lever (how many pages —
``pages_per_block * page_size`` tokens — each grid step streams through
VMEM); together with ``--kv_page_size`` it is autotuned like any other
lever (``tune.space.SERVE_LEVERS``).  GQA folds ``heads/kv_heads``
query heads into each program's row block, and only the program's own
kv head's slice of each page is fetched.

**Int8 KV** (``--quant=int8_kv``): the pool may be int8 with one f32
scale per (layer, page), written at prefill/append time
(``serve.decode``).  Scales ride the scalar-prefetch channel and the
dequantize happens *inside* the kernel, fused with the score/value
matmuls — never a dense ``astype`` of the cache in the layer loop (the
``dequantize-in-hot-loop`` lint exists to keep it that way).

Accumulation is always float32.  On non-TPU backends the kernel runs in
Pallas interpreter mode (``ops._pallas.interpret``), which is how the
parity tests pin it against the gather reference on the CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_hc_bench.ops._pallas import interpret as _interpret

_NEG_INF = -1e30

_PARAMS = pltpu.CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary")
)


def _kernel(tables_ref, lengths_ref, k_scales_ref, v_scales_ref,
            q_ref, k_pool, v_pool, o_ref, lse_ref,
            k_buf, v_buf, m_ref, l_ref, acc_ref, sem, *,
            scale, page_size, pages_per_block, quantized, layer):
    """One (batch row, kv head, page block) program.

    The block's pages are consecutive *table slots* (the physical
    pages they map to are arbitrary — each slot is DMA'd from the
    ``ANY``-space pool into ``k_buf``/``v_buf`` scratch), so the
    block's token positions are contiguous and masking is the usual
    ``kpos < length`` test.
    """
    ppb = pages_per_block
    b, h, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nj = pl.num_programs(2)
    bk = ppb * page_size
    length = lengths_ref[b]

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def copies():
        out = []
        for i in range(ppb):
            page = tables_ref[b, j * ppb + i]
            rows = pl.ds(i * page_size, page_size)
            out.append(pltpu.make_async_copy(
                k_pool.at[layer, page, :, h, :],
                k_buf.at[rows, :], sem.at[0, i]))
            out.append(pltpu.make_async_copy(
                v_pool.at[layer, page, :, h, :],
                v_buf.at[rows, :], sem.at[1, i]))
        return out

    def block_body():
        # start every page fetch of the block before the first wait,
        # so the DMAs overlap each other
        for cp in copies():
            cp.start()
        for cp in copies():
            cp.wait()
        if quantized:
            ks, vs = [], []
            for i in range(ppb):
                page = tables_ref[b, j * ppb + i]
                rows = pl.ds(i * page_size, page_size)
                ks.append(k_buf[rows, :].astype(jnp.float32)
                          * k_scales_ref[layer, page])
                vs.append(v_buf[rows, :].astype(jnp.float32)
                          * v_scales_ref[layer, page])
            k = ks[0] if ppb == 1 else jnp.concatenate(ks, axis=0)
            v = vs[0] if ppb == 1 else jnp.concatenate(vs, axis=0)
        else:
            k = k_buf[...]
            v = v_buf[...]
        q = q_ref[0, 0]                                # [group, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                      # [group, bk] f32
        kpos = j * bk + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        visible = kpos < length
        s = jnp.where(visible, s, _NEG_INF)
        m_old = m_ref[:]                               # [group, 1]
        m_new = jnp.maximum(m_old, jnp.max(s, axis=1, keepdims=True))
        # fully-masked blocks keep m == _NEG_INF; exp(s-m)=1 there, so
        # re-mask (the flash_attention forward's exact discipline)
        p = jnp.where(visible, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_old - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = m_new
        acc_ref[:] = acc_ref[:] * corr + jnp.dot(
            p if quantized else p.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )

    # blocks entirely past the row's cache depth contribute nothing:
    # skip the fetches and both matmuls
    pl.when(j * bk < length)(block_body)

    @pl.when(j == nj - 1)
    def _():
        l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[:] + jnp.log(l)


def paged_decode_attention(q, k_pages, v_pages, tables, lengths,
                           scale: float | None = None,
                           pages_per_block: int = 1,
                           k_scales=None, v_scales=None,
                           layer: int = 0,
                           return_lse: bool = False):
    """Single-query attention over a paged KV pool, no dense gather.

    Args:
      q: ``[b, heads, head_dim]`` — one query token per request row.
      k_pages, v_pages: ``[layers, pages, page_size, kv_heads,
        head_dim]`` pool (a 4-D single-layer pool is accepted too).
        Passing the WHOLE pool with a static ``layer`` index matters:
        the pool stays an ``ANY``-space operand the kernel DMAs pages
        out of — a ``k_pages[l]`` slice at the call site would
        materialize a per-layer pool copy as a temp.  f32/bf16, or
        int8 with ``*_scales``.
      tables: ``[b, w]`` int32 page tables (slot t holds tokens
        ``t*page_size..``); every slot must hold a valid pool index
        (the serving engine's trash page 0 covers unused slots).
      lengths: ``[b]`` int32 — valid tokens per row, *including* any
        token already appended at position ``lengths-1``.
      scale: score scale; default ``1/sqrt(head_dim)``.
      pages_per_block: pages per grid step (the block-size lever);
        table width is padded to a multiple (pad slots -> page 0).
      k_scales, v_scales: ``[layers, pages]`` f32 per-page dequant
        scales (``[pages]`` for a 4-D pool), required iff int8.
      layer: static layer index into the pool's leading dim.
      return_lse: also return the per-row logsumexp of the scores —
        lets the caller merge tokens *not yet in the pool* (the decode
        step's freshly computed K/V) into the online softmax without a
        second pass.
    Returns:
      ``[b, heads, head_dim]`` in q's dtype; with ``return_lse``, a
      ``(out, lse [b, heads] f32)`` pair.
    """
    if k_pages.ndim == 4:
        k_pages, v_pages = k_pages[None], v_pages[None]
        if k_scales is not None:
            k_scales, v_scales = k_scales[None], v_scales[None]
        layer = 0
    b, heads, d = q.shape
    _, pages, page_size, kv_heads, _ = k_pages.shape
    layer = int(layer)
    w = tables.shape[1]
    if heads % kv_heads:
        raise ValueError(f"heads={heads} not a multiple of "
                         f"kv_heads={kv_heads}")
    group = heads // kv_heads
    quantized = k_pages.dtype == jnp.int8
    if quantized and (k_scales is None or v_scales is None):
        raise ValueError("int8 KV pool needs k_scales/v_scales "
                         "([layers, pages] f32 per-page scales)")
    scale = (1.0 / d ** 0.5) if scale is None else float(scale)
    ppb = max(1, min(int(pages_per_block), w))
    if w % ppb:
        pad = ppb - w % ppb
        tables = jnp.pad(tables, ((0, 0), (0, pad)))    # pad slots -> 0
        w += pad
    nb = w // ppb

    qg = q.reshape(b, kv_heads, group, d)
    if not quantized:
        # dummy f32 scales keep ONE kernel signature; never read
        k_scales = v_scales = jnp.ones((1, 1), jnp.float32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b, kv_heads, nb),
        in_specs=[
            pl.BlockSpec((1, 1, group, d),
                         lambda b_, h, j, tbl, ln, ks, vs: (b_, h, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),       # k pool (HBM)
            pl.BlockSpec(memory_space=pltpu.ANY),       # v pool (HBM)
        ],
        out_specs=[
            pl.BlockSpec((1, 1, group, d),
                         lambda b_, h, j, tbl, ln, ks, vs: (b_, h, 0, 0)),
            pl.BlockSpec((1, 1, group, 1),
                         lambda b_, h, j, tbl, ln, ks, vs: (b_, h, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((ppb * page_size, d), k_pages.dtype),  # k block
            pltpu.VMEM((ppb * page_size, d), v_pages.dtype),  # v block
            pltpu.VMEM((group, 1), jnp.float32),       # running max
            pltpu.VMEM((group, 1), jnp.float32),       # running sum
            pltpu.VMEM((group, d), jnp.float32),       # output acc
            pltpu.SemaphoreType.DMA((2, ppb)),         # k/v page fetches
        ],
    )
    kernel = functools.partial(
        _kernel, scale=scale, page_size=page_size,
        pages_per_block=ppb, quantized=quantized, layer=layer)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, kv_heads, group, d), q.dtype),
            jax.ShapeDtypeStruct((b, kv_heads, group, 1), jnp.float32),
        ],
        interpret=_interpret(),
        compiler_params=_PARAMS,
    )(tables, lengths, k_scales, v_scales, qg, k_pages, v_pages)
    out = out.reshape(b, heads, d)
    if return_lse:
        return out, lse.reshape(b, heads)
    return out
