"""Pallas max-pool with a VMEM-resident backward kernel.

Why this exists (round 5): the googlenet trace attribution put **22.1%**
of device time in `select-and-scatter` — XLA's max-pool VJP — at ~4-5x
its bandwidth roofline (BASELINE.md round-5 attribution), and the
XLA-level equality-mask rewrite is a recorded 1.8-2.4x NULL because
every window tap re-reads the input from HBM
(`scripts/exp_pool_bwd_r05.py`).  The only formulation that can reach
the roofline reads each array once: this kernel holds a full spatial
tile in VMEM and computes every tap from registers —

    dx[i] = sum over taps k of  (x[i] == y[(i-k)/s]) * dy[(i-k)/s]

with the strided reads done as phase reshapes (Mosaic has no strided
slice / interior pad / scatter-add — probed; edge-pad + phase-stack
interleave is the supported vocabulary).  Gradient semantics on TIES
differ from select-and-scatter: every tied element receives the full
cotangent (torch/TPU-common behavior) where s&s routes it to the first
max only.  For continuous inputs ties have measure zero (parity-pinned
in tests/test_pool_bwd.py).

**RECORDED NULL (round 5, measured — `scripts/exp_pool_bwd_r05.py`,
bracketed on hardware):** this kernel is 3.6x / 2.0x / 1.95x SLOWER
than XLA's select-and-scatter on googlenet's three pool-bwd shapes
(stride-2 stem pools + the stride-1 SAME branch pool).
The in-VMEM tap loop is VPU-compute-bound — 9 taps x (f32 compare +
select + pad-accumulate) is ~27 full-array vector passes, where s&s
does one hardware window scan.  Together with the XLA equality-mask
null (1.6-2.7x slower, same script) this closes the "s&s runs ~4x
above its traffic roofline" finding: the headroom is not reachable by
re-expressing the computation — s&s is compute-bound on window scans,
not bandwidth-wasteful.  The kernel stays as working, parity-tested
measurement apparatus (the house convention for contested nulls —
see ops/xent.py, ops/fused_conv.py); it is NOT wired into any model.

Reference anchor: tf_cnn_benchmarks' pooling layers run through
MKL-DNN's pool-backward primitive (SURVEY.md §2b #21 — the compute
engine the reference swaps in for exactly these hot ops); this is the
TPU-native counterpart.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_hc_bench.ops._pallas import interpret as _interpret

# Mosaic's stack accounting for this kernel measures ~12.4 bytes per
# input element per window tap (89.55M for 112x112x64 at 9 taps); the
# scoped limit is raised to 100M of v5e's 128M physical VMEM and tiles
# are budgeted against it with some slack
VMEM_LIMIT_BYTES = 100 * 1024 * 1024
_STACK_BYTES_PER_ELEM_TAP = 12.4
_BUDGET = VMEM_LIMIT_BYTES * 0.9


def _same_pad_low(in_dim: int, window: int, stride: int) -> tuple[int, int]:
    out = -(-in_dim // stride)
    total = max((out - 1) * stride + window - in_dim, 0)
    return out, total // 2


def _pool_dims(x_shape, window, strides, padding):
    H, W = x_shape[1], x_shape[2]
    (wh, ww), (sh, sw) = window, strides
    if padding == "SAME":
        Ho, plh = _same_pad_low(H, wh, sh)
        Wo, plw = _same_pad_low(W, ww, sw)
    else:  # VALID
        Ho, plh = (H - wh) // sh + 1, 0
        Wo, plw = (W - ww) // sw + 1, 0
    return Ho, Wo, plh, plw


def _bwd_kernel(x_ref, y_ref, dy_ref, dx_ref, *, window, strides,
                pads, out_dims):
    (wh, ww), (sh, sw) = window, strides
    plh, plw = pads
    Ho, Wo = out_dims
    x = x_ref[0]
    y = y_ref[0]
    dy = dy_ref[0].astype(jnp.float32)
    H, W, C = x.shape
    # pad x so every tap's phase-read is in bounds; -inf never equals a
    # window max (a window always overlaps real input under SAME/VALID)
    HpP = Ho + (wh - 1) // sh          # phase-array rows
    WpP = Wo + (ww - 1) // sw
    ninf = jnp.asarray(-jnp.inf, x.dtype)
    xp = lax.pad(x, ninf, ((plh, HpP * sh - plh - H, 0),
                           (plw, WpP * sw - plw - W, 0), (0, 0, 0)))
    # two sequential single-dim phase splits (Mosaic rejects the 5-D
    # double split's layout; one split at a time matches its tiling)
    acc = {(pi, pj): jnp.zeros((HpP, WpP, C), jnp.float32)
           for pi in range(sh) for pj in range(sw)}
    for ki in range(wh):
        a, pi = ki // sh, ki % sh
        xk_h = xp.reshape(HpP, sh, WpP * sw, C)[a:a + Ho, pi]
        for kj in range(ww):
            b, pj = kj // sw, kj % sw
            xk = xk_h.reshape(Ho, WpP, sw, C)[:, b:b + Wo, pj, :]
            # f32 compare: v5e's VPU has no bf16 cmp ("Target does not
            # support this comparison"); the upcast is exact so equality
            # is unchanged
            contrib = jnp.where(
                xk.astype(jnp.float32) == y.astype(jnp.float32), dy, 0.0)
            acc[(pi, pj)] = acc[(pi, pj)] + lax.pad(
                contrib, jnp.float32(0),
                ((a, HpP - a - Ho, 0), (b, WpP - b - Wo, 0), (0, 0, 0)))
    # interleave phases back to the input grid, one dim at a time
    cols = [jnp.stack([acc[(pi, pj)] for pj in range(sw)],
                      axis=2).reshape(HpP, WpP * sw, C)
            for pi in range(sh)]
    full = jnp.stack(cols, axis=1).reshape(HpP * sh, WpP * sw, C)
    dx_ref[0] = full[plh:plh + H, plw:plw + W, :].astype(x.dtype)


def _channel_tile(H: int, W: int, C: int, taps: int) -> int:
    # Pallas requires the lane block be a multiple of 128 or the full C
    per_c = H * W * taps * _STACK_BYTES_PER_ELEM_TAP
    candidates = [C] + [m for m in (512, 384, 256, 128) if C % m == 0]
    fitting = [ct for ct in candidates if ct * per_c <= _BUDGET]
    return max(fitting) if fitting else 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def max_pool(x, window=(3, 3), strides=(2, 2), padding="SAME"):
    """Drop-in ``nn.max_pool`` with the Pallas VMEM backward.

    Forward is XLA's ``reduce_window`` (already optimal); only the VJP
    is replaced.  Falls back to the XLA VJP off-TPU-shapes (see
    ``_channel_tile``).
    """
    return _pool_fwd_raw(x, window, strides, padding)


def _pool_fwd_raw(x, window, strides, padding):
    return lax.reduce_window(
        x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
        else jnp.iinfo(x.dtype).min,
        lax.max, (1, *window, 1), (1, *strides, 1), padding)


def _pool_fwd(x, window, strides, padding):
    y = _pool_fwd_raw(x, window, strides, padding)
    return y, (x, y)


def _xla_pool_vjp(x, dy, window, strides, padding, out_dtype):
    if not jnp.issubdtype(x.dtype, jnp.floating):
        # integer primals have no JAX tangent space (vjp hands back
        # float0 cotangents): run the select-and-scatter on the f32
        # image of the values — exact for |v| < 2^24, and max selection
        # only compares values — and cast the cotangent back
        dx = _xla_pool_vjp(x.astype(jnp.float32), dy.astype(jnp.float32),
                           window, strides, padding, jnp.float32)
        return dx.astype(x.dtype)
    _, vjp = jax.vjp(
        lambda v: _pool_fwd_raw(v, window, strides, padding), x)
    return vjp(dy.astype(out_dtype))[0]


def _pool_bwd(window, strides, padding, res, dy):
    x, y = res
    B, H, W, C = x.shape
    Ho, Wo, plh, plw = _pool_dims(x.shape, window, strides, padding)
    ct = _channel_tile(H, W, C, window[0] * window[1])
    if (ct == 0 or window[0] < strides[0] or window[1] < strides[1]
            or not jnp.issubdtype(x.dtype, jnp.floating)):
        # shape out of kernel range (stride > window would need negative
        # high pads — the skipped-input-rows case), or a non-float dtype
        # (the kernel's -inf pad identity has no integer encoding —
        # jnp.asarray(-inf, int) raises): XLA's own select-and-scatter
        # VJP, whose pad identity is dtype-aware (_pool_fwd_raw)
        return (_xla_pool_vjp(x, dy, window, strides, padding, y.dtype),)
    kernel = functools.partial(
        _bwd_kernel, window=window, strides=strides, pads=(plh, plw),
        out_dims=(Ho, Wo))
    def _kernel_path(operands):
        x_, y_, dy_ = operands
        return pl.pallas_call(
            kernel,
            grid=(B, C // ct),
            in_specs=[
                pl.BlockSpec((1, H, W, ct), lambda b, c: (b, 0, 0, c)),
                pl.BlockSpec((1, Ho, Wo, ct), lambda b, c: (b, 0, 0, c)),
                pl.BlockSpec((1, Ho, Wo, ct), lambda b, c: (b, 0, 0, c)),
            ],
            out_specs=pl.BlockSpec((1, H, W, ct),
                                   lambda b, c: (b, 0, 0, c)),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            # Mosaic's stack accounting for the per-tap pad temporaries
            # runs ~10x the live set; v5e has 128M physical VMEM and the
            # default 16M scoped limit is what overflows — raise it
            # instead of shrinking the lane tile
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel"),
                vmem_limit_bytes=VMEM_LIMIT_BYTES),
            interpret=_interpret(),
        )(x_, y_, dy_)

    def _xla_path(operands):
        x_, _, dy_ = operands
        return _xla_pool_vjp(x_, dy_, window, strides, padding, y.dtype)

    # an input that itself contains -inf would tie with the kernel's
    # -inf pad taps (every tied element gets the full cotangent — wrong
    # where the "tie" is padding): a value-, not shape-, dependent
    # hazard, so dispatch at runtime on the (rare) -inf scan
    dx = lax.cond(jnp.isneginf(x).any(), _xla_path, _kernel_path,
                  (x, y, dy.astype(y.dtype)))
    return (dx,)


max_pool.defvjp(_pool_fwd, _pool_bwd)
