"""Blocked online-softmax cross-entropy (Pallas TPU kernel, custom VJP).

Motivation: the BERT-base MLM head scores every position against a 30522-
token vocabulary.  A [tokens, vocab] logits matrix at bf16/f32 is tens of
MB per batch; the stock ``softmax_cross_entropy_with_integer_labels`` then
materializes full-width fp32 temporaries (max, exp, sum) — several extra
HBM round-trips on a bandwidth-bound chip.  This kernel streams the vocab
dimension through VMEM in blocks with the online logsumexp recurrence
(the flash-attention trick applied to the classifier head):

    m' = max(m, max(block));  s' = s * e^(m-m') + sum(e^(block - m'))

so each logits element is read exactly once in the forward pass.  The
backward kernel recomputes ``softmax - onehot`` blockwise from the saved
row logsumexp — again one read of logits, one write of dlogits.

On non-TPU backends the same kernel runs in Pallas interpreter mode (how
the unit tests exercise it on the virtual CPU mesh).

**STATUS (round 3): DEMOTED — measured slower-or-parity at every config.**
The win-or-retire measurement VERDICT #2 demanded (BASELINE.md "fused
xent, the full record"):

    bert_base  30k vocab, seq 128,  bs=128:  886 vs 1059 ex/s  (0.84x)
    gpt2       50k vocab, seq 1024 (flash):  82.3 vs 99.9 ex/s (0.82x)
    llama_1b   32k vocab, seq 2048, bs=2:    drift-paired median 0.990x

Even in its motivating regime (0.5 GB/step of f32 logits on llama_1b)
XLA's own softmax-xent fusion matches the hand kernel — consistent with
the round-3 fused-conv finding that XLA is at its fused bound in-model.
``--fused_xent`` stays as an EXPERIMENTAL knob (the kernel is correct and
unit-tested; no ``auto`` heuristic exists because there is no winning
region to select).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tpu_hc_bench.ops._pallas import interpret as _interpret

# Row/vocab block sizes: rows feed the VPU 8-sublane tiles, vocab blocks
# are lane-major multiples of 128.  512*128 f32 block = 256 KiB in VMEM.
_BLOCK_ROWS = 128
_BLOCK_VOCAB = 512
_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# forward kernel: per (row-block i, vocab-block j) with running accumulators
# ---------------------------------------------------------------------------


def _fwd_kernel(logits_ref, labels_ref, loss_ref, lse_ref, m_ref, s_ref, c_ref):
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        s_ref[:] = jnp.zeros_like(s_ref)
        c_ref[:] = jnp.zeros_like(c_ref)

    block = logits_ref[:].astype(jnp.float32)          # [BN, BV]
    bn, bv = block.shape

    # online logsumexp update
    m_old = m_ref[:]                                    # [BN, 1]
    bm = jnp.max(block, axis=1, keepdims=True)
    m_new = jnp.maximum(m_old, bm)
    s_ref[:] = s_ref[:] * jnp.exp(m_old - m_new) + jnp.sum(
        jnp.exp(block - m_new), axis=1, keepdims=True
    )
    m_ref[:] = m_new

    # gather the label logit if it falls inside this vocab block
    labels = labels_ref[:]                              # [BN, 1] int32
    local = labels - j * bv
    col_ids = jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    hit = col_ids == local                              # one column at most
    c_ref[:] = c_ref[:] + jnp.sum(
        jnp.where(hit, block, 0.0), axis=1, keepdims=True
    )

    @pl.when(j == nj - 1)
    def _():
        lse = m_ref[:] + jnp.log(s_ref[:])
        lse_ref[:] = lse
        loss_ref[:] = lse - c_ref[:]


def _fwd_call(logits: jax.Array, labels: jax.Array):
    n, v = logits.shape
    grid = (n // _BLOCK_ROWS, v // _BLOCK_VOCAB)
    loss, lse = pl.pallas_call(
        _fwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, _BLOCK_VOCAB),
                         lambda i, j: (i, j)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),  # loss
            jax.ShapeDtypeStruct((n, 1), jnp.float32),  # logsumexp residual
        ],
        scratch_shapes=[
            _scratch((_BLOCK_ROWS, 1)),  # running max m
            _scratch((_BLOCK_ROWS, 1)),  # running sumexp s
            _scratch((_BLOCK_ROWS, 1)),  # correct-class logit c
        ],
        interpret=_interpret(),
    )(logits, labels)
    return loss, lse


def _scratch(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


# ---------------------------------------------------------------------------
# backward kernel: dlogits = (softmax - onehot) * g
# ---------------------------------------------------------------------------


def _bwd_kernel(logits_ref, labels_ref, lse_ref, g_ref, dlogits_ref):
    j = pl.program_id(1)
    block = logits_ref[:].astype(jnp.float32)
    bn, bv = block.shape
    probs = jnp.exp(block - lse_ref[:])
    labels = labels_ref[:]
    local = labels - j * bv
    col_ids = jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    onehot = (col_ids == local).astype(jnp.float32)
    dlogits_ref[:] = ((probs - onehot) * g_ref[:]).astype(dlogits_ref.dtype)


def _bwd_call(logits, labels, lse, g):
    n, v = logits.shape
    grid = (n // _BLOCK_ROWS, v // _BLOCK_VOCAB)
    return pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, _BLOCK_VOCAB), lambda i, j: (i, j)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, _BLOCK_VOCAB),
                               lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((n, v), logits.dtype),
        interpret=_interpret(),
    )(logits, labels, lse, g)


# ---------------------------------------------------------------------------
# public op with padding + custom VJP
# ---------------------------------------------------------------------------


def _pad_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@jax.custom_vjp
def _xent_padded(logits, labels2d):
    loss, _ = _fwd_call(logits, labels2d)
    return loss


def _xent_fwd(logits, labels2d):
    loss, lse = _fwd_call(logits, labels2d)
    return loss, (logits, labels2d, lse)


def _xent_bwd(res, g):
    logits, labels2d, lse = res
    dlogits = _bwd_call(logits, labels2d, lse, g.astype(jnp.float32))
    return dlogits, None


_xent_padded.defvjp(_xent_fwd, _xent_bwd)


def softmax_xent(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-example cross-entropy via the blocked Pallas kernel.

    Args:
      logits: [N, V] (any float dtype; accumulation is fp32).
      labels: [N] int32 class ids in [0, V).
    Returns:
      [N] fp32 per-example loss, matching
      ``optax.softmax_cross_entropy_with_integer_labels``.
    """
    n, v = logits.shape
    np_, vp = _pad_up(n, _BLOCK_ROWS), _pad_up(v, _BLOCK_VOCAB)
    # pad vocab with -inf-ish (exp -> 0) and rows with anything (sliced off)
    padded = jnp.pad(
        logits.astype(jnp.float32),
        ((0, np_ - n), (0, vp - v)),
        constant_values=_NEG_INF,
    )
    labels2d = jnp.pad(labels.astype(jnp.int32), (0, np_ - n)).reshape(np_, 1)
    loss = _xent_padded(padded, labels2d)
    return loss.reshape(np_)[:n]


def softmax_xent_reference(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Straight-line jnp reference (what XLA compiles by default)."""
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    correct = jnp.take_along_axis(
        logits, labels[:, None].astype(jnp.int32), axis=-1
    )[:, 0]
    return lse - correct
