"""Distributed communication layer: XLA collectives over the ICI/DCN mesh.

TPU-native replacement for the reference's dual MPI stacks (OpenMPI/UCX/HCOLL
and IntelMPI/libfabric over InfiniBand verbs — SURVEY.md §2b #16-#20) and for
Horovod's fused gradient allreduce.
"""

from tpu_hc_bench.parallel.collectives import (  # noqa: F401
    allreduce_gradients,
    fused_psum_tree,
    psum,
    pmean,
    all_gather,
    reduce_scatter,
    ppermute_ring,
)
from tpu_hc_bench.parallel.fabric import Fabric, resolve_fabric  # noqa: F401
