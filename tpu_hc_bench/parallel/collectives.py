"""Collective wrappers + the Horovod fusion-buffer behavioral port.

The reference's gradient path is Horovod's C++ core: background thread,
tensor-fusion buffer (128 MiB, ``HOROVOD_FUSION_THRESHOLD=134217728`` at
``run-tf-sing-ucx-openmpi.sh:105``), ring/hierarchical MPI allreduce over
UCX/verbs (SURVEY.md §2b #20).  On TPU the allreduce is an XLA collective
compiled into the training step — no background thread, no MPI — but the
*fusion* concept survives: small gradient tensors are flattened and
concatenated into buckets of at most ``fusion_threshold_bytes`` so each
``psum`` moves one large contiguous buffer over ICI instead of many small
ones (latency-bound -> bandwidth-bound, exactly Horovod's trick).

These helpers must be called inside a ``jax.shard_map``-ed (or otherwise
mesh-mapped) function where ``axis_name`` is bound.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp

from tpu_hc_bench.flags import DEFAULT_FUSION_THRESHOLD_BYTES
from tpu_hc_bench.topology import DATA_AXIS


def psum(x: Any, axis_name: str = DATA_AXIS) -> Any:
    """Sum over the mesh axis — MPI_Allreduce(SUM) / HCOLL equivalent."""
    return jax.lax.psum(x, axis_name)


def pmean(x: Any, axis_name: str = DATA_AXIS) -> Any:
    """Mean over the mesh axis — Horovod's default gradient averaging."""
    return jax.lax.pmean(x, axis_name)


def all_gather(x: Any, axis_name: str = DATA_AXIS, axis: int = 0) -> Any:
    """MPI_Allgather equivalent (OSU osu_allgather analog)."""
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=True)


def reduce_scatter(x: Any, axis_name: str = DATA_AXIS, axis: int = 0) -> Any:
    """MPI_Reduce_scatter equivalent; the building block of ring allreduce."""
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def ppermute_ring(x: Any, axis_name: str = DATA_AXIS, shift: int = 1) -> Any:
    """Ring permute — the point-to-point primitive (osu_latency analog).

    Sends each shard to its ``+shift`` ring neighbor over ICI, the XLA
    counterpart of UCX point-to-point transport (SURVEY.md §2b #16).
    """
    n = jax.lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def _flatten_to_buckets(
    leaves: Sequence[jax.Array], threshold_bytes: int
) -> list[list[int]]:
    """Greedily group leaf indices into buckets of <= threshold bytes.

    A leaf larger than the threshold gets its own bucket (Horovod does the
    same: oversized tensors bypass the fusion buffer).
    """
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    for i, leaf in enumerate(leaves):
        nbytes = leaf.size * leaf.dtype.itemsize
        if cur and cur_bytes + nbytes > threshold_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        if cur_bytes >= threshold_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(cur)
    return buckets


def fused_psum_tree(
    tree: Any,
    axis_name: str | tuple[str, ...] = DATA_AXIS,
    threshold_bytes: int = DEFAULT_FUSION_THRESHOLD_BYTES,
    average: bool = False,
) -> Any:
    """Allreduce a pytree through fusion buckets — Horovod fusion-buffer port.

    Leaves are flattened, concatenated per-bucket (grouped greedily up to
    ``threshold_bytes``, preserving order), reduced with one ``psum`` per
    bucket, then split and reshaped back.  Mixed dtypes within a bucket are
    upcast to the widest float dtype for the wire and cast back on unpack.
    ``axis_name`` may be a tuple of bound mesh axes (e.g. the DP x SP
    step reduces over both).
    """
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    buckets = _flatten_to_buckets(leaves, threshold_bytes)
    names = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    denom = 1
    if average:
        for a in names:
            denom *= jax.lax.axis_size(a)
    out: list[jax.Array | None] = [None] * len(leaves)
    for bucket in buckets:
        wire_dtype = jnp.result_type(*[leaves[i].dtype for i in bucket])
        flat = jnp.concatenate(
            [leaves[i].astype(wire_dtype).reshape(-1) for i in bucket]
        )
        reduced = jax.lax.psum(flat, axis_name)
        if average:
            reduced = reduced / denom
        offset = 0
        for i in bucket:
            n = leaves[i].size
            out[i] = (
                reduced[offset : offset + n]
                .reshape(leaves[i].shape)
                .astype(leaves[i].dtype)
            )
            offset += n
    return jax.tree.unflatten(treedef, out)


def allreduce_gradients(
    grads: Any,
    axis_name: str | tuple[str, ...] = DATA_AXIS,
    threshold_bytes: int = DEFAULT_FUSION_THRESHOLD_BYTES,
    fuse: bool = True,
) -> Any:
    """The Horovod DistributedOptimizer step: average grads across workers.

    ``fuse=True`` routes through the fusion buckets; ``fuse=False`` emits one
    ``pmean`` per leaf and leaves combining to XLA (useful for A/B-ing the
    fusion port against the compiler, which is the honest TPU default).
    """
    if fuse:
        return fused_psum_tree(
            grads, axis_name=axis_name, threshold_bytes=threshold_bytes,
            average=True,
        )
    return jax.tree.map(lambda g: jax.lax.pmean(g, axis_name), grads)
