"""Collective wrappers + the Horovod fusion-buffer behavioral port.

The reference's gradient path is Horovod's C++ core: background thread,
tensor-fusion buffer (128 MiB, ``HOROVOD_FUSION_THRESHOLD=134217728`` at
``run-tf-sing-ucx-openmpi.sh:105``), ring/hierarchical MPI allreduce over
UCX/verbs (SURVEY.md §2b #20).  On TPU the allreduce is an XLA collective
compiled into the training step — no background thread, no MPI — but the
*fusion* concept survives: small gradient tensors are flattened and
concatenated into buckets of at most ``fusion_threshold_bytes`` so each
``psum`` moves one large contiguous buffer over ICI instead of many small
ones (latency-bound -> bandwidth-bound, exactly Horovod's trick).

Communication/compute **overlap** (round 6): a bucket's collective is
data-dependent only on the gradients it carries, so XLA's async
collectives can run it concurrently with the *rest* of the backward pass
— but only if the program gives the scheduler that freedom.  Two things
here do:

- ``overlap=True`` (the default) packs buckets in REVERSE flatten order.
  Tree-flatten order tracks forward/layer order for the zoo's models, so
  reversed order is backward-completion order: the last layers' grads —
  produced FIRST in the backward — fill the first buckets, and each
  bucket's collective can start while earlier layers are still
  differentiating.  (Forward-order packing puts a late-completing leaf
  in the first bucket and serializes everything behind it.)
- ``overlap=False`` pins an ``optimization_barrier`` across the whole
  gradient tree before the first collective — the explicit
  "allreduce after the full backward pass" arm (exactly what a
  post-``value_and_grad`` Horovod hook does), kept as the A/B control
  for ``--overlap_grad_comm``.

``reduce_scatter_tree`` / ``all_gather_tree`` are the ZeRO-1 wire pair
(``--variable_update=zero1``): the same buckets, but each bucket moves a
reduce-scatter (every device receives only its 1/N shard of the summed
gradients) and, after the sharded optimizer update, an all-gather of the
updated parameter shards.  Leaves are padded per-leaf to the axis size,
so the shard layout is threshold-independent (checkpoints survive a
``--fusion_threshold_bytes`` change).

These helpers must be called inside a ``jax.shard_map``-ed (or otherwise
mesh-mapped) function where ``axis_name`` is bound.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp

from tpu_hc_bench.flags import DEFAULT_FUSION_THRESHOLD_BYTES
from tpu_hc_bench.topology import DATA_AXIS


def psum(x: Any, axis_name: str = DATA_AXIS) -> Any:
    """Sum over the mesh axis — MPI_Allreduce(SUM) / HCOLL equivalent."""
    return jax.lax.psum(x, axis_name)


def pmean(x: Any, axis_name: str = DATA_AXIS) -> Any:
    """Mean over the mesh axis — Horovod's default gradient averaging."""
    return jax.lax.pmean(x, axis_name)


def all_gather(x: Any, axis_name: str = DATA_AXIS, axis: int = 0) -> Any:
    """MPI_Allgather equivalent (OSU osu_allgather analog)."""
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=True)


def reduce_scatter(x: Any, axis_name: str = DATA_AXIS, axis: int = 0) -> Any:
    """MPI_Reduce_scatter equivalent; the building block of ring allreduce."""
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def ppermute_ring(x: Any, axis_name: str = DATA_AXIS, shift: int = 1) -> Any:
    """Ring permute — the point-to-point primitive (osu_latency analog).

    Sends each shard to its ``+shift`` ring neighbor over ICI, the XLA
    counterpart of UCX point-to-point transport (SURVEY.md §2b #16).
    """
    n = jax.lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def _flatten_to_buckets(
    leaves: Sequence[jax.Array], threshold_bytes: int,
    order: Sequence[int] | None = None,
) -> list[list[int]]:
    """Greedily group leaf indices into buckets of <= threshold bytes.

    A leaf larger than the threshold gets its own bucket (Horovod does the
    same: oversized tensors bypass the fusion buffer).  ``order`` packs
    the leaves in that index order (default: flatten order); the overlap
    path passes reverse order so each bucket holds gradients that become
    available together during the backward pass.
    """
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    for i in (order if order is not None else range(len(leaves))):
        leaf = leaves[i]
        nbytes = leaf.size * leaf.dtype.itemsize
        if cur and cur_bytes + nbytes > threshold_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        if cur_bytes >= threshold_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(cur)
    return buckets


def _bucket_order(num_leaves: int, overlap: bool) -> list[int]:
    """Bucket packing order: backward-completion (reversed flatten) order
    when overlapping, flatten order otherwise."""
    idx = list(range(num_leaves))
    return idx[::-1] if overlap else idx


def _serialize_after_backward(leaves: list[jax.Array],
                              overlap: bool) -> list[jax.Array]:
    """The ``overlap=False`` control arm: an optimization barrier across
    the FULL gradient tree, so no collective can be scheduled before the
    last gradient exists — communication strictly follows the complete
    backward pass, the behavior ``--overlap_grad_comm=off`` selects."""
    if overlap or not leaves:
        return leaves
    return list(jax.lax.optimization_barrier(tuple(leaves)))


def fused_psum_tree(
    tree: Any,
    axis_name: str | tuple[str, ...] = DATA_AXIS,
    threshold_bytes: int = DEFAULT_FUSION_THRESHOLD_BYTES,
    average: bool = False,
    overlap: bool = True,
) -> Any:
    """Allreduce a pytree through fusion buckets — Horovod fusion-buffer port.

    Leaves are flattened, concatenated per-bucket (grouped greedily up to
    ``threshold_bytes``), reduced with one ``psum`` per bucket, then split
    and reshaped back.  Mixed dtypes within a bucket are upcast to the
    widest float dtype (``jnp.result_type``) for the wire and cast back on
    unpack — bitwise lossless for the leaves already at the wire dtype.
    ``axis_name`` may be a tuple of bound mesh axes (e.g. the DP x SP
    step reduces over both).

    ``overlap`` selects bucket-packing order and scheduling freedom (see
    module docstring): ``True`` packs in backward-completion order so
    XLA's async collectives can run concurrently with the remaining
    backward compute; ``False`` barriers the full tree first — the
    serialized control arm.  Bucketing never changes the VALUES (each
    element's cross-device sum is the same in any bucket), only the
    schedule.
    """
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    leaves = _serialize_after_backward(leaves, overlap)
    buckets = _flatten_to_buckets(leaves, threshold_bytes,
                                  _bucket_order(len(leaves), overlap))
    names = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    denom = 1
    if average:
        for a in names:
            denom *= jax.lax.axis_size(a)
    out: list[jax.Array | None] = [None] * len(leaves)
    for bucket in buckets:
        wire_dtype = jnp.result_type(*[leaves[i].dtype for i in bucket])
        flat = jnp.concatenate(
            [leaves[i].astype(wire_dtype).reshape(-1) for i in bucket]
        )
        reduced = jax.lax.psum(flat, axis_name)
        if average:
            reduced = reduced / denom
        offset = 0
        for i in bucket:
            n = leaves[i].size
            out[i] = (
                reduced[offset : offset + n]
                .reshape(leaves[i].shape)
                .astype(leaves[i].dtype)
            )
            offset += n
    return jax.tree.unflatten(treedef, out)


def allreduce_gradients(
    grads: Any,
    axis_name: str | tuple[str, ...] = DATA_AXIS,
    threshold_bytes: int = DEFAULT_FUSION_THRESHOLD_BYTES,
    fuse: bool = True,
    overlap: bool = True,
) -> Any:
    """The Horovod DistributedOptimizer step: average grads across workers.

    ``fuse=True`` routes through the fusion buckets; ``fuse=False`` emits one
    ``pmean`` per leaf and leaves combining to XLA (useful for A/B-ing the
    fusion port against the compiler, which is the honest TPU default).
    ``overlap`` is the ``--overlap_grad_comm`` arm (see fused_psum_tree);
    the unfused path only honors its ``False`` barrier (per-leaf pmeans
    are already maximally schedulable).
    """
    if fuse:
        return fused_psum_tree(
            grads, axis_name=axis_name, threshold_bytes=threshold_bytes,
            average=True, overlap=overlap,
        )
    leaves, treedef = jax.tree.flatten(grads)
    leaves = _serialize_after_backward(leaves, overlap)
    return jax.tree.unflatten(
        treedef, [jax.lax.pmean(g, axis_name) for g in leaves])


# ---------------------------------------------------------------------
# ZeRO-1 wire pair: bucketed reduce-scatter + all-gather over a pytree


def zero1_shard_len(size: int, num_shards: int) -> int:
    """Per-device shard length of a ``size``-element leaf: ceil-divided,
    so every leaf pads to ``num_shards * shard_len`` (layout is
    threshold-independent — only a function of leaf shapes and N)."""
    return -(-size // num_shards)


def zero1_resplit_rows(rows, size: int, num_shards: int):
    """Re-layout one leaf's stacked shards for a NEW axis size: the
    elastic-resume reshard (``--resume=elastic``).

    ``rows`` is the gathered ``[n_old, k_old]`` stacked-shard array of a
    ``size``-element leaf (``_leaf_to_rows``' layout: flattened leaf,
    zero-padded to ``n_old * k_old``).  Strip the old padding, re-pad to
    ``num_shards * zero1_shard_len(size, num_shards)``, restack — pure
    host numpy, bitwise on the ``size`` real elements, so an 8-way
    checkpoint resplit to 4 and back to 8 round-trips exactly.
    """
    import numpy as np

    k = zero1_shard_len(size, num_shards)
    flat = np.asarray(rows).reshape(-1)[:size]
    pad = num_shards * k - size
    if pad:
        flat = np.pad(flat, (0, pad))
    return flat.reshape(num_shards, k)


def _leaf_to_rows(leaf: jax.Array, num_shards: int, wire_dtype) -> jax.Array:
    """Pad a leaf to ``num_shards * k`` and reshape ``[num_shards, k]`` —
    row ``i`` is device ``i``'s shard of the flattened leaf."""
    k = zero1_shard_len(leaf.size, num_shards)
    flat = leaf.astype(wire_dtype).reshape(-1)
    pad = num_shards * k - leaf.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(num_shards, k)


def reduce_scatter_tree(
    tree: Any,
    axis_name: str = DATA_AXIS,
    threshold_bytes: int = DEFAULT_FUSION_THRESHOLD_BYTES,
    average: bool = False,
    overlap: bool = True,
) -> Any:
    """Bucketed gradient reduce-scatter: the ZeRO-1 half-allreduce.

    Each leaf is padded to the axis size and laid out ``[N, k]`` (row i =
    device i's shard); a bucket concatenates its leaves' rows along the
    shard dim and moves ONE ``psum_scatter`` — after which every device
    holds only its 1/N shard of each summed gradient, at half the ring
    traffic of the full allreduce.  Returns a pytree matching ``tree``
    whose leaves are 1-D per-device shards of length
    ``zero1_shard_len(leaf.size, N)``, cast back to the leaf dtype.
    ``overlap`` follows fused_psum_tree's contract.
    """
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    n = jax.lax.axis_size(axis_name)
    leaves = _serialize_after_backward(leaves, overlap)
    buckets = _flatten_to_buckets(leaves, threshold_bytes,
                                  _bucket_order(len(leaves), overlap))
    out: list[jax.Array | None] = [None] * len(leaves)
    for bucket in buckets:
        wire_dtype = jnp.result_type(*[leaves[i].dtype for i in bucket])
        rows = jnp.concatenate(
            [_leaf_to_rows(leaves[i], n, wire_dtype) for i in bucket],
            axis=1)
        reduced = jax.lax.psum_scatter(
            rows, axis_name, scatter_dimension=0, tiled=True
        ).reshape(-1)
        if average:
            reduced = reduced / n
        offset = 0
        for i in bucket:
            k = zero1_shard_len(leaves[i].size, n)
            out[i] = reduced[offset:offset + k].astype(leaves[i].dtype)
            offset += k
    return jax.tree.unflatten(treedef, out)


def all_gather_tree(
    shard_tree: Any,
    template_tree: Any,
    axis_name: str = DATA_AXIS,
    threshold_bytes: int = DEFAULT_FUSION_THRESHOLD_BYTES,
    overlap: bool = True,
) -> Any:
    """The ZeRO-1 return leg: bucketed all-gather of per-device 1-D leaf
    shards (``reduce_scatter_tree``'s layout) back into full leaves with
    ``template_tree``'s shapes/dtypes.  Bucket membership mirrors the
    scatter's, so each bucket's update→gather chain depends only on its
    own shards and can overlap other buckets' remaining backward/update
    work.
    """
    shards, treedef = jax.tree.flatten(shard_tree)
    templates = jax.tree.leaves(template_tree)
    if not shards:
        return shard_tree
    n = jax.lax.axis_size(axis_name)
    buckets = _flatten_to_buckets(templates, threshold_bytes,
                                  _bucket_order(len(templates), overlap))
    out: list[jax.Array | None] = [None] * len(shards)
    for bucket in buckets:
        flat = jnp.concatenate([shards[i].reshape(-1) for i in bucket])
        gathered = jax.lax.all_gather(
            flat, axis_name, axis=0, tiled=True
        ).reshape(n, -1)
        offset = 0
        for i in bucket:
            t = templates[i]
            k = zero1_shard_len(t.size, n)
            out[i] = (
                gathered[:, offset:offset + k]
                .reshape(-1)[:t.size]
                .reshape(t.shape)
                .astype(t.dtype)
            )
            offset += k
    return jax.tree.unflatten(treedef, out)
