"""Multi-host bring-up: ``jax.distributed`` in place of ORTE/hydra + SSH mesh.

The reference forms a cluster by nmap subnet sweep -> ``nodeips.txt`` ->
all-to-all passwordless-SSH mesh (``azure-scripts/setup-pwdless-ssh.sh``),
then ``mpirun -hostfile ~/nodeips.txt`` launches one rank per worker on every
node (``run-tf-sing-ucx-openmpi.sh:99-109``).

On a TPU pod the control plane already knows the topology: every host runs
the same program and ``jax.distributed.initialize()`` discovers coordinator,
process count, and process id from the TPU metadata.  This module keeps the
*hostfile contract* anyway — a ``nodeips.txt``-style file can drive explicit
initialization for non-TPU-pod deployments (CPU clusters, tests), playing
exactly the role the reference file plays for mpirun (:25,101).
"""

from __future__ import annotations

import os
from pathlib import Path

import jax

# Default port for the JAX distributed coordinator (no reference analog;
# ORTE picks its own ports).
DEFAULT_COORDINATOR_PORT = 9944

# Hostfile contract: one IP/hostname per line, first line = coordinator
# (the reference's nodeips.txt, setup-pwdless-ssh.sh:32).
DEFAULT_HOSTFILE = Path.home() / "nodeips.txt"


def read_hostfile(path: Path | str | None = None) -> list[str]:
    """Parse a nodeips.txt-style hostfile (blank lines / #comments skipped)."""
    p = Path(path or DEFAULT_HOSTFILE)
    hosts = []
    for line in p.read_text().splitlines():
        line = line.strip()
        if line and not line.startswith("#"):
            hosts.append(line)
    if not hosts:
        raise ValueError(f"hostfile {p} contains no hosts")
    return hosts


def initialize(
    hostfile: Path | str | None = None,
    process_id: int | None = None,
    coordinator_port: int = DEFAULT_COORDINATOR_PORT,
) -> None:
    """Initialize multi-host JAX.

    Resolution order:
    1. Already initialized -> no-op.
    2. On a TPU pod (or under a cluster env JAX understands) with no explicit
       args -> ``jax.distributed.initialize()`` auto-detect.
    3. Explicit hostfile (+ process_id, or $TPU_HC_BENCH_PROCESS_ID) ->
       coordinator is the first host, num_processes is the line count —
       the mpirun-hostfile behavior (run-tf-sing-ucx-openmpi.sh:101).
    """
    if jax._src.distributed.global_state.client is not None:  # already up
        return
    explicit = hostfile is not None or process_id is not None
    if not explicit and os.environ.get("TPU_HC_BENCH_HOSTFILE") is None:
        jax.distributed.initialize()
        return
    hosts = read_hostfile(hostfile or os.environ.get("TPU_HC_BENCH_HOSTFILE"))
    if process_id is None:
        process_id = int(os.environ["TPU_HC_BENCH_PROCESS_ID"])
    if coordinator_port == DEFAULT_COORDINATOR_PORT:
        # env override so colocated launches (tests, the scaling harness)
        # can pick distinct ports without colliding on the default
        coordinator_port = int(os.environ.get(
            "TPU_HC_BENCH_COORDINATOR_PORT", coordinator_port))
    jax.distributed.initialize(
        coordinator_address=f"{hosts[0]}:{coordinator_port}",
        num_processes=len(hosts),
        process_id=process_id,
    )


def is_coordinator() -> bool:
    """True on the rank-0 host (the reference's 'head node' running the launcher)."""
    return jax.process_index() == 0
