"""Fabric selection: the reference's ``ib|sock`` switch, TPU-native.

The reference launchers take a 4th positional arg ``fabric in {ib, sock}``
(``run-tf-sing-ucx-openmpi.sh:27-30``): ``ib`` configures the fast path
(UCX pml, HCOLL collectives, live PKEY read from sysfs, ``:85-92``) and
``sock`` forces plain TCP (``-mca pml ^ucx``, ``:93-94``) — a slow fallback
that doubles as the no-InfiniBand smoke test (SURVEY.md §4.4).

TPU translation (BASELINE.json north star): ``ib -> ici`` (XLA collectives
over the inter-chip interconnect — the compiled fast path) and
``sock -> host`` (gradients bounced through host memory and reduced on CPU —
a genuinely slow, genuinely working fallback that exercises the full train
loop without ICI collectives, exactly the role ``sock`` plays).  ``dcn`` is
accepted as an alias for the cross-slice case on multi-slice pods, where the
mesh layout (topology.build_mesh) already puts the host-crossing phase of
the allreduce on DCN.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any

import jax
import numpy as np


class Fabric(enum.Enum):
    ICI = "ici"    # fast path: XLA collectives over ICI (reference: ib)
    DCN = "dcn"    # cross-slice collectives ride DCN (multi-slice pods)
    HOST = "host"  # slow path: host-mediated reduce (reference: sock)

    @property
    def is_fast(self) -> bool:
        return self is not Fabric.HOST


_ALIASES = {
    "ib": Fabric.ICI,      # reference fast path maps to ICI
    "ici": Fabric.ICI,
    "dcn": Fabric.DCN,
    "sock": Fabric.HOST,   # reference slow/TCP path maps to host bounce
    "host": Fabric.HOST,
}


def resolve_fabric(name: str) -> Fabric:
    """Accept both reference (``ib|sock``) and native (``ici|dcn|host``) names."""
    try:
        return _ALIASES[name.strip().lower()]
    except KeyError:
        raise ValueError(
            f"unknown fabric {name!r}; expected one of {sorted(_ALIASES)}"
        ) from None


@dataclasses.dataclass(frozen=True)
class FabricConfig:
    """Launch-time fabric tuning — the analog of :85-95's env assembly."""

    fabric: Fabric
    fusion_threshold_bytes: int

    def env_exports(self) -> dict[str, str]:
        """Env/registry entries (UCX_TLS / HCOLL / FI_PROVIDER analogs)."""
        return {
            "TPU_HC_BENCH_FABRIC": self.fabric.value,
            "TPU_HC_BENCH_FUSION_THRESHOLD": str(self.fusion_threshold_bytes),
        }

    def summary(self) -> str:
        if self.fabric.is_fast:
            return (
                f"fabric={self.fabric.value}: XLA collectives over "
                f"ICI{'+DCN' if self.fabric is Fabric.DCN else ''}, "
                f"fusion_threshold={self.fusion_threshold_bytes}B"
            )
        return "fabric=host: host-mediated allreduce (slow-path smoke test)"


def host_allreduce(tree: Any, devices: list[jax.Device] | None = None) -> Any:
    """The ``sock`` slow path: reduce per-device values through host memory.

    Takes a pytree whose leaves are stacked per-device arrays (leading axis =
    device), pulls them to host, averages with numpy, and returns replicated
    host arrays.  Deliberately unoptimized — it exists to (a) smoke-test the
    training loop without ICI collectives and (b) give the fabric A/B
    comparison its slow arm, mirroring the reference's ib-vs-sock experiment
    (README.md:70-73).

    Multi-process (world > 1): the stacked leaves are global jax.Arrays
    whose shards span hosts, so each process reduces only its addressable
    shards, then the partial sums cross hosts in ONE flat
    ``process_allgather`` per call — the TCP hop of the reference's sock
    fabric (gradient bytes leave the device fabric and transit host
    memory + the coordinator network every step).
    """
    del devices
    if jax.process_count() == 1:
        def _reduce(leaf):
            host = np.asarray(jax.device_get(leaf))
            return np.mean(host, axis=0)

        return jax.tree.map(_reduce, tree)

    from jax.experimental import multihost_utils

    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    partial_sums, local_rows = [], None
    for leaf in leaves:
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        rows = sum(s.shape[0] for s in shards)
        if local_rows is None:
            local_rows = rows
        # sum over this host's slice of the device axis, in f32
        partial_sums.append(
            sum(s.sum(axis=0, dtype=np.float32) for s in shards))
    flat = (np.concatenate([p.ravel() for p in partial_sums])
            if partial_sums else np.zeros((0,), np.float32))
    gathered = np.asarray(multihost_utils.process_allgather(flat))
    total = gathered.sum(axis=0) / (local_rows * jax.process_count())
    out, off = [], 0
    for leaf, p in zip(leaves, partial_sums):
        n = p.size
        out.append(total[off:off + n].reshape(p.shape).astype(leaf.dtype))
        off += n
    return jax.tree.unflatten(treedef, out)
