"""Pipeline parallelism: GPipe microbatching over a ``pipe`` mesh axis.

Beyond-reference capability (the reference is DP-only, SURVEY.md §2c —
pipeline parallelism listed "absent"), built the TPU way: the schedule is
a ``lax.scan`` whose carried activations hop stage-to-stage with
``ppermute`` (neighbor ICI transfers), so the whole pipeline — bubbles,
stage compute, inter-stage sends — compiles into ONE XLA program per
training step.  The backward schedule is not hand-written: JAX transposes
the forward scan, turning each ``ppermute`` into its reverse hop, which
*is* GPipe's backward pass.

Layer-to-stage mapping reuses the decoder families' parameter trees
verbatim (any model exposing the ``pp_embed``/``pp_layer_module``/
``pp_head`` interface with ``layer_i`` param naming — GPTLM and LlamaLM):
``stack_layer_params`` stacks the ``layer_i`` subtrees into one
``[L, ...]`` pytree whose leading dim shards over the pipe axis
(``L / n_pipe`` layers per stage, applied with an inner ``lax.scan`` —
scan-over-layers).  Embedding and head replicate and run on every stage;
gating + the gradient psums below keep the math exactly equal to the
unsharded model (tested in tests/test_pipeline.py).

Gradient bookkeeping (the subtle part): the device-local loss is
``pmean``-ed over BOTH mesh axes inside the loss function, so for the
total objective J each rank's autodiff produces its *partial* dJ/dparam.
Stage-sharded layer params receive their full gradient locally (every
rank's loss routes through every stage exactly once), so they psum over
``data`` only; replicated embed/head params psum over ``data`` AND
``pipe`` — the embedding contribution lives on pipe-rank 0 (input gate),
the head contribution is 1/n on every rank (all ranks compute the head on
the broadcast pipeline output), and the tied-embedding case is the sum of
both, which one psum delivers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_hc_bench.topology import DATA_AXIS, PIPE_AXIS


def pipeline_apply(block_fn, stage_params, x_mb, axis_name: str = PIPE_AXIS,
                   rng=None):
    """Run microbatches through the pipeline; must be inside shard_map.

    ``block_fn(layer_params, h, key) -> (h, aux)`` applies ONE layer
    (``key`` is a per-(stage, layer, tick) dropout key, or None when
    ``rng`` is None; ``aux`` is a scalar auxiliary-loss term, 0 for plain
    layers).  ``stage_params`` is this stage's ``[L_local, ...]`` stacked
    layer pytree.  ``x_mb`` is ``[M, mb, ...]`` microbatched activations,
    replicated over the pipe axis (only stage 0 reads them).  Returns
    ``([M, mb, ...] outputs, aux_sum)``: outputs identical on every stage
    (psum-broadcast from the last); ``aux_sum`` is this *stage's* summed
    aux over its layers and the M valid microbatches (bubble ticks that
    process garbage activations are excluded by the validity gate).

    The scan runs ``M + n - 1`` ticks (GPipe fill + drain); at tick t,
    stage 0 injects microbatch t, stage ``s`` works on microbatch
    ``t - s``, and the last stage retires microbatch ``t - (n-1)``.
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    num_mb = x_mb.shape[0]
    n_local = jax.tree.leaves(stage_params)[0].shape[0]

    def stage_apply(h, t):
        if rng is None:
            keys = jnp.zeros((n_local, 2), jnp.uint32)  # unused placeholder
        else:
            # unique per (stage, tick, layer)
            keys = jax.random.split(
                jax.random.fold_in(jax.random.fold_in(rng, t), idx), n_local)

        def body(h, xs):
            p, key = xs
            h, aux = block_fn(p, h, None if rng is None else key)
            return h, aux

        h, auxes = jax.lax.scan(body, h, (stage_params, keys))
        return h, auxes.sum()

    perm = [(i, (i + 1) % n) for i in range(n)]
    state0 = jnp.zeros_like(x_mb[0])
    out0 = jnp.zeros_like(x_mb)
    aux0 = jnp.zeros((), jnp.float32)

    def tick(carry, t):
        state, outputs, aux_acc = carry
        mb_in = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, num_mb - 1), 0, keepdims=False)
        h = jnp.where(idx == 0, mb_in, state)
        y, aux = stage_apply(h, t)
        # this stage works on microbatch t - idx; outside [0, M) it is a
        # fill/drain bubble processing garbage -> drop its aux term
        valid = (t - idx >= 0) & (t - idx < num_mb)
        aux_acc = aux_acc + jnp.where(valid, aux.astype(jnp.float32), 0.0)
        t_out = t - (n - 1)
        o_idx = jnp.clip(t_out, 0, num_mb - 1)
        cur = jax.lax.dynamic_index_in_dim(outputs, o_idx, 0, keepdims=False)
        retired = jnp.where((idx == n - 1) & (t_out >= 0), y, cur)
        outputs = jax.lax.dynamic_update_index_in_dim(outputs, retired,
                                                      o_idx, 0)
        state = jax.lax.ppermute(y, axis_name, perm)
        return (state, outputs, aux_acc), None

    (_, outputs, aux_sum), _ = jax.lax.scan(
        tick, (state0, out0, aux0), jnp.arange(num_mb + n - 1))
    # broadcast the retired outputs from the last stage to every stage
    outputs = jax.lax.psum(
        jnp.where(idx == n - 1, outputs, jnp.zeros_like(outputs)), axis_name)
    return outputs, aux_sum


def stack_layer_params(params: dict, num_layers: int) -> dict:
    """Decoder param tree (``layer_i`` naming) -> {'trunk': [L, ...]
    stacked layers, <rest>}.

    Host (numpy) leaves stack with ``np.stack`` so the checkpoint-
    interchange path never materializes the full stacked trunk on the
    default device — ``place_pp_state`` then does the only transfer,
    straight into the pipe sharding (a PP model may not fit one device).
    """
    import numpy as np

    layers = [params[f"layer_{i}"] for i in range(num_layers)]
    rest = {k: v for k, v in params.items() if not k.startswith("layer_")}

    def stack(*xs):
        if all(isinstance(x, np.ndarray) for x in xs):
            return np.stack(xs)
        return jnp.stack(xs)

    rest["trunk"] = jax.tree.map(stack, *layers)
    return rest


def unstack_layer_params(params: dict, num_layers: int) -> dict:
    """Inverse of ``stack_layer_params`` (checkpoint interchange)."""
    out = {k: v for k, v in params.items() if k != "trunk"}
    for i in range(num_layers):
        out[f"layer_{i}"] = jax.tree.map(lambda x: x[i], params["trunk"])
    return out


def _map_param_like(opt_state, params, f, otherwise=None):
    """Apply ``f`` to every params-shaped subtree of an optax state
    (momentum/adam moments); other leaves pass through ``otherwise``
    (default: untouched).  The single home of the is-param-like
    structural predicate — sharding-spec derivation and checkpoint
    restacking must agree on it."""
    pstruct = jax.tree.structure(params)
    is_param_like = lambda n: jax.tree.structure(n) == pstruct

    def per_node(n):
        if is_param_like(n):
            return f(n)
        return n if otherwise is None else otherwise(n)

    return jax.tree.map(per_node, opt_state, is_leaf=is_param_like)


def pp_state_from_train_state(state, num_layers: int):
    """DP TrainState -> PP ``(params, opt_state)`` (checkpoint interchange).

    Restacks the ``layer_i`` param subtrees — and the params-shaped
    subtrees of the optimizer state (momentum trace) — into the
    pipe-shardable ``trunk`` layout, so a run checkpointed under DP
    resumes under DP x PP with the optimizer state intact.  The reverse
    direction is ``train_state_from_pp``.
    """
    params = stack_layer_params(state.params, num_layers)
    opt_state = _map_param_like(
        state.opt_state, state.params,
        lambda t: stack_layer_params(t, num_layers))
    return params, opt_state


def train_state_from_pp(params: dict, opt_state, template, num_layers: int):
    """PP ``(params, opt_state)`` -> DP TrainState (via a template state
    supplying apply_fn/tx/step/batch_stats)."""
    p = unstack_layer_params(params, num_layers)
    opt = _map_param_like(opt_state, params,
                          lambda t: unstack_layer_params(t, num_layers))
    return template.replace(params=p, opt_state=opt)


def pp_param_specs(params: dict, tp: bool = False) -> dict:
    """trunk shards its leading (layer) dim over pipe; the rest replicates.

    ``tp=True`` (DP x PP x TP hybrid) additionally shards each stacked
    layer tensor's feature dims over the model axis per the Megatron
    ``tp_param_spec`` rules (applied to the within-layer path, skipping
    the leading stacked-layer dim).  These full specs are for *placement*;
    the pipeline's partial-manual shard_map uses the pipe-only variant as
    ``in_specs`` and the model axis stays auto (GSPMD).
    """
    from tpu_hc_bench.train.step import tp_param_spec

    def trunk_leaf(path, x):
        inner: tuple = ()
        if tp:
            name = "/".join(getattr(k, "key", str(k)) for k in path)
            inner = tuple(tp_param_spec(name, x.ndim - 1))
        pad = (None,) * (x.ndim - 1 - len(inner))
        return P(PIPE_AXIS, *inner, *pad)

    out = {}
    for k, v in params.items():
        if k == "trunk":
            out[k] = jax.tree_util.tree_map_with_path(trunk_leaf, v)
        else:
            out[k] = jax.tree.map(lambda x: P(), v)
    return out


def _opt_specs(opt_state, param_specs: dict, params: dict):
    """Specs for the optimizer state: param-shaped subtrees (momentum
    trace) inherit the param specs, everything else replicates."""
    return _map_param_like(opt_state, params, lambda _: param_specs,
                           otherwise=lambda _: P())


def _pp_forward(model, num_microbatches: int, deterministic: bool):
    """The shared DP x PP stage forward, derived from the model's
    ``pp_embed``/``pp_layer_module``/``pp_head`` interface; returns
    ``forward(params, tokens, rng) -> (logits, aux_sum)``.  Must run
    inside a shard_map binding the pipe axis."""
    layer = model.pp_layer_module()

    def block_fn(p, h, key):
        rngs = None if key is None else {"dropout": key}
        y, upd = layer.apply({"params": p}, h, not deterministic and
                             key is not None, rngs=rngs, mutable=["losses"])
        terms = jax.tree.leaves(upd.get("losses", {}))
        aux = (sum(jnp.sum(t) for t in terms) if terms
               else jnp.zeros((), jnp.float32))
        return y, aux

    if model.remat:
        # --gradient_checkpointing: recompute each layer in the backward
        block_fn = jax.checkpoint(block_fn)

    def forward(params, tokens, rng):
        b, s = tokens.shape
        x, rng = model.pp_embed(params, tokens, rng)
        mb = b // num_microbatches
        xs = x.reshape(num_microbatches, mb, s, model.hidden)
        ys, aux = pipeline_apply(block_fn, params["trunk"], xs, rng=rng)
        x = ys.reshape(b, s, model.hidden)
        return model.pp_head(params, x), aux

    return forward


def build_pp_eval_step(mesh: Mesh, model, cfg, num_microbatches: int,
                       example_params: dict, tp: bool = False):
    """Forward-only DP x PP eval step (tf_cnn --eval under
    --pipeline_parallel, round 3): returns ``step(params, batch) ->
    (loss, correct)`` with the exact global weighted mean, matching
    ``train.step.build_eval_step``'s arms so PP eval reports the same
    numbers as DP eval of the same checkpoint."""
    del cfg
    forward = _pp_forward(model, num_microbatches, deterministic=True)

    def device_eval(params, batch):
        from tpu_hc_bench.train.step import weighted_text_metrics

        tokens, targets, weights = batch
        logits, _ = forward(params, tokens, None)
        num, den, correct = weighted_text_metrics(logits, targets, weights)
        num = jax.lax.psum(num, DATA_AXIS)
        den = jax.lax.psum(den, DATA_AXIS)
        correct = jax.lax.psum(correct, DATA_AXIS)
        # outputs are identical on every pipe rank (the head runs on the
        # broadcast pipeline output) — no pipe reduction needed
        return num / jnp.maximum(den, 1.0), correct

    pspecs = pp_param_specs(example_params)
    manual: dict = {}
    if tp:
        manual = {"axis_names": frozenset({DATA_AXIS, PIPE_AXIS})}
    shard_fn = jax.shard_map(
        device_eval, mesh=mesh,
        in_specs=(pspecs, P(DATA_AXIS)),
        out_specs=(P(), P()),
        check_vma=False,
        **manual,
    )
    return jax.jit(shard_fn)


def build_pp_train_step(mesh: Mesh, model, cfg, num_microbatches: int,
                        example_params: dict, example_opt_state,
                        deterministic: bool = False, tp: bool = False):
    """DP x PP training step for any decoder exposing the PP interface.

    ``model`` implements ``pp_embed`` / ``pp_layer_module`` / ``pp_head``
    (GPTLM and LlamaLM today) and its params have been restacked with
    ``stack_layer_params``.  The stage forward is DERIVED from those
    methods — no per-family wiring lives here.  The step is a
    ``shard_map`` over the ``(data, pipe)`` mesh: batch sharded over
    data, trunk sharded over pipe, embed/head replicated.
    ``deterministic=True`` disables dropout (the numerically-testable
    mode, = ``train=False``).  MoE layers' Switch aux losses ARE
    collected: each stage sums its layers' sown terms over the valid
    microbatches (``pipeline_apply``), and the per-microbatch-grouped
    mean joins the objective at ``AUX_LOSS_COEF`` (a grouped estimator of
    the same Switch statistic — not bitwise the full-batch value; see the
    note in ``device_step``).
    """
    from tpu_hc_bench.train.step import make_optimizer

    tx = make_optimizer(cfg)
    forward = _pp_forward(model, num_microbatches, deterministic)

    def device_step(params, opt_state, batch, rng):
        tokens, targets, weights = batch
        n_pipe = jax.lax.axis_size(PIPE_AXIS)
        is_last = jax.lax.axis_index(PIPE_AXIS) == n_pipe - 1
        if deterministic:
            rng = None
        else:
            rng = jax.random.fold_in(rng, jax.lax.axis_index(DATA_AXIS))

        def loss_fn(p):
            logits, aux = forward(p, tokens, rng)
            losses = optax.softmax_cross_entropy_with_integer_labels(
                logits, targets)
            loss = (losses * weights).sum() / jnp.maximum(weights.sum(), 1.0)
            # every pipe rank computes the head on the broadcast pipeline
            # output, but only the LAST stage's loss is "real": gating it
            # makes exactly one backward seed enter the shared pipeline per
            # data column, so no cotangent is double-counted regardless of
            # psum-transpose semantics.  The aux term is NOT gated: each
            # stage's sum is a distinct term of the objective, seeded once
            # on its own rank.  NOTE the per-microbatch aux mean is a
            # *grouped estimator*: the Switch aux is a product of two
            # per-group means, so it differs from the full-batch statistic
            # by the cross-group covariance (same estimator family the
            # data-sharded non-PP step uses per device shard).
            from tpu_hc_bench.models.moe import AUX_LOSS_COEF

            return (jnp.where(is_last, loss, 0.0)
                    + AUX_LOSS_COEF * aux / num_microbatches)

        if cfg.forward_only:
            loss = loss_fn(params)
            loss = jax.lax.pmean(jax.lax.psum(loss, PIPE_AXIS), DATA_AXIS)
            return params, opt_state, loss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # stage-sharded trunk: each rank holds its own stages' full grads
        # -> average over data columns only.  Replicated embed/head: the
        # contributions live on single pipe ranks (embedding via the
        # stage-0 input gate, head/ln_f via the gated last-stage loss; the
        # tied embedding is the sum of both) -> collect with a pipe psum,
        # then average over data.
        grads = {
            k: jax.tree.map(
                lambda g: jax.lax.pmean(
                    g if k == "trunk" else jax.lax.psum(g, PIPE_AXIS),
                    DATA_AXIS),
                v)
            for k, v in grads.items()
        }
        loss = jax.lax.pmean(jax.lax.psum(loss, PIPE_AXIS), DATA_AXIS)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # shard_map specs carry the MANUAL axes only (data, pipe); under the
    # DPxPPxTP hybrid the model axis stays auto — the arrays arrive
    # model-sharded (place_pp_state tp=True) and GSPMD partitions the
    # per-stage layer math, inserting the Megatron all-reduces
    pspecs = pp_param_specs(example_params)
    ospecs = _opt_specs(example_opt_state, pspecs, example_params)
    manual: dict = {}
    if tp:
        manual = {"axis_names": frozenset({DATA_AXIS, PIPE_AXIS})}
    shard_fn = jax.shard_map(
        device_step, mesh=mesh,
        in_specs=(pspecs, ospecs, P(DATA_AXIS), P()),
        out_specs=(pspecs, ospecs, P()),
        check_vma=False,
        **manual,
    )
    jitted = jax.jit(shard_fn, donate_argnums=(0, 1))

    def step(params, opt_state, batch, rng=None):
        if rng is None:
            if not deterministic:
                raise ValueError(
                    "pipeline step with dropout active (deterministic="
                    "False) requires a per-step rng key — a silent fixed "
                    "key would reuse identical dropout masks every step"
                )
            rng = jax.random.PRNGKey(0)   # ignored under deterministic
        return jitted(params, opt_state, batch, rng)

    return step, tx


def place_pp_state(params: dict, opt_state, mesh: Mesh, tp: bool = False):
    """Place a PP ``(params, opt_state)`` on the mesh: trunk sharded over
    the pipe axis (and, with ``tp``, feature dims over the model axis),
    everything else replicated.  ``opt_state=None`` places params only
    (forward-only eval never needs the params-sized momentum trace)."""
    pspecs = pp_param_specs(params, tp=tp)
    put = lambda tree, specs: jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs)
    if opt_state is None:
        return put(params, pspecs)
    ospecs = _opt_specs(opt_state, pspecs, params)
    return put(params, pspecs), put(opt_state, ospecs)


def make_pp_state(model, cfg, example_tokens, mesh: Mesh, tp: bool = False):
    """Init the decoder's params, restack layers for the pipe axis,
    init the optimizer.

    Returns ``(params, opt_state)`` placed on the mesh (trunk sharded over
    pipe, everything else replicated).
    """
    from tpu_hc_bench.train.step import make_optimizer

    init_fn = jax.jit(functools.partial(model.init, train=False))
    variables = init_fn(
        {"params": jax.random.PRNGKey(cfg.seed),
         "dropout": jax.random.PRNGKey(cfg.seed + 1)},
        jnp.asarray(example_tokens[:1]),
    )
    params = stack_layer_params(variables["params"], model.num_layers)
    tx = make_optimizer(cfg)
    opt_state = tx.init(params)
    return place_pp_state(params, opt_state, mesh, tp=tp)
