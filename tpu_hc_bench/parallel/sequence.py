"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference's workload has no sequence dimension (CNNs only, SURVEY.md
§2c), but its *scale story* — one capability axis per fabric hop — maps on
TPU to sharding the sequence dimension of transformer attention over a mesh
axis, so contexts longer than one chip's HBM can be trained.  Two standard
TPU-native strategies, both composing with the data-parallel axis:

- **Ring attention** (blockwise, ``jax.lax.ppermute``): K/V shards rotate
  around the ring while each device accumulates its queries' attention with
  a numerically-stable online softmax.  Communication is neighbor-to-
  neighbor over ICI and overlaps with the per-block matmuls; memory is
  O(local_seq^2) per step instead of O(global_seq^2).
- **Ulysses** (all-to-all): one ``all_to_all`` re-shards activations from
  sequence-sharded to head-sharded, attention runs locally over the full
  sequence with ``heads/axis_size`` heads, and a second ``all_to_all``
  restores sequence sharding.  Cheaper at moderate context, requires
  ``heads % axis_size == 0``.

Both are called *inside* a ``jax.shard_map`` where ``axis_name`` is bound
and q/k/v carry the local sequence shard: ``[batch, local_seq, heads,
head_dim]``.  Outputs have the same layout.  Softmax statistics accumulate
in float32 regardless of input dtype (bf16-safe).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tpu_hc_bench.topology import SEQ_AXIS

_NEG_INF = -1e30  # mask value: large-negative, not -inf (keeps exp() clean)


def dense_attention(q, k, v, causal: bool = False, scale: float | None = None,
                    q_offset: int | jax.Array = 0,
                    k_offset: int | jax.Array = 0):
    """Plain softmax attention — the single-device reference implementation.

    ``q``/``k``/``v``: [batch, seq, heads, head_dim].  ``q_offset``/
    ``k_offset`` are the global positions of the first query/key row (used
    for causal masking of sequence shards).
    """
    d = q.shape[-1]
    scale = (1.0 / d ** 0.5) if scale is None else scale
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        s = jnp.where(qpos[:, None] >= kpos[None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def ring_attention(q, k, v, axis_name: str = SEQ_AXIS, causal: bool = False,
                   scale: float | None = None, kv_repeat: int = 1):
    """Blockwise ring attention over a sequence-sharded mesh axis.

    Must run inside ``shard_map`` with ``axis_name`` bound; q/k/v are the
    local sequence shards ``[batch, local_seq, heads, head_dim]``.  K/V
    travel the ring via ``ppermute`` (ICI neighbor hops); each of the
    ``axis_size`` steps folds one K/V block into the online-softmax
    accumulator (running max ``m``, normalizer ``l``, weighted sum ``o`` —
    all float32).  Equivalent to dense attention over the global sequence.

    ``kv_repeat > 1`` (GQA): k/v carry ``heads / kv_repeat`` KV heads and
    are broadcast up to the query-head count *inside each fold* — the
    ring only ever moves the un-repeated KV bytes.
    """
    from tpu_hc_bench.parallel.collectives import ppermute_ring

    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = (1.0 / d ** 0.5) if scale is None else scale

    qpos = my * lq + jnp.arange(lq)                       # global query rows

    def fold(carry, k_blk, v_blk, src):
        if kv_repeat > 1:
            # block-local broadcast: no extra ring traffic
            k_blk = jnp.repeat(k_blk, kv_repeat, axis=2)
            v_blk = jnp.repeat(v_blk, kv_repeat, axis=2)
        m, l, o = carry
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            kpos = src * lk + jnp.arange(lk)
            visible = qpos[:, None] >= kpos[None, :]
            s = jnp.where(visible, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        if causal:
            # fully-masked rows still have m == _NEG_INF: force weights to 0
            p = jnp.where(visible, p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        o = (o * corr.transpose(0, 2, 1)[..., None]
             + jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32)))
        return m_new, l, o

    m0 = jnp.full((b, h, lq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, lq), jnp.float32)
    o0 = jnp.zeros((b, lq, h, d), jnp.float32)

    # fold the local block first, then n-1 ring rotations (no wasted hop)
    carry0 = fold((m0, l0, o0), k, v, my)

    def body(t, carry):
        k_blk, v_blk, acc = carry
        k_blk = ppermute_ring(k_blk, axis_name)
        v_blk = ppermute_ring(v_blk, axis_name)
        acc = fold(acc, k_blk, v_blk, (my - t) % n)
        return k_blk, v_blk, acc

    _, _, (m, l, o) = jax.lax.fori_loop(1, n, body, (k, v, carry0))
    l = jnp.maximum(l, 1e-30)
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = SEQ_AXIS,
                      causal: bool = False, scale: float | None = None,
                      attn_fn=None, kv_repeat: int = 1):
    """All-to-all (DeepSpeed-Ulysses-style) sequence parallelism.

    Re-shards [batch, local_seq, heads, head_dim] -> [batch, global_seq,
    local_heads, head_dim] with one ``all_to_all``, runs full-sequence
    attention on the local head group, then reverses the exchange.  Needs
    ``heads % axis_size == 0``.  ``attn_fn(q, k, v, causal=..., scale=...)``
    (always called with those keywords forwarded) overrides the local
    attention (e.g. a Pallas flash kernel); default is ``dense_attention``.

    ``kv_repeat > 1`` (GQA): k/v carry ``heads / kv_repeat`` KV heads and
    are exchanged un-repeated (needs ``kv_heads % axis_size == 0`` too),
    then broadcast to the local query-head count after the reshard — the
    all_to_all only ever moves the un-repeated KV bytes.
    """
    n = jax.lax.axis_size(axis_name)
    h = q.shape[2]
    h_kv = k.shape[2]
    if h % n:
        raise ValueError(f"heads={h} not divisible by axis size {n}")
    if kv_repeat > 1 and h_kv % n:
        raise ValueError(
            f"kv heads={h_kv} not divisible by axis size {n}")

    def seq_to_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    if kv_repeat > 1:
        qg = seq_to_heads(q)
        kg = jnp.repeat(seq_to_heads(k), kv_repeat, axis=2)
        vg = jnp.repeat(seq_to_heads(v), kv_repeat, axis=2)
    else:
        # one stacked exchange for q/k/v instead of three collective
        # launches (split/concat shifted by 1 for the leading stack dim)
        qg, kg, vg = jax.lax.all_to_all(
            jnp.stack((q, k, v)), axis_name, split_axis=3, concat_axis=2,
            tiled=True)
    if attn_fn is None:
        attn_fn = dense_attention
    out = attn_fn(qg, kg, vg, causal=causal, scale=scale)
    return heads_to_seq(out)


_IMPLS = {"dense", "flash", "ring", "ulysses", "ulysses_flash"}


def local_attention(q, k, v, impl: str = "dense",
                    axis_name: str | None = None, causal: bool = False,
                    scale: float | None = None, kv_repeat: int = 1):
    """Dispatch: the one attention entry point model code calls.

    ``impl='dense'``/``'flash'`` ignore ``axis_name`` (each shard attends
    locally — only correct unsharded); ``ring``/``ulysses``/
    ``ulysses_flash`` require ``axis_name``.  ``flash`` is the Pallas
    blocked-softmax kernel (``ops.flash_attention``); ``dense`` is the
    XLA-compiled reference; ``ulysses_flash`` composes the all-to-all
    sequence resharding with the flash kernel for the full-sequence local
    attention — the long-context production combination (O(S) memory from
    flash x S-scaling from the seq axis).

    ``kv_repeat > 1`` (GQA): k/v arrive with ``heads / kv_repeat`` KV
    heads.  The single-device impls broadcast them up front (pure compute
    reshape); the sequence-parallel impls move the un-repeated KV bytes
    over the fabric and broadcast after/inside the collective.
    """
    if impl not in _IMPLS:
        raise ValueError(
            f"unknown attention impl {impl!r}; have {sorted(_IMPLS)}"
        )
    if impl in ("dense", "flash") and kv_repeat > 1:
        k = jnp.repeat(k, kv_repeat, axis=2)
        v = jnp.repeat(v, kv_repeat, axis=2)
    if impl == "dense":
        return dense_attention(q, k, v, causal=causal, scale=scale)
    if impl == "flash":
        from tpu_hc_bench.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, scale=scale)
    if axis_name is None:
        raise ValueError(f"impl={impl!r} requires axis_name (a bound mesh axis)")
    if impl == "ring":
        return ring_attention(q, k, v, axis_name, causal=causal, scale=scale,
                              kv_repeat=kv_repeat)
    if impl == "ulysses_flash":
        from tpu_hc_bench.ops.flash_attention import flash_attention

        return ulysses_attention(q, k, v, axis_name, causal=causal,
                                 scale=scale, attn_fn=flash_attention,
                                 kv_repeat=kv_repeat)
    assert impl == "ulysses", impl   # _IMPLS membership checked above
    return ulysses_attention(q, k, v, axis_name, causal=causal, scale=scale,
                             kv_repeat=kv_repeat)
