"""Runtime fault tolerance for benchmark/training runs.

The reference harness treats every run as a disposable 150-step
measurement — a NaN, a preempted VM, or a hung collective just kills the
job (SURVEY.md §5).  Production TPU fleets live with preemption and
silent numeric corruption as the common case, so this package makes runs
*survive* the failures the analysis (PR 1) and observability (PR 2)
layers can only report:

- ``guards``     — jit-compatible non-finite detection on loss/grad
                   global norm with an ``--on_nonfinite={abort,skip,
                   rewind}`` policy and a consecutive-failure budget.
- ``preempt``    — SIGTERM/SIGINT → flag polled at step boundaries →
                   one emergency checkpoint + metrics flush → distinct
                   exit code; ``--resume=auto`` closes the loop.
- ``watchdog``   — monitor thread over the driver's step-completion
                   markers; on ``--step_timeout_s`` of silence it dumps
                   every Python thread stack + the last metrics record
                   and aborts instead of hanging a cluster forever.
- ``inject``     — ``--inject_fault=nan_loss@40,hang@80:30,sigterm@120,
                   io_error@ckpt`` deterministic fault injection, so
                   every recovery path is exercised by real tests.
- ``retry``      — bounded retry-with-backoff for checkpoint/metrics
                   I/O errors.

Every resilience event (``nonfinite_skip``, ``rewind``,
``emergency_ckpt``, ``preempt``, ``watchdog_dump``, ``injected_fault``,
``io_retry``) is emitted as a structured record into the PR-2 metrics
stream, so ``python -m tpu_hc_bench.obs summarize`` shows them.

Process exit-code contract (documented in README.md, returned by
``launcher.main`` / asserted by the subprocess tests):
"""

# Exit codes: chosen from/near the BSD sysexits range so they never
# collide with shell (1/2), signal (128+N), or Python (1) conventions.
EXIT_OK = 0                 # clean run, nonzero throughput measured
EXIT_ZERO_THROUGHPUT = 1    # run completed but measured no progress
EXIT_WATCHDOG = 70          # watchdog abort: no step completed within
                            # --step_timeout_s (EX_SOFTWARE: the only
                            # trustworthy signal when a collective
                            # deadlocks — stacks were dumped to stderr)
EXIT_PREEMPTED = 75         # SIGTERM/SIGINT honored: emergency
                            # checkpoint written, relaunch with
                            # --resume=auto to continue (EX_TEMPFAIL)

# The contract as a classification table: exit code -> class token
# (None = clean success).  This is the ONE home — the tuner's runner,
# the sweep, and the fleet supervisor all consume it from here; two
# drifting copies would mean a scheduler reacting to a code the
# launcher no longer emits (the regex-miscount failure mode of
# ADVICE.md round 5, relocated to process management).
EXIT_CLASSES: dict[int, str | None] = {
    EXIT_OK: None,
    EXIT_ZERO_THROUGHPUT: "zero-throughput",
    EXIT_WATCHDOG: "watchdog-timeout",
    EXIT_PREEMPTED: "preempted",
}


def classify_exit(code: int) -> str | None:
    """The exit-code contract as one lookup: None for a clean run, the
    class token for a contract code, ``exit-<n>`` for anything else
    (a crash outside the contract), and ``signal-<n>`` for a negative
    subprocess returncode (killed by signal n before the handler ran —
    the no-emergency-checkpoint death the fleet must treat as crash,
    not preemption)."""
    if code in EXIT_CLASSES:
        return EXIT_CLASSES[code]
    if code < 0:
        return f"signal-{-code}"
    return f"exit-{code}"


# The error re-exports resolve lazily (PEP 562): ``guards`` pulls in
# jax/optax (~10s cold on this container), and the exit-code table
# above must stay importable by pure process-orchestration code (the
# tune runner, the fleet supervisor) that never touches a device.
_LAZY = {
    "GuardBudgetError": "tpu_hc_bench.resilience.guards",
    "NonFiniteError": "tpu_hc_bench.resilience.guards",
    "PreemptedError": "tpu_hc_bench.resilience.preempt",
}

__all__ = [
    "EXIT_OK", "EXIT_ZERO_THROUGHPUT", "EXIT_WATCHDOG", "EXIT_PREEMPTED",
    "EXIT_CLASSES", "classify_exit",
    "GuardBudgetError", "NonFiniteError", "PreemptedError",
]


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
