"""Runtime fault tolerance for benchmark/training runs.

The reference harness treats every run as a disposable 150-step
measurement — a NaN, a preempted VM, or a hung collective just kills the
job (SURVEY.md §5).  Production TPU fleets live with preemption and
silent numeric corruption as the common case, so this package makes runs
*survive* the failures the analysis (PR 1) and observability (PR 2)
layers can only report:

- ``guards``     — jit-compatible non-finite detection on loss/grad
                   global norm with an ``--on_nonfinite={abort,skip,
                   rewind}`` policy and a consecutive-failure budget.
- ``preempt``    — SIGTERM/SIGINT → flag polled at step boundaries →
                   one emergency checkpoint + metrics flush → distinct
                   exit code; ``--resume=auto`` closes the loop.
- ``watchdog``   — monitor thread over the driver's step-completion
                   markers; on ``--step_timeout_s`` of silence it dumps
                   every Python thread stack + the last metrics record
                   and aborts instead of hanging a cluster forever.
- ``inject``     — ``--inject_fault=nan_loss@40,hang@80:30,sigterm@120,
                   io_error@ckpt`` deterministic fault injection, so
                   every recovery path is exercised by real tests.
- ``retry``      — bounded retry-with-backoff for checkpoint/metrics
                   I/O errors.

Every resilience event (``nonfinite_skip``, ``rewind``,
``emergency_ckpt``, ``preempt``, ``watchdog_dump``, ``injected_fault``,
``io_retry``) is emitted as a structured record into the PR-2 metrics
stream, so ``python -m tpu_hc_bench.obs summarize`` shows them.

Process exit-code contract (documented in README.md, returned by
``launcher.main`` / asserted by the subprocess tests):
"""

# Exit codes: chosen from/near the BSD sysexits range so they never
# collide with shell (1/2), signal (128+N), or Python (1) conventions.
EXIT_OK = 0                 # clean run, nonzero throughput measured
EXIT_ZERO_THROUGHPUT = 1    # run completed but measured no progress
EXIT_WATCHDOG = 70          # watchdog abort: no step completed within
                            # --step_timeout_s (EX_SOFTWARE: the only
                            # trustworthy signal when a collective
                            # deadlocks — stacks were dumped to stderr)
EXIT_PREEMPTED = 75         # SIGTERM/SIGINT honored: emergency
                            # checkpoint written, relaunch with
                            # --resume=auto to continue (EX_TEMPFAIL)

from tpu_hc_bench.resilience.guards import (   # noqa: E402
    GuardBudgetError, NonFiniteError,
)
from tpu_hc_bench.resilience.preempt import PreemptedError  # noqa: E402

__all__ = [
    "EXIT_OK", "EXIT_ZERO_THROUGHPUT", "EXIT_WATCHDOG", "EXIT_PREEMPTED",
    "GuardBudgetError", "NonFiniteError", "PreemptedError",
]
