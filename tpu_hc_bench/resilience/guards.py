"""Non-finite step guards: detect NaN/inf loss or gradients in-step.

The PaLM training report's loss-spike protocol (PAPERS.md) is the
standard answer to silent numeric corruption: detect the bad step,
refuse its update, and either continue (``skip``) or restart from the
last good state with the offending data skipped (``rewind``).  The
detection here is **jit-compatible** — ``finite_flag`` runs inside the
compiled train step, and ``select_state`` drops the update with a
``jnp.where`` select *inside the same compiled program*, which is the
only donation-safe way to do it: the input state buffers are donated to
the step, so a host-side "keep the old state" after the fact would read
freed buffers.  The state is threaded through the select instead.

Budget accounting (``GuardTracker``) stays on device as two int32
scalars updated by a tiny jitted program per step — no host sync in the
dispatch path.  The driver observes them once per sync window through a
DOUBLE-BUFFERED fetch (``handles`` snapshots the refs at window N, the
values are fetched at window N+1 when they are long complete — the hot
loop never stalls on the fetch), enforcing the ``--max_bad_steps``
consecutive-failure budget one window late; saves, preemption, and the
final step settle synchronously (``poll``) so a poisoned run still
terminates and poisoned state is never persisted.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


class NonFiniteError(RuntimeError):
    """A non-finite loss/gradient was detected and policy says die."""


class GuardBudgetError(NonFiniteError):
    """The --max_bad_steps consecutive-failure budget was exhausted."""


def finite_flag(loss, grads=None):
    """Scalar bool: loss (and, when given, the gradient global norm)
    are all finite.  Traceable — call inside the compiled step."""
    ok = jnp.isfinite(loss)
    if grads is not None and jax.tree.leaves(grads):
        ok = ok & jnp.isfinite(optax.global_norm(grads))
    return ok


def select_state(ok, new_state, old_state):
    """Thread the state through a select: the updated tree when ``ok``,
    the incoming tree otherwise (the ``skip`` policy's dropped update).
    Must run inside the same compiled program as the update, so donation
    of the input state stays sound."""
    return jax.tree.map(
        lambda n, o: jnp.where(ok, n, o), new_state, old_state)


def nonfinite_metric(ok):
    """The per-step guard metric: 1 when the step was bad, else 0."""
    return jnp.where(ok, 0, 1).astype(jnp.int32)


class GuardTracker:
    """Device-side (streak, total, peak) counters over the per-step
    guard flag.

    ``update`` dispatches one tiny jitted program per step (async, no
    host round trip); ``poll`` fetches the scalars — the one deliberate
    sync point, paid once per sync window by the driver.  ``peak`` is
    the longest streak ever seen, so a consecutive-failure run that
    ends *inside* a window (streak already reset to 0 by a good step at
    the boundary) still trips the --max_bad_steps budget.
    """

    def __init__(self):
        self.reset()

    @staticmethod
    @jax.jit
    def _advance(streak, total, peak, bad):
        bad = (bad > 0).astype(jnp.int32)
        streak = jnp.where(bad > 0, streak + 1, 0)
        return streak, total + bad, jnp.maximum(peak, streak)

    def update(self, bad) -> None:
        self._streak, self._total, self._peak = self._advance(
            self._streak, self._total, self._peak, bad)

    def poll(self) -> tuple[int, int, int]:
        """Fetch ``(consecutive_bad, total_bad, peak_consecutive)`` —
        syncs the tracker."""
        streak, total, peak = jax.device_get(
            [self._streak, self._total, self._peak])
        return int(streak), int(total), int(peak)

    def handles(self) -> tuple:
        """The live ``(streak, total, peak)`` device scalars, as refs.

        The driver's double-buffered window poll snapshots these at a
        sync-window boundary and ``device_get``s them one window LATER,
        when their producing steps have long completed — a fetch that
        never stalls the dispatch path (``_advance`` returns fresh
        arrays each step, so held refs are a stable snapshot)."""
        return (self._streak, self._total, self._peak)

    def reset(self) -> None:
        self._streak = jnp.zeros((), jnp.int32)
        self._total = jnp.zeros((), jnp.int32)
        self._peak = jnp.zeros((), jnp.int32)


def guard_mode(cfg) -> str:
    """The step builders' guard wiring for a resolved config.

    ``"skip"``  — detect AND drop bad updates via the in-step select;
    ``"flag"``  — detect only (the ``rewind`` policy restores a
    checkpoint, so the poisoned update needs no select);
    ``"off"``   — no guard ops in the compiled step (``abort`` checks
    the display-step losses the timeline already fetches, at zero cost
    to the hot path; forward-only steps have no update to protect).
    """
    policy = getattr(cfg, "on_nonfinite", "abort")
    if getattr(cfg, "forward_only", False) or getattr(cfg, "eval", False):
        return "off"
    if policy == "skip":
        return "skip"
    if policy == "rewind":
        return "flag"
    return "off"
