"""Deterministic fault injection: ``--inject_fault=CLASS@WHERE[,...]``.

Every recovery path in this package is exercised by *real* injected
failures, not hope.  The grammar names a failure class and the timed
step (or target) it fires at:

- ``nan_loss@N``   — poison step N's batch (float leaves × NaN), so the
                     loss AND gradients of that step are non-finite —
                     exercises the ``--on_nonfinite`` guard end to end.
- ``hang@N:S``     — sleep S seconds before dispatching step N
                     (completion markers stop arriving — the hung-
                     collective signature the watchdog exists for).
- ``sigterm@N``    — ``kill(self, SIGTERM)`` before step N — exercises
                     the preemption → emergency-checkpoint → resume path.
- ``io_error@ckpt``— the next checkpoint save raises ``OSError`` once —
                     exercises the bounded retry-with-backoff.

Entries may repeat (``nan_loss@3,nan_loss@4``).  Parsing is loud:
``flags.resolve()`` validates the spec at flag time, not after 50
warmup steps.  Each fired fault is printed and emitted as an
``injected_fault`` record into the metrics stream.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import time

#: the two lanes' fault vocabularies — one home, so a malformed entry
#: in EITHER lane names both grammars instead of leaving the user to
#: guess which spelling belongs to which flag
TRAIN_VOCAB = "nan_loss@STEP | hang@STEP:SECONDS | sigterm@STEP | io_error@ckpt"
SERVE_VOCAB = ("hang@STEP:SECONDS | nan_logits@RID | sigterm@T_SECONDS"
               " | pool_squeeze@T_SECONDS:PAGES")

_USAGE = (
    "--inject_fault grammar: comma-separated entries of "
    + TRAIN_VOCAB
)


def malformed(entry: str, lane: str = "train") -> str:
    """The ONE parse-error message both lanes raise: names the entry,
    the lane it was given to, and BOTH vocabularies (the most common
    mistake is a valid spelling handed to the wrong flag)."""
    return (f"malformed fault entry {entry!r} for the {lane} lane; "
            f"train grammar (--inject_fault): {TRAIN_VOCAB}; "
            f"serve grammar (--serve_faults): {SERVE_VOCAB}")


def split_entries(spec: str | None,
                  lane: str = "train") -> list[tuple]:
    """Shared ``CLASS@WHERE[:ARG]`` splitter for both lanes' fault
    grammars: comma-separated entries -> ``(cls, where, arg, entry)``
    tuples (``arg`` is None when no ``:`` part), loud on structural
    malformation.  Class/argument *semantics* stay with each lane's
    parser (``parse_plan`` here, ``serve.faults.parse_serve_plan``)."""
    out: list[tuple] = []
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        cls, sep, rest = entry.partition("@")
        if not sep or not cls or not rest:
            raise ValueError(malformed(entry, lane))
        where, sep2, arg = rest.partition(":")
        if not where or (sep2 and not arg):
            raise ValueError(malformed(entry, lane))
        out.append((cls, where, arg if sep2 else None, entry))
    return out


@dataclasses.dataclass
class FaultPlan:
    nan_loss: frozenset[int]
    hang: dict[int, float]          # step -> seconds
    sigterm: frozenset[int]
    io_error: set[str]              # targets, one-shot (disarmed on fire)

    def __bool__(self) -> bool:
        return bool(self.nan_loss or self.hang or self.sigterm
                    or self.io_error)

    def fire_step_faults(self, step: int, print_fn, obs_writer=None) -> None:
        """Host-side faults that fire *before* step ``step`` dispatches."""
        if step in self.hang:
            seconds = self.hang[step]
            self._announce(print_fn, obs_writer, "hang", step,
                           seconds=seconds)
            time.sleep(seconds)
        if step in self.sigterm:
            self._announce(print_fn, obs_writer, "sigterm", step)
            os.kill(os.getpid(), signal.SIGTERM)

    def poison_batch(self, step: int, batch, print_fn, obs_writer=None):
        """nan_loss: multiply every float leaf of step ``step``'s batch
        by NaN (integer leaves — labels, token ids — pass through)."""
        if step not in self.nan_loss:
            return batch
        import jax
        import jax.numpy as jnp

        leaves = jax.tree.leaves(batch)
        if not any(jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                   for x in leaves):
            raise ValueError(
                f"inject_fault=nan_loss@{step}: the batch has no float "
                "leaves to poison (token/id inputs are integers); use an "
                "image or speech model")
        self._announce(print_fn, obs_writer, "nan_loss", step)
        return jax.tree.map(
            lambda x: x * jnp.nan
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
            batch)

    def maybe_io_error(self, target: str) -> None:
        """One-shot OSError for ``io_error@<target>`` (disarms on fire);
        called from inside the retried I/O path."""
        if target in self.io_error:
            self.io_error.discard(target)
            raise OSError(f"injected io_error@{target}")

    @staticmethod
    def _announce(print_fn, obs_writer, fault: str, step: int,
                  **fields) -> None:
        detail = "".join(f" {k}={v}" for k, v in fields.items())
        print_fn(f"inject: {fault} at timed step {step}{detail}")
        if obs_writer is not None:
            obs_writer.event("injected_fault", fault=fault, step=step,
                             **fields)


def parse_plan(spec: str | None) -> FaultPlan | None:
    """Parse the --inject_fault grammar; None/empty spec -> None."""
    if not spec:
        return None
    nan_loss: set[int] = set()
    hang: dict[int, float] = {}
    sigterm: set[int] = set()
    io_error: set[str] = set()
    for cls, where, arg, entry in split_entries(spec, lane="train"):
        try:
            if cls == "nan_loss":
                if arg is not None:
                    raise ValueError
                nan_loss.add(_step(where))
            elif cls == "hang":
                if arg is None:
                    raise ValueError
                hang[_step(where)] = _seconds(arg)
            elif cls == "sigterm":
                if arg is not None:
                    raise ValueError
                sigterm.add(_step(where))
            elif cls == "io_error":
                if where != "ckpt" or arg is not None:
                    raise ValueError
                io_error.add(where)
            else:
                raise ValueError
        except ValueError:
            raise ValueError(malformed(entry, "train")) from None
    return FaultPlan(nan_loss=frozenset(nan_loss), hang=hang,
                     sigterm=frozenset(sigterm), io_error=io_error)


def _step(s: str) -> int:
    step = int(s)
    if step < 1:
        raise ValueError
    return step


def _seconds(s: str) -> float:
    seconds = float(s)
    if seconds <= 0:
        raise ValueError
    return seconds
