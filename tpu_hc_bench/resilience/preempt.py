"""Preemption handling: SIGTERM/SIGINT → graceful emergency checkpoint.

TPU fleets preempt VMs with a SIGTERM and a short grace window
(the Pathways-style elastic-training pattern in PAPERS.md).  The
handler here only *sets a flag*; the driver's step loop polls it at
step boundaries — the one place the training state is consistent — and
then writes one emergency checkpoint, flushes the metrics stream, and
raises :class:`PreemptedError`, which ``launcher.main`` maps to the
distinct ``EXIT_PREEMPTED`` code.  ``kill -TERM <pid>`` → relaunch with
``--resume=auto`` → continue-from-step just works.

Multi-host: every process gets its own signal (or none — preemption
notices are per-VM), and a checkpoint written by half a mesh is
garbage, so the decision to stop is made **collectively** through
``utils.sync.all_processes_any`` — the shared cross-host agreement
primitive — at sync-window boundaries (the same step on every process,
as a collective requires).

A second signal while the first is still being honored restores the
original disposition, so an operator's double Ctrl-C still kills a run
stuck in its own emergency save.
"""

from __future__ import annotations

import signal
import threading
from typing import Callable


class PreemptedError(RuntimeError):
    """The run stopped at a step boundary to honor a preemption signal.

    ``launcher.main`` maps this to ``resilience.EXIT_PREEMPTED`` (75).

    ``topology`` is the run's checkpoint-topology record
    (``topology.topology_record``) — the emergency checkpoint carries
    the same record as its sidecar, so the message can tell the
    relauncher which world wrote it and that ``--resume=elastic``
    continues it on a DIFFERENT world size (the preempted fleet may
    not come back at full strength).
    """

    def __init__(self, step: int, checkpoint_saved: bool,
                 signum: int | None = None,
                 topology: dict | None = None):
        self.step = step
        self.checkpoint_saved = checkpoint_saved
        self.signum = signum
        self.topology = topology
        if checkpoint_saved:
            world = (topology or {}).get("world")
            saved_as = f" (world {world})" if world else ""
            ckpt = (f"emergency checkpoint saved{saved_as}; relaunch "
                    f"with --resume=auto to continue — or "
                    f"--resume=elastic to continue on a different "
                    f"world size")
        else:
            ckpt = "no --train_dir, nothing saved"
        super().__init__(
            f"preempted after timed step {step} "
            f"(signal {signum}): {ckpt}")


class PreemptionHandler:
    """Installable SIGTERM/SIGINT flag; poll with ``requested``/``agreed``.

    ``install`` is a no-op outside the main thread (CPython only
    delivers signals there) and restores the previous handlers on
    ``uninstall`` — safe to wrap around a library call under pytest.
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, print_fn: Callable[[str], None] = print):
        self._event = threading.Event()
        self._print = print_fn
        self._saved: dict[int, object] = {}
        self.signum: int | None = None

    @property
    def active(self) -> bool:
        return bool(self._saved)

    def install(self) -> "PreemptionHandler":
        if threading.current_thread() is not threading.main_thread():
            return self        # signals never arrive here; stay inert
        for sig in self.SIGNALS:
            self._saved[sig] = signal.signal(sig, self._on_signal)
        return self

    def uninstall(self) -> None:
        for sig, old in self._saved.items():
            try:
                signal.signal(sig, old)
            except (ValueError, TypeError):  # not main thread / odd saved
                pass
        self._saved.clear()

    def _on_signal(self, signum, frame) -> None:
        if self._event.is_set():
            # second signal: the graceful path is already running (or
            # stuck) — restore the original disposition and RE-DELIVER,
            # so this very signal already gets default handling (an
            # operator's second Ctrl-C must not be swallowed)
            self.uninstall()
            signal.raise_signal(signum)
            return
        self.signum = signum
        self._event.set()
        self._print(
            f"signal {signum} received: will checkpoint and exit at the "
            f"next step boundary (send again to force default handling)")

    def requested(self) -> bool:
        """This process saw a signal (cheap local check, poll freely)."""
        return self._event.is_set()

    def agreed(self, world: int) -> bool:
        """Cross-host agreement to stop: True iff ANY process requested.

        With ``world > 1`` this is a collective — every process must
        call it at the same step boundary.
        """
        if world <= 1:
            return self.requested()
        from tpu_hc_bench.utils.sync import all_processes_any

        return all_processes_any(self.requested())
