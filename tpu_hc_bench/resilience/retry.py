"""Bounded retry-with-backoff for run-critical I/O.

Checkpoint saves and metrics writes hit real filesystems (NFS/GCS fuse
mounts on TPU VMs) whose transient errors should not kill a multi-hour
run.  ``retry_io`` retries ``OSError`` a bounded number of times with
exponential backoff, loudly: every retry is printed and (when a writer
is supplied) emitted as an ``io_retry`` record into the metrics stream.
Anything that still fails after the budget re-raises — bounded means the
run terminates instead of retrying a dead filesystem forever.
"""

from __future__ import annotations

import time
from typing import Any, Callable

DEFAULT_ATTEMPTS = 3
DEFAULT_BASE_DELAY_S = 0.1


def retry_io(
    fn: Callable[[], Any],
    what: str,
    attempts: int = DEFAULT_ATTEMPTS,
    base_delay_s: float = DEFAULT_BASE_DELAY_S,
    print_fn: Callable[[str], None] | None = None,
    obs_writer: Any = None,
) -> Any:
    """Run ``fn()``, retrying ``OSError`` with exponential backoff.

    Returns ``fn()``'s value; re-raises the last error once ``attempts``
    are exhausted.  Non-OSError exceptions propagate immediately — a
    shape mismatch or keyboard interrupt is not a transient I/O fault.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1: {attempts}")
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except OSError as e:
            if attempt == attempts:
                raise
            delay = base_delay_s * (2 ** (attempt - 1))
            if print_fn is not None:
                print_fn(
                    f"WARNING: {what} failed (attempt {attempt}/{attempts}: "
                    f"{e}); retrying in {delay:.2f}s")
            if obs_writer is not None:
                try:
                    obs_writer.event("io_retry", what=what, attempt=attempt,
                                     error=str(e), delay_s=delay)
                except Exception:
                    pass  # the metrics stream may be the failing device
            time.sleep(delay)
