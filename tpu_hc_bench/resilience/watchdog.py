"""Hung-step watchdog: abort with diagnostics instead of hanging forever.

When a collective deadlocks (one host died mid-allreduce, a DCN link
flapped), every surviving process blocks inside XLA with no exception
to catch — the step loop just stops completing steps.  The only
trustworthy signal is *absence of progress*, and the driver already has
the perfect progress oracle: the per-step completion markers of the
arrival-fetcher timeline.  The watchdog is a monitor thread over that
timestamp; if no step completes within ``--step_timeout_s`` it dumps
every Python thread's stack (``faulthandler`` — works even while the
main thread is stuck in C++) plus the last metrics record to stderr,
emits a ``watchdog_dump`` record, and exits the process with the
distinct ``EXIT_WATCHDOG`` code so the scheduler reaps the job instead
of billing a wedged cluster forever.

``--step_timeout_s=auto`` calibrates from the measured warmup: any
healthy step — including a recompile — finishes well inside
``AUTO_TIMEOUT_MULT ×`` the mean warmup step time (which includes the
full compile), floored at ``AUTO_TIMEOUT_MIN_S``.
"""

from __future__ import annotations

import faulthandler
import os
import sys
import threading
import time
from typing import Any, Callable

AUTO_TIMEOUT_MULT = 10.0
AUTO_TIMEOUT_MIN_S = 60.0


def resolve_timeout(spec: str | float | None,
                    warmup_step_s: float | None = None) -> float | None:
    """``--step_timeout_s`` → seconds (or None = watchdog off).

    Accepts a positive number, ``"auto"`` (k× the warmup mean step time,
    floored — ``warmup_step_s`` must be provided then), or
    None/""/"0"/"off" to disable.  Loud on anything else.
    """
    if spec is None:
        return None
    if isinstance(spec, str):
        s = spec.strip().lower()
        if s in ("", "0", "off", "none"):
            return None
        if s == "auto":
            if warmup_step_s is None:
                return None     # caller resolves again post-warmup
            return max(AUTO_TIMEOUT_MIN_S,
                       AUTO_TIMEOUT_MULT * warmup_step_s)
        spec = s
    try:
        timeout = float(spec)
    except ValueError:
        raise ValueError(
            f"--step_timeout_s must be a positive number, 'auto', or "
            f"unset/off: {spec!r}") from None
    if timeout <= 0:
        raise ValueError(
            f"--step_timeout_s must be > 0 (use unset/off to disable): "
            f"{spec!r}")
    return timeout


class Watchdog:
    """Monitor thread: no completed step for ``timeout_s`` → dump + abort.

    ``progress_fn`` returns the wall time (``time.perf_counter``) of the
    last completed step, or None before the first one; the arming time
    stands in until then.  ``on_timeout`` (tests) replaces the default
    ``os._exit(EXIT_WATCHDOG)`` so the firing path is unit-testable
    in-process.  ``forensics_fn`` (the driver passes
    ``obs.memory.dump_forensics``) runs on fire, before the metrics
    stream closes: a hang wedged on an allocator stall looks exactly
    like a hang wedged on a collective until the live-buffer breakdown
    says which — best-effort, it can never mask the dump/abort.
    """

    def __init__(self, timeout_s: float,
                 progress_fn: Callable[[], float | None],
                 print_fn: Callable[[str], None] = print,
                 last_record_fn: Callable[[], Any] | None = None,
                 obs_writer: Any = None,
                 on_timeout: Callable[[float], None] | None = None,
                 poll_s: float | None = None,
                 forensics_fn: Callable[[], Any] | None = None):
        self.timeout_s = float(timeout_s)
        self._progress = progress_fn
        self._print = print_fn
        self._last_record = last_record_fn
        self._obs = obs_writer
        self._on_timeout = on_timeout
        self._forensics = forensics_fn
        self._poll_s = poll_s or max(0.05, min(5.0, self.timeout_s / 4))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._armed_t = 0.0
        self._paused = False
        self.fired = False

    def start(self) -> "Watchdog":
        self._armed_t = time.perf_counter()
        self._thread = threading.Thread(
            target=self._run, name="tpu-hc-bench-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self._poll_s)

    def pause(self) -> None:
        """Suspend timeout checks — around legitimate long stalls the
        progress oracle cannot see (a multi-GB checkpoint save to slow
        storage blocks the step loop but is NOT a hang)."""
        self._paused = True

    def resume(self) -> None:
        """Re-arm with a fresh baseline: the paused span must not count
        against the next step's timeout."""
        self._armed_t = time.perf_counter()
        self._paused = False

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            if self._paused:
                continue
            last = self._progress()
            if last is None or last < self._armed_t:
                last = self._armed_t
            age = time.perf_counter() - last
            if age > self.timeout_s:
                self._fire(age)
                return

    def _fire(self, age: float) -> None:
        self.fired = True
        sys.stderr.write(
            f"\nwatchdog: no step completed in {age:.1f}s "
            f"(timeout {self.timeout_s:.1f}s) — dumping all thread "
            f"stacks and aborting (exit {_exit_code()})\n")
        try:
            # C-level dump: works even when the main thread is wedged
            # inside an XLA collective and will never run Python again
            faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
        except Exception:
            pass
        if self._last_record is not None:
            try:
                rec = self._last_record()
                if rec is not None:
                    sys.stderr.write(f"watchdog: last metrics record: "
                                     f"{rec}\n")
            except Exception:
                pass
        if self._forensics is not None:
            # bounded: the forensics walk the live-buffer table on a
            # runtime that may be THE wedged thing — a daemon thread
            # with a capped join keeps the abort guarantee (exit 70)
            # even when the probe itself hangs on the runtime lock
            try:
                t = threading.Thread(target=self._forensics,
                                     name="tpu-hc-bench-forensics",
                                     daemon=True)
                t.start()
                t.join(timeout=10.0)
            except Exception:
                pass
        if self._obs is not None:
            try:
                self._obs.event("watchdog_dump", age_s=age,
                                timeout_s=self.timeout_s)
                # terminate the goodput ledger so the wedged span is
                # accounted (obs.goodput), then flush+fsync — close()
                # is this stream's durability guarantee and the very
                # next thing is os._exit
                self._obs.event("phase", phase="end", t=time.monotonic(),
                                step=None, reason="watchdog")
                self._obs.close()
            except Exception:
                pass
        sys.stderr.flush()
        if self._on_timeout is not None:
            self._on_timeout(age)
            return
        os._exit(_exit_code())


def _exit_code() -> int:
    from tpu_hc_bench.resilience import EXIT_WATCHDOG

    return EXIT_WATCHDOG
