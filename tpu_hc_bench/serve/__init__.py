"""Request-driven serving benchmark — the inference workload lane.

The reference harness (and every round of this repo before 16) is a
*training* workload driver; the north star — "serve heavy traffic from
millions of users" — names the scenario it could not exercise at all:
inference under load.  This package closes that gap with a miniature of
the two techniques the related work canonized:

- **Continuous batching** (Orca): requests are admitted into and
  retired from the running decode batch *per decode step*, instead of
  batches running to completion while arrivals queue
  (``serve.engine``; ``--batching=static`` keeps the classic arm as
  the A/B control).
- **Paged KV cache** (vLLM): decode members allocate KV cache in fixed
  pages from a shared pool, so memory scales with tokens actually held
  rather than worst-case sequence slabs (``serve.decode``).

Everything runs over a small ladder of AOT-compiled ``(batch, seqlen)``
bucket shapes, warmed at startup through the training lane's
``--compile_cache`` and the ``obs.efficiency`` lowering path — after
warmup the engine only ever calls AOT executables, so a mid-traffic
recompile is structurally impossible (an off-ladder shape raises).
SLO reporting (p50/p95/p99 TTFT + end-to-end, queue depth, tokens/s,
goodput-under-load) rides the existing ``obs.metrics`` stream as
``request``/``serve`` records, so ``obs summarize|diff|watch`` render
serving runs with no new artifact format (``serve.slo``).

Entry point: ``python -m tpu_hc_bench serve --model moe_tiny
--arrival_rate 8 --num_requests 64 --metrics_dir /runs/serve``.

This module is import-light on purpose: ``serve.slo`` is pure record
processing (the obs CLI must keep working without a jax backend), and
the engine/decoder only import jax when constructed.
"""
