from tpu_hc_bench.serve.cli import main

raise SystemExit(main())
