"""Seeded synthetic request arrival processes + request synthesis.

"Millions of users" scaled down to an arrival-rate axis: the serving
benchmark is *open-loop* — requests arrive on their own schedule whether
or not the engine keeps up, so queueing delay is measured instead of
hidden (a closed client-loop would throttle arrivals to service rate
and report flattering latencies).  Three shapes:

- ``poisson``: memoryless exponential gaps at ``rate`` — the classic
  open-loop model, and the headline A/B's fixed-rate axis.
- ``bursty``: an on/off duty cycle — ``burst_factor`` x the mean rate
  for the first quarter of each ``period_s``, near-idle otherwise.
  Same mean rate as poisson; the tail (p99) is where it hurts.
- ``diurnal``: a sinusoidal rate over ``period_s`` (the day/night
  traffic curve, compressed) via Lewis-Shedler thinning.

Everything is drawn from ``numpy.random.default_rng`` keyed on the
seed, so a (process, rate, n, seed) tuple names one exact trace —
reproducible across machines and independent of engine pacing (the
``data/tokens.py`` counter-rng discipline).
"""

from __future__ import annotations

import dataclasses

import numpy as np

PROCESSES = ("poisson", "bursty", "diurnal")


@dataclasses.dataclass(frozen=True)
class Request:
    """One synthetic inference request.

    ``prompt`` is the int32 token array for decode members and ``None``
    for classify members (non-text zoo members serve single-forward
    requests); ``output_len`` is the generation budget — a decode
    request retires after ``output_len`` tokens.
    """

    rid: int
    arrival_s: float
    prompt: np.ndarray | None
    output_len: int

    @property
    def prompt_len(self) -> int:
        return 0 if self.prompt is None else int(len(self.prompt))


def arrival_times(process: str, rate: float, n: int, seed: int = 0,
                  burst_factor: float = 4.0,
                  period_s: float = 8.0) -> np.ndarray:
    """``n`` sorted arrival offsets (seconds from t=0) at mean ``rate``.

    All three processes share the mean: an A/B over arrival *shape*
    holds offered load fixed.
    """
    if process not in PROCESSES:
        raise ValueError(
            f"arrival process must be one of {PROCESSES}: {process!r}")
    if rate <= 0:
        raise ValueError(f"arrival rate must be > 0: {rate}")
    if n < 1:
        raise ValueError(f"need >= 1 arrival: {n}")
    rng = np.random.default_rng((seed, 3))
    if process == "poisson":
        gaps = rng.exponential(1.0 / rate, size=n)
        return np.cumsum(gaps)
    # time-varying lambda(t), sampled by Lewis-Shedler thinning against
    # the process's peak rate: candidates at rate_max, kept with
    # probability lambda(t)/rate_max — exact for any bounded lambda
    duty = 0.25
    if process == "bursty":
        peak = rate * burst_factor

        def lam(t):
            # mean over a period = duty*peak + (1-duty)*low == rate
            low = max(0.0, rate * (1.0 - duty * burst_factor)
                      / (1.0 - duty))
            return np.where((t % period_s) < duty * period_s, peak, low)
    else:                                   # diurnal
        peak = 2.0 * rate

        def lam(t):
            return rate * (1.0 + np.sin(2.0 * np.pi * t / period_s))

    out: list[float] = []
    t = 0.0
    while len(out) < n:
        t += float(rng.exponential(1.0 / peak))
        if float(rng.random()) * peak <= float(lam(np.float64(t))):
            out.append(t)
    return np.asarray(out)


def sample_lengths(n: int, max_len: int, seed: int = 0,
                   mean_frac: float = 0.5) -> np.ndarray:
    """``n`` request lengths in ``[1, max_len]``: lognormal body (the
    long-tail shape of real prompt/output distributions) clipped at the
    ceiling, keyed off the seed."""
    if max_len < 1:
        raise ValueError(f"max_len must be >= 1: {max_len}")
    rng = np.random.default_rng((seed, 5))
    body = rng.lognormal(mean=np.log(max(1.0, mean_frac * max_len)),
                         sigma=0.6, size=n)
    return np.clip(np.round(body), 1, max_len).astype(np.int64)


def build_requests(cfg, vocab_size: int | None,
                   seed: int | None = None) -> list[Request]:
    """The run's full request trace from a resolved serve config.

    ``vocab_size`` None = classify member (no prompts, one forward per
    request).  Deterministic per (cfg arrival knobs, seed): the engine,
    the A/B control arm, and a re-run all see the identical trace.
    """
    seed = cfg.seed if seed is None else seed
    times = arrival_times(cfg.arrival, cfg.arrival_rate,
                          cfg.num_requests, seed=seed)
    out_lens = sample_lengths(cfg.num_requests, cfg.max_output_len,
                              seed=seed + 1)
    if vocab_size is None:
        return [Request(rid=i, arrival_s=float(times[i]), prompt=None,
                        output_len=1)
                for i in range(cfg.num_requests)]
    from tpu_hc_bench.data.tokens import PromptSampler

    prompt_lens = sample_lengths(cfg.num_requests, cfg.max_prompt_len,
                                 seed=seed + 2)
    sampler = PromptSampler(vocab_size=vocab_size, data_dir=cfg.data_dir,
                            seed=seed)
    return [
        Request(rid=i, arrival_s=float(times[i]),
                prompt=sampler.sample(i, int(prompt_lens[i])),
                output_len=int(out_lens[i]))
        for i in range(cfg.num_requests)
    ]
