"""``python -m tpu_hc_bench serve`` — the serving-lane entry point.

Same flag grammar as the training launcher (everything parses through
``flags.build_parser``; resolve() runs the serving validity matrix and
rejects training-only knobs loudly), same observability contract
(``--metrics_dir`` leaves manifest.json + metrics.jsonl and the banner
prints the summarize command), same exit codes where they apply:

- ``0``  clean (every request completed, shed, or quarantined)
- ``1``  run completed but zero requests finished
- ``70`` scheduler-iteration watchdog fired (``--serve_step_timeout_s``)
- ``75`` SIGTERM/Ctrl-C honored: the engine drained, journaled every
  unfinished request, and ``--serve_resume=<journal>`` replays them
  exactly once

On every exit path — including Ctrl-C — the metrics stream and the
FleetWriter are flushed and closed, so the tail of an interrupted run
is still on disk for ``obs summarize``.

Example::

    JAX_PLATFORMS=cpu python -m tpu_hc_bench serve --model moe_tiny \
        --arrival_rate 8 --num_requests 64 --max_prompt_len 32 \
        --max_output_len 16 --metrics_dir /tmp/serve_run
"""

from __future__ import annotations

import os
import sys
from typing import Callable

from tpu_hc_bench import flags as flags_mod


def build_engine_and_requests(cfg, print_fn):
    """The one engine/trace handshake every serve entry point shares
    (CLI, ``BENCH_WORKLOAD=serve``, scripts/bench_serve.py): construct
    the warmed engine, then the arrival trace — classify members carry
    no vocabulary, so the sampler runs promptless for them."""
    from tpu_hc_bench.serve import arrivals
    from tpu_hc_bench.serve.engine import ServeEngine

    engine = ServeEngine(cfg, print_fn=print_fn)
    vocab = engine.spec.vocab_size if engine.decode_mode else None
    return engine, arrivals.build_requests(cfg, vocab)


def serve_writer(cfg, metrics_dir):
    """A MetricsWriter stamped with the serve-lane manifest, or a
    disabled writer when ``metrics_dir`` is falsy."""
    from tpu_hc_bench.obs import metrics as obs_metrics

    return obs_metrics.MetricsWriter(
        metrics_dir,
        obs_metrics.run_manifest(cfg=cfg, extra={"workload": "serve"})
        if metrics_dir else None)


def run_serve(engine, requests, writer, *, batching=None, clock=None):
    """One closed loop with the writer(s) closed on every exit path.

    A metrics-enabled run also gets a FleetWriter beside the metrics
    stream (round 22): the engine heartbeats at serve-record cadence
    carrying ``kv_peak_pages``, so ``obs watch``'s fleet view shows
    per-host KV pressure.  process_index is pinned to 0 — the serve
    lane is single-process today and the FleetWriter default would
    touch ``jax.process_index()`` (a device round-trip) from the hot
    path's setup."""
    fleet = None
    out_dir = getattr(writer, "out_dir", None)
    if out_dir:
        from tpu_hc_bench.obs import fleet as fleet_mod

        fleet = fleet_mod.FleetWriter(out_dir, process_index=0)
    try:
        return engine.run(requests, batching=batching, writer=writer,
                          clock=clock, fleet=fleet)
    finally:
        writer.close()
        if fleet is not None:
            fleet.close()


def main(argv: list[str] | None = None,
         print_fn: Callable[[str], None] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    print_fn = print_fn or (lambda m: print(m, flush=True))
    cfg = flags_mod.parse_flags(argv, workload="serve")

    if os.environ.get("JAX_PLATFORMS"):
        # same re-assert as the training launcher: the env var can lose
        # to a tunneled-device plugin's registration priority
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if cfg.virtual_devices:
        import jax

        jax.config.update("jax_num_cpu_devices", cfg.virtual_devices)

    from tpu_hc_bench.obs import metrics as obs_metrics
    from tpu_hc_bench.serve import slo as slo_mod

    print_fn(f"command: python -m tpu_hc_bench serve {' '.join(argv)}")
    for line in cfg.summary_lines():
        print_fn(line)

    engine, requests = build_engine_and_requests(cfg, print_fn)
    if cfg.serve_resume:
        # drain-journal replay: serve every unfinished request of the
        # SIGTERM'd run exactly once (the journal is the trace)
        from tpu_hc_bench.serve import faults as faults_mod

        payload = faults_mod.read_journal(cfg.serve_resume)
        requests = faults_mod.journal_requests(payload)
        print_fn(f"resume: {len(requests)} unfinished request(s) from "
                 f"{cfg.serve_resume} (reason={payload.get('reason')})")
    writer = serve_writer(cfg, cfg.metrics_dir)
    if writer.enabled:
        print_fn(f"metrics: {cfg.metrics_dir}/{obs_metrics.METRICS_NAME} "
                 f"(+ {obs_metrics.MANIFEST_NAME}); live view: "
                 f"python -m tpu_hc_bench.obs watch {cfg.metrics_dir}")
    try:
        summary = run_serve(engine, requests, writer)
    except KeyboardInterrupt:
        # the engine's own handler converts SIGINT into a drain while
        # run() is live; this catches a Ctrl-C outside that window —
        # run_serve's finally already flushed and closed the streams
        print_fn("interrupted — metrics stream closed")
        return 130
    for line in slo_mod.slo_lines(summary):
        print_fn(line)
    if cfg.metrics_dir:
        print_fn("summarize: python -m tpu_hc_bench.obs summarize "
                 + cfg.metrics_dir)
    if summary.get("drained"):
        from tpu_hc_bench import resilience

        return resilience.EXIT_PREEMPTED
    return 0 if summary["completed"] > 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
