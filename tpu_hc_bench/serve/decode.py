"""Paged-KV prefill/decode programs for the decoder zoo members.

The training models are Flax modules whose ``__call__`` is a full
prefill-shaped forward; serving needs *incremental* decode — one token
per request per step, attending over everything generated so far.
Rather than fork the model definitions, this module re-walks each
family's OWN param tree functionally (the ``pp_embed``/``pp_head``
discipline ``parallel.pipeline`` established): every matmul/norm is the
family's own Flax sub-module ``.apply``'d onto its param subtree, and
only the attention inner product — the part that must read a KV cache
— is reimplemented, with the same f32-softmax/1-over-sqrt(d)
convention as ``parallel.sequence.dense_attention``.  Numerical parity
with ``model.apply`` over the full context is pinned by
``tests/test_serve.py``.

**Paged KV cache** (vLLM): one pool of fixed-size pages per run,
``k_pages``/``v_pages`` shaped ``[layers, pages, page_size, kv_heads,
head_dim]``.  A request holds a page *table* (int32 page indices); the
decode step gathers its keys by table lookup and scatters the new
token's K/V into ``table[pos // page]``.  Page 0 is the reserved
*trash* page: padded/inactive rows write there (and are masked on
read), so one compiled program serves any admission pattern.

Two compiled shapes per family, both AOT-lowered at engine warmup
(``obs.efficiency.aot_compile``):

- ``prefill``: batch 1 over a padded prompt-length bucket — computes
  the whole prompt's K/V in one pass, writes the pages, and returns
  the first generated token (the TTFT token).
- ``decode``: one token for a batch-bucket of in-flight requests at
  *per-row* cache depths (the continuous-batching shape).

Supported families: ``GPTLM`` (gpt2*, moe*: learned positions, dense
or MoE FFN) and ``LlamaLM`` (llama*: RoPE, GQA, SwiGLU).  Everything
else that claims ``causal_lm`` fails loudly at engine construction.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _softmax_attend(q, keys, values, mask):
    """Single-query attention over gathered cache rows.

    ``q`` [b, 1, heads, d]; ``keys``/``values`` [b, S, heads, d];
    ``mask`` [b, S] bool (True = attend).  Same convention as
    ``parallel.sequence.dense_attention``: f32 scores, 1/sqrt(d) scale,
    probabilities cast back to the value dtype.
    """
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, keys,
                   preferred_element_type=jnp.float32) * (1.0 / d ** 0.5)
    s = jnp.where(mask[:, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(values.dtype), values)


@dataclasses.dataclass
class _Family:
    """One decoder family's functional pieces over its own param tree."""

    model: Any
    num_layers: int
    heads: int
    kv_heads: int
    head_dim: int
    embed_decode: Callable      # (params, tokens [b], positions [b]) -> [b,1,H]
    layer_params: Callable      # (params, l) -> layer subtree
    attn_norm: Callable         # (p_l, x) -> normed
    qkv: Callable               # (p_l, x, positions [b,s]) -> q, k, v
                                # ([b,s,heads,d], [b,s,kvh,d] x2; RoPE
                                # families rotate inside)
    attn_out: Callable          # (p_l, ctx [b,s,heads,d]) -> [b,s,H]
    ffn: Callable               # (p_l, x normed) -> [b,s,H]
    ffn_norm: Callable          # (p_l, x) -> normed

    def embed_prefill(self, params, tokens):
        # positions arange(s) — exactly the training forward's layout
        x, _ = self.model.pp_embed(params, tokens, None)
        return x

    def head(self, params, x):
        return self.model.pp_head(params, x)


def build_family(model) -> _Family:
    """The family adapter for a constructed decoder module."""
    from tpu_hc_bench.models.gpt import GPTLM
    from tpu_hc_bench.models.llama import LlamaLM, RMSNorm, apply_rope

    if isinstance(model, GPTLM):
        if model.scan_layers:
            raise ValueError(
                "serving decodes the unrolled layer_i param layout; "
                "--scan_layers checkpoints are not servable")
        d = model.hidden // model.heads
        dt = model.dtype

        def embed_decode(params, tokens, positions):
            wte = params["wte"]["embedding"].astype(dt)
            wpe = params["wpe"]["embedding"].astype(dt)
            return (wte[tokens] + wpe[positions])[:, None]

        def qkv(p_l, x, positions):
            del positions               # learned positions live in embed
            qkv_all = nn.DenseGeneral((3, model.heads, d), dtype=dt).apply(
                {"params": p_l["MultiHeadAttention_0"]["qkv"]}, x)
            return qkv_all[:, :, 0], qkv_all[:, :, 1], qkv_all[:, :, 2]

        def ffn(p_l, h):
            if model.num_experts:
                from tpu_hc_bench.models.moe import MoEFFN

                # serving ALWAYS dispatches ragged (grouped matmuls):
                # the einsum path drops capacity-overflow tokens, which
                # is tolerable batch-shaping noise in training but a
                # correctness hazard when serving (a request's token
                # silently losing its FFN), and it would also make
                # incremental decode diverge from the full forward.
                # Zero drops == ideal top-k == prefill/decode agree
                # exactly; param tree is impl-independent.
                return MoEFFN(
                    model.hidden, model.ffn, model.num_experts,
                    top_k=model.top_k, dtype=dt, impl="ragged",
                    ragged_f_chunk=model.moe_f_chunk,
                ).apply({"params": p_l["moe"]}, h)
            h = nn.Dense(model.ffn, dtype=dt).apply(
                {"params": p_l["fc"]}, h)
            h = nn.gelu(h)
            return nn.Dense(model.hidden, dtype=dt).apply(
                {"params": p_l["proj"]}, h)

        return _Family(
            model=model, num_layers=model.num_layers, heads=model.heads,
            kv_heads=model.heads, head_dim=d,
            embed_decode=embed_decode,
            layer_params=lambda params, l: params[f"layer_{l}"],
            attn_norm=lambda p_l, x: nn.LayerNorm(dtype=dt).apply(
                {"params": p_l["ln1"]}, x),
            qkv=qkv,
            attn_out=lambda p_l, ctx: nn.DenseGeneral(
                model.hidden, axis=(-2, -1), dtype=dt).apply(
                {"params": p_l["MultiHeadAttention_0"]["out"]}, ctx),
            ffn=ffn,
            ffn_norm=lambda p_l, x: nn.LayerNorm(dtype=dt).apply(
                {"params": p_l["ln2"]}, x),
        )

    if isinstance(model, LlamaLM):
        if model.scan_layers:
            raise ValueError(
                "serving decodes the unrolled layer_i param layout; "
                "--scan_layers checkpoints are not servable")
        d = model.hidden // model.heads
        dt = model.dtype

        def embed_decode(params, tokens, positions):
            del positions               # RoPE rotates inside attention
            emb = params["tok_embed"]["embedding"].astype(dt)
            return emb[tokens][:, None]

        def qkv(p_l, x, positions):
            a = p_l["attn"]
            q = nn.DenseGeneral((model.heads, d), use_bias=False,
                                dtype=dt).apply({"params": a["wq"]}, x)
            k = nn.DenseGeneral((model.num_kv_heads, d), use_bias=False,
                                dtype=dt).apply({"params": a["wk"]}, x)
            v = nn.DenseGeneral((model.num_kv_heads, d), use_bias=False,
                                dtype=dt).apply({"params": a["wv"]}, x)
            return (apply_rope(q, positions), apply_rope(k, positions), v)

        def ffn(p_l, h):
            gate = nn.Dense(model.ffn, use_bias=False, dtype=dt).apply(
                {"params": p_l["gate"]}, h)
            up = nn.Dense(model.ffn, use_bias=False, dtype=dt).apply(
                {"params": p_l["up"]}, h)
            return nn.Dense(model.hidden, use_bias=False, dtype=dt).apply(
                {"params": p_l["down"]}, nn.silu(gate) * up)

        return _Family(
            model=model, num_layers=model.num_layers, heads=model.heads,
            kv_heads=model.num_kv_heads, head_dim=d,
            embed_decode=embed_decode,
            layer_params=lambda params, l: params[f"layer_{l}"],
            attn_norm=lambda p_l, x: RMSNorm(dtype=dt).apply(
                {"params": p_l["attn_norm"]}, x),
            qkv=qkv,
            attn_out=lambda p_l, ctx: nn.DenseGeneral(
                model.hidden, axis=(-2, -1), use_bias=False,
                dtype=dt).apply({"params": p_l["attn"]["wo"]}, ctx),
            ffn=ffn,
            ffn_norm=lambda p_l, x: RMSNorm(dtype=dt).apply(
                {"params": p_l["mlp_norm"]}, x),
        )

    raise ValueError(
        f"no paged-decode family for {type(model).__name__} (supported: "
        "GPTLM, LlamaLM); non-causal members serve single-forward "
        "requests instead")


def init_kv_pages(family: _Family, num_pages: int, page_size: int,
                  dtype) -> tuple[jax.Array, jax.Array]:
    """The zeroed page pool: ``[L, pages, page_size, kv_heads, d]`` x2."""
    shape = (family.num_layers, num_pages, page_size, family.kv_heads,
             family.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def build_prefill_fn(family: _Family, page_size: int, table_width: int):
    """The (batch-1, padded prompt bucket) prefill program.

    Args at call time: ``(params, k_pages, v_pages, tokens [1, s],
    length [], table [w])``.  Returns ``(next_token [1], logits
    [1, vocab], k_pages, v_pages)`` with the prompt's K/V scattered
    into the table's pages (pad positions routed to the trash page 0).
    """
    from tpu_hc_bench.parallel.sequence import dense_attention

    def prefill(params, k_pages, v_pages, tokens, length, table):
        s = tokens.shape[1]
        positions = jnp.arange(s)[None, :]
        x = family.embed_prefill(params, tokens)
        group = family.heads // family.kv_heads
        new_k, new_v = [], []
        for l in range(family.num_layers):
            p_l = family.layer_params(params, l)
            h = family.attn_norm(p_l, x)
            q, k, v = family.qkv(p_l, h, positions)
            new_k.append(k)
            new_v.append(v)
            if group > 1:
                k = jnp.repeat(k, group, axis=2)
                v = jnp.repeat(v, group, axis=2)
            # causal masking alone is sufficient under right-padding:
            # the only logits read are at `length - 1`, whose keys
            # j <= length - 1 are all valid prompt positions
            ctx = dense_attention(q, k, v, causal=True)
            x = x + family.attn_out(p_l, ctx)
            x = x + family.ffn(p_l, family.ffn_norm(p_l, x))
        x_last = jax.lax.dynamic_slice_in_dim(x, length - 1, 1, axis=1)
        logits = family.head(params, x_last)[:, 0]      # [1, vocab]
        next_token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # scatter the prompt K/V into this request's pages; pads -> trash
        pos = jnp.arange(s)
        page_idx = jnp.where(
            pos < length,
            table[jnp.clip(pos // page_size, 0, table_width - 1)], 0)
        offset = pos % page_size
        kn = jnp.stack([k[0] for k in new_k])       # [L, s, kvh, d]
        vn = jnp.stack([v[0] for v in new_v])
        k_pages = k_pages.at[:, page_idx, offset].set(kn)
        v_pages = v_pages.at[:, page_idx, offset].set(vn)
        return next_token, logits, k_pages, v_pages

    return prefill


def build_decode_fn(family: _Family, page_size: int, table_width: int):
    """The one-token-per-row decode program for a batch bucket.

    Args at call time: ``(params, k_pages, v_pages, tokens [b],
    tables [b, w], lengths [b], active [b])`` where ``lengths`` is each
    row's cache depth (== the fed token's position).  Inactive rows
    compute on the trash page and write back to it; retirement and
    admission are pure host-side bookkeeping, never a new shape.
    Returns ``(next_tokens [b], logits [b, vocab], k_pages, v_pages)``.
    """

    def decode(params, k_pages, v_pages, tokens, tables, lengths, active):
        b = tokens.shape[0]
        span = table_width * page_size
        x = family.embed_decode(params, tokens, lengths)
        group = family.heads // family.kv_heads
        kv_valid = jnp.arange(span)[None, :] < lengths[:, None]
        mask = jnp.concatenate(
            [kv_valid, jnp.ones((b, 1), bool)], axis=1)
        new_k, new_v = [], []
        for l in range(family.num_layers):
            p_l = family.layer_params(params, l)
            h = family.attn_norm(p_l, x)
            q, k, v = family.qkv(p_l, h, lengths[:, None])
            new_k.append(k[:, 0])
            new_v.append(v[:, 0])
            kc = k_pages[l][tables].reshape(
                b, span, family.kv_heads, family.head_dim)
            vc = v_pages[l][tables].reshape(
                b, span, family.kv_heads, family.head_dim)
            keys = jnp.concatenate([kc, k], axis=1)
            values = jnp.concatenate([vc, v], axis=1)
            if group > 1:
                keys = jnp.repeat(keys, group, axis=2)
                values = jnp.repeat(values, group, axis=2)
            ctx = _softmax_attend(q, keys, values, mask)
            x = x + family.attn_out(p_l, ctx)
            x = x + family.ffn(p_l, family.ffn_norm(p_l, x))
        logits = family.head(params, x)[:, 0]
        next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        rows = jnp.arange(b)
        page_idx = jnp.where(
            active,
            tables[rows, jnp.clip(lengths // page_size, 0,
                                  table_width - 1)], 0)
        offset = lengths % page_size
        kn = jnp.stack(new_k, axis=0)               # [L, b, kvh, d]
        vn = jnp.stack(new_v, axis=0)
        k_pages = k_pages.at[:, page_idx, offset].set(kn)
        v_pages = v_pages.at[:, page_idx, offset].set(vn)
        return next_tokens, logits, k_pages, v_pages

    return decode
