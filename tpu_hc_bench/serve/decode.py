"""Paged-KV prefill/decode programs for the decoder zoo members.

The training models are Flax modules whose ``__call__`` is a full
prefill-shaped forward; serving needs *incremental* decode — one token
per request per step, attending over everything generated so far.
Rather than fork the model definitions, this module re-walks each
family's OWN param tree functionally (the ``pp_embed``/``pp_head``
discipline ``parallel.pipeline`` established): every matmul/norm is the
family's own Flax sub-module ``.apply``'d onto its param subtree, and
only the attention inner product — the part that must read a KV cache
— is reimplemented, with the same f32-softmax/1-over-sqrt(d)
convention as ``parallel.sequence.dense_attention``.  Numerical parity
with ``model.apply`` over the full context is pinned by
``tests/test_serve.py`` and ``tests/test_zz_decode_kernels.py``.

**Paged KV cache** (vLLM): one pool of fixed-size pages per run,
``k_pages``/``v_pages`` shaped ``[layers, pages, page_size, kv_heads,
head_dim]``.  A request holds a page *table* (int32 page indices); the
decode step reads its keys through the table and scatters the new
token's K/V into ``table[pos // page]``.  Page 0 is the reserved
*trash* page: padded/inactive rows write there (and are masked on
read), so one compiled program serves any admission pattern.

**Decode attention arms** (round 18, ``--decode_attention``):

- ``gather`` — the reference: gather the tables' pages into a dense
  worst-case ``[b, S, heads, d]`` temporary and run ``_softmax_attend``.
  Simple, and the parity anchor for everything else.
- ``paged`` — ``ops.paged_decode_attention``: a Pallas flash-decode
  kernel that reads K/V *directly through the page tables* (no dense
  gather ever materializes; online softmax over pages; block size =
  ``--decode_block_pages``).  The fresh token's K/V — not yet in the
  pool — merge into the online softmax through the kernel's returned
  logsumexp, so the scatter stays the one vectorized write at the end
  of the step.  The paged arm also fuses each residual-add with the
  following norm (``ops.fused_residual_norm``).

**Quantization arms** (``--quant``):

- ``int8_w`` — ``quantize_weights``: the decode projections (QKV,
  attention out, dense FFN / SwiGLU) held as per-output-channel int8
  with f32 scales, dequantized *at the matmul* (the scale multiplies
  the matmul output — never a dense f32 weight copy in the layer
  loop; the ``dequantize-in-hot-loop`` lint enforces the form).  MoE
  expert tensors stay f32 (the ragged dispatch owns them).
- ``int8_kv`` — the page pool is int8 with one f32 scale per (layer,
  page), written at prefill (per-chunk amax) and on every append (the
  touched page is dequantized, extended, and requantized — one
  vectorized op over all layers, outside the layer loop), and
  consumed *inside* the paged kernel.  Requires the paged arm.

Two compiled shapes per family, both AOT-lowered at engine warmup
(``obs.efficiency.aot_compile``):

- ``prefill``: batch 1 over a padded prompt-length bucket — computes
  the whole prompt's K/V in one pass, writes the pages, and returns
  the first generated token (the TTFT token).
- ``decode``: one token for a batch-bucket of in-flight requests at
  *per-row* cache depths (the continuous-batching shape).

Supported families: ``GPTLM`` (gpt2*, moe*: learned positions, dense
or MoE FFN) and ``LlamaLM`` (llama*: RoPE, GQA, SwiGLU).  Everything
else that claims ``causal_lm`` fails loudly at engine construction.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpu_hc_bench.ops._pallas import pad_up as _pad_up
from tpu_hc_bench.ops.fused_residual_ln import fused_residual_norm
from tpu_hc_bench.ops.paged_attention import paged_decode_attention

_NEG_INF = -1e30
_QUANT_EPS = 1e-8

QUANT_ARMS = ("off", "int8_w", "int8_kv")
DECODE_ATTENTION_ARMS = ("gather", "paged")


def _softmax_attend(q, keys, values, mask):
    """Single-query attention over gathered cache rows.

    ``q`` [b, 1, heads, d]; ``keys``/``values`` [b, S, heads, d];
    ``mask`` [b, S] bool (True = attend).  Same convention as
    ``parallel.sequence.dense_attention``: f32 scores, 1/sqrt(d) scale,
    probabilities cast back to the value dtype.
    """
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, keys,
                   preferred_element_type=jnp.float32) * (1.0 / d ** 0.5)
    s = jnp.where(mask[:, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(values.dtype), values)


def _qeinsum(spec, x, leaf, dtype):
    """Scale-fused quantized matmul: the int8 kernel feeds the einsum
    directly and the per-output-channel scale multiplies the matmul
    OUTPUT — the form that never materializes a dense f32 weight copy
    (and the form the ``dequantize-in-hot-loop`` lint accepts)."""
    return (jnp.einsum(spec, x, leaf["q"].astype(dtype))
            * leaf["scale"].astype(dtype))


def _quantize_leaf(w, contract_axes) -> dict:
    """Per-output-channel symmetric int8: amax over the contraction
    axes, scale = amax/127 (floored so all-zero channels stay finite)."""
    wf = jnp.asarray(w).astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=contract_axes, keepdims=True)
    scale = jnp.maximum(amax / 127.0, _QUANT_EPS)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "scale": jnp.squeeze(scale, axis=contract_axes)}


def _with_path(tree: dict, path: tuple, value) -> dict:
    """A copy of ``tree`` with the node at ``path`` replaced (shallow
    copies along the path only; untouched subtrees are shared)."""
    d = dict(tree)
    if len(path) == 1:
        d[path[0]] = value
    else:
        d[path[0]] = _with_path(tree[path[0]], path[1:], value)
    return d


@dataclasses.dataclass
class _Family:
    """One decoder family's functional pieces over its own param tree."""

    model: Any
    num_layers: int
    heads: int
    kv_heads: int
    head_dim: int
    norm_kind: str              # "layernorm" (GPT) | "rmsnorm" (Llama)
    embed_decode: Callable      # (params, tokens [b], positions [b]) -> [b,1,H]
    layer_params: Callable      # (params, l) -> layer subtree
    attn_norm: Callable         # (p_l, x) -> normed
    attn_norm_params: Callable  # (p_l) -> (gamma, beta|None)
    qkv: Callable               # (p_l, x, positions [b,s]) -> q, k, v
                                # ([b,s,heads,d], [b,s,kvh,d] x2; RoPE
                                # families rotate inside)
    attn_out: Callable          # (p_l, ctx [b,s,heads,d]) -> [b,s,H]
    ffn: Callable               # (p_l, x normed) -> [b,s,H]
    ffn_norm: Callable          # (p_l, x) -> normed
    ffn_norm_params: Callable   # (p_l) -> (gamma, beta|None)
    quant_paths: Callable       # (l) -> [(param path, contract axes)]
                                # quantize_weights' int8_w walk

    def embed_prefill(self, params, tokens):
        # positions arange(s) — exactly the training forward's layout
        x, _ = self.model.pp_embed(params, tokens, None)
        return x

    def head(self, params, x):
        return self.model.pp_head(params, x)


def build_family(model, quant: str = "off") -> _Family:
    """The family adapter for a constructed decoder module.

    ``quant="int8_w"`` swaps the projection callables for scale-fused
    int8 einsums over the tree ``quantize_weights`` produces; every
    other leaf (embeddings, norms, biases, head, MoE experts) is read
    exactly as in the f32 adapter.
    """
    from tpu_hc_bench.models.gpt import GPTLM
    from tpu_hc_bench.models.llama import LlamaLM, RMSNorm, apply_rope

    if quant not in QUANT_ARMS:
        raise ValueError(f"quant must be one of {QUANT_ARMS}: {quant!r}")
    int8_w = quant == "int8_w"

    if isinstance(model, GPTLM):
        if model.scan_layers:
            raise ValueError(
                "serving decodes the unrolled layer_i param layout; "
                "--scan_layers checkpoints are not servable")
        d = model.hidden // model.heads
        dt = model.dtype

        def embed_decode(params, tokens, positions):
            wte = params["wte"]["embedding"].astype(dt)
            wpe = params["wpe"]["embedding"].astype(dt)
            return (wte[tokens] + wpe[positions])[:, None]

        if int8_w:
            def qkv(p_l, x, positions):
                del positions           # learned positions live in embed
                a = p_l["MultiHeadAttention_0"]["qkv"]
                out = (_qeinsum("bsh,hknd->bsknd", x, a["kernel"], dt)
                       + a["bias"].astype(dt))
                return out[:, :, 0], out[:, :, 1], out[:, :, 2]

            def attn_out(p_l, ctx):
                o = p_l["MultiHeadAttention_0"]["out"]
                return (_qeinsum("bsnd,ndh->bsh", ctx, o["kernel"], dt)
                        + o["bias"].astype(dt))
        else:
            def qkv(p_l, x, positions):
                del positions           # learned positions live in embed
                qkv_all = nn.DenseGeneral((3, model.heads, d),
                                          dtype=dt).apply(
                    {"params": p_l["MultiHeadAttention_0"]["qkv"]}, x)
                return qkv_all[:, :, 0], qkv_all[:, :, 1], qkv_all[:, :, 2]

            def attn_out(p_l, ctx):
                return nn.DenseGeneral(
                    model.hidden, axis=(-2, -1), dtype=dt).apply(
                    {"params": p_l["MultiHeadAttention_0"]["out"]}, ctx)

        def ffn(p_l, h):
            if model.num_experts:
                from tpu_hc_bench.models.moe import MoEFFN

                # serving ALWAYS dispatches ragged (grouped matmuls):
                # the einsum path drops capacity-overflow tokens, which
                # is tolerable batch-shaping noise in training but a
                # correctness hazard when serving (a request's token
                # silently losing its FFN), and it would also make
                # incremental decode diverge from the full forward.
                # Zero drops == ideal top-k == prefill/decode agree
                # exactly; param tree is impl-independent.  Expert
                # tensors stay f32 under int8_w (the ragged grouped
                # matmuls own their layout).
                return MoEFFN(
                    model.hidden, model.ffn, model.num_experts,
                    top_k=model.top_k, dtype=dt, impl="ragged",
                    ragged_f_chunk=model.moe_f_chunk,
                ).apply({"params": p_l["moe"]}, h)
            if int8_w:
                h = (_qeinsum("bsh,hf->bsf", h, p_l["fc"]["kernel"], dt)
                     + p_l["fc"]["bias"].astype(dt))
                h = nn.gelu(h)
                return (_qeinsum("bsf,fh->bsh", h, p_l["proj"]["kernel"],
                                 dt)
                        + p_l["proj"]["bias"].astype(dt))
            h = nn.Dense(model.ffn, dtype=dt).apply(
                {"params": p_l["fc"]}, h)
            h = nn.gelu(h)
            return nn.Dense(model.hidden, dtype=dt).apply(
                {"params": p_l["proj"]}, h)

        def quant_paths(l):
            base = (f"layer_{l}", "MultiHeadAttention_0")
            paths = [(base + ("qkv", "kernel"), (0,)),
                     (base + ("out", "kernel"), (0, 1))]
            if not model.num_experts:
                paths += [((f"layer_{l}", "fc", "kernel"), (0,)),
                          ((f"layer_{l}", "proj", "kernel"), (0,))]
            return paths

        return _Family(
            model=model, num_layers=model.num_layers, heads=model.heads,
            kv_heads=model.heads, head_dim=d, norm_kind="layernorm",
            embed_decode=embed_decode,
            layer_params=lambda params, l: params[f"layer_{l}"],
            attn_norm=lambda p_l, x: nn.LayerNorm(dtype=dt).apply(
                {"params": p_l["ln1"]}, x),
            attn_norm_params=lambda p_l: (p_l["ln1"]["scale"],
                                          p_l["ln1"]["bias"]),
            qkv=qkv,
            attn_out=attn_out,
            ffn=ffn,
            ffn_norm=lambda p_l, x: nn.LayerNorm(dtype=dt).apply(
                {"params": p_l["ln2"]}, x),
            ffn_norm_params=lambda p_l: (p_l["ln2"]["scale"],
                                         p_l["ln2"]["bias"]),
            quant_paths=quant_paths,
        )

    if isinstance(model, LlamaLM):
        if model.scan_layers:
            raise ValueError(
                "serving decodes the unrolled layer_i param layout; "
                "--scan_layers checkpoints are not servable")
        d = model.hidden // model.heads
        dt = model.dtype

        def embed_decode(params, tokens, positions):
            del positions               # RoPE rotates inside attention
            emb = params["tok_embed"]["embedding"].astype(dt)
            return emb[tokens][:, None]

        if int8_w:
            def qkv(p_l, x, positions):
                a = p_l["attn"]
                q = _qeinsum("bsh,hnd->bsnd", x, a["wq"]["kernel"], dt)
                k = _qeinsum("bsh,hnd->bsnd", x, a["wk"]["kernel"], dt)
                v = _qeinsum("bsh,hnd->bsnd", x, a["wv"]["kernel"], dt)
                return (apply_rope(q, positions),
                        apply_rope(k, positions), v)

            def attn_out(p_l, ctx):
                return _qeinsum("bsnd,ndh->bsh", ctx,
                                p_l["attn"]["wo"]["kernel"], dt)

            def ffn(p_l, h):
                gate = _qeinsum("bsh,hf->bsf", h, p_l["gate"]["kernel"],
                                dt)
                up = _qeinsum("bsh,hf->bsf", h, p_l["up"]["kernel"], dt)
                return _qeinsum("bsf,fh->bsh", nn.silu(gate) * up,
                                p_l["down"]["kernel"], dt)
        else:
            def qkv(p_l, x, positions):
                a = p_l["attn"]
                q = nn.DenseGeneral((model.heads, d), use_bias=False,
                                    dtype=dt).apply({"params": a["wq"]}, x)
                k = nn.DenseGeneral((model.num_kv_heads, d),
                                    use_bias=False,
                                    dtype=dt).apply({"params": a["wk"]}, x)
                v = nn.DenseGeneral((model.num_kv_heads, d),
                                    use_bias=False,
                                    dtype=dt).apply({"params": a["wv"]}, x)
                return (apply_rope(q, positions),
                        apply_rope(k, positions), v)

            def attn_out(p_l, ctx):
                return nn.DenseGeneral(
                    model.hidden, axis=(-2, -1), use_bias=False,
                    dtype=dt).apply({"params": p_l["attn"]["wo"]}, ctx)

            def ffn(p_l, h):
                gate = nn.Dense(model.ffn, use_bias=False,
                                dtype=dt).apply({"params": p_l["gate"]}, h)
                up = nn.Dense(model.ffn, use_bias=False, dtype=dt).apply(
                    {"params": p_l["up"]}, h)
                return nn.Dense(model.hidden, use_bias=False,
                                dtype=dt).apply(
                    {"params": p_l["down"]}, nn.silu(gate) * up)

        def quant_paths(l):
            return [((f"layer_{l}", "attn", "wq", "kernel"), (0,)),
                    ((f"layer_{l}", "attn", "wk", "kernel"), (0,)),
                    ((f"layer_{l}", "attn", "wv", "kernel"), (0,)),
                    ((f"layer_{l}", "attn", "wo", "kernel"), (0, 1)),
                    ((f"layer_{l}", "gate", "kernel"), (0,)),
                    ((f"layer_{l}", "up", "kernel"), (0,)),
                    ((f"layer_{l}", "down", "kernel"), (0,))]

        return _Family(
            model=model, num_layers=model.num_layers, heads=model.heads,
            kv_heads=model.num_kv_heads, head_dim=d, norm_kind="rmsnorm",
            embed_decode=embed_decode,
            layer_params=lambda params, l: params[f"layer_{l}"],
            attn_norm=lambda p_l, x: RMSNorm(dtype=dt).apply(
                {"params": p_l["attn_norm"]}, x),
            attn_norm_params=lambda p_l: (p_l["attn_norm"]["scale"], None),
            qkv=qkv,
            attn_out=attn_out,
            ffn=ffn,
            ffn_norm=lambda p_l, x: RMSNorm(dtype=dt).apply(
                {"params": p_l["mlp_norm"]}, x),
            ffn_norm_params=lambda p_l: (p_l["mlp_norm"]["scale"], None),
            quant_paths=quant_paths,
        )

    raise ValueError(
        f"no paged-decode family for {type(model).__name__} (supported: "
        "GPTLM, LlamaLM); non-causal members serve single-forward "
        "requests instead")


def quantize_weights(family: _Family, params: dict) -> dict:
    """The ``--quant=int8_w`` param tree: every decode projection kernel
    replaced by ``{"q": int8, "scale": f32 per-output-channel}``;
    embeddings, norms, biases, the head, and MoE expert tensors are the
    original leaves (shared, not copied)."""
    out = params
    for l in range(family.num_layers):
        for path, caxes in family.quant_paths(l):
            leaf = params
            for k in path:
                leaf = leaf[k]
            out = _with_path(out, path, _quantize_leaf(leaf, caxes))
    return out


def init_kv_pages(family: _Family, num_pages: int, page_size: int,
                  dtype) -> tuple[jax.Array, jax.Array]:
    """The zeroed page pool: ``[L, pages, page_size, kv_heads, d]`` x2."""
    shape = (family.num_layers, num_pages, page_size, family.kv_heads,
             family.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def init_kv_state(family: _Family, num_pages: int, page_size: int,
                  dtype, quant: str = "off") -> tuple:
    """The engine's KV carry: ``(k_pages, v_pages)`` — int8 pools plus
    per-(layer, page) f32 scales under ``int8_kv`` (scales start at 1,
    matching the zeroed pool)."""
    if quant == "int8_kv":
        kp, vp = init_kv_pages(family, num_pages, page_size, jnp.int8)
        sc = jnp.ones((family.num_layers, num_pages), jnp.float32)
        return kp, vp, sc, sc
    return init_kv_pages(family, num_pages, page_size, dtype)


def build_page_copy_fn():
    """The copy-on-write program (round 25): duplicate physical page
    ``src`` into ``dst`` across every KV leaf, all layers at once.

    Every carry leaf — f32/int8 pools ``[L, pages, ps, kvh, d]`` AND
    the int8_kv per-(layer, page) scale planes ``[L, pages]`` — indexes
    pages on axis 1, so one tree_map covers both quant arms; an int8
    page is copied in its final quantized layout, scale and all (no
    dequant round-trip).  Args at call time: ``(kv, src [], dst [])``;
    one AOT program per engine (page count is baked into the pool
    shapes, not the program), warmed beside the decode buckets so a
    first mid-traffic COW is never a compile.
    """

    def page_copy(kv, src, dst):
        return jax.tree_util.tree_map(
            lambda x: x.at[:, dst].set(x[:, src]), kv)

    return page_copy


def _write_quantized_chunks(pages_q, scales, new, table, length,
                            page_size, table_width):
    """Prefill's int8 page write: ``new`` [L, s, kvh, d] chunked into
    pages, one amax-derived scale per (layer, chunk), chunks past the
    prompt routed to the trash page 0."""
    num_layers, s = new.shape[0], new.shape[1]
    s_pad = _pad_up(s, page_size)
    if s_pad != s:
        new = jnp.pad(new, ((0, 0), (0, s_pad - s), (0, 0), (0, 0)))
    c = s_pad // page_size
    chunks = new.reshape(num_layers, c, page_size, *new.shape[2:])
    idx = jnp.arange(c)
    cpage = jnp.where(idx * page_size < length,
                      table[jnp.clip(idx, 0, table_width - 1)], 0)
    amax = jnp.max(jnp.abs(chunks), axis=(2, 3, 4))
    sc = jnp.maximum(amax / 127.0, _QUANT_EPS)              # [L, c]
    q = jnp.clip(jnp.round(chunks / sc[:, :, None, None, None]),
                 -127, 127).astype(jnp.int8)
    return pages_q.at[:, cpage].set(q), scales.at[:, cpage].set(sc)


def _append_quantized(pages_q, scales, page_idx, offset, new):
    """Decode's int8 append: the touched page is dequantized with its
    stored scale, the new row written, and the page requantized with a
    fresh amax — ONE vectorized op over all layers and rows, outside
    the layer loop.  Rows past the append offset are zeroed BEFORE the
    amax: a page recycled from a retired request (the allocator never
    scrubs) still holds the previous occupant's values at those
    offsets, and trusting them would quantize this request's fresh
    token with a scale inflated by someone else's garbage (reads are
    masked either way; the fresh row's precision is what's at stake)."""
    b = page_idx.shape[0]
    rows = jnp.arange(b)
    old = pages_q[:, page_idx]                      # [L, b, ps, kvh, d]
    sc = scales[:, page_idx]                        # [L, b]
    page = old.astype(jnp.float32) * sc[..., None, None, None]
    page_size = page.shape[2]
    own = (jnp.arange(page_size)[None, :]
           <= offset[:, None])                      # [b, ps]
    page = jnp.where(own[None, :, :, None, None], page, 0.0)
    page = page.at[:, rows, offset].set(new.astype(jnp.float32))
    amax = jnp.max(jnp.abs(page), axis=(2, 3, 4))
    new_sc = jnp.maximum(amax / 127.0, _QUANT_EPS)
    q = jnp.clip(jnp.round(page / new_sc[..., None, None, None]),
                 -127, 127).astype(jnp.int8)
    return (pages_q.at[:, page_idx].set(q),
            scales.at[:, page_idx].set(new_sc))


def build_prefill_fn(family: _Family, page_size: int, table_width: int,
                     quant: str = "off"):
    """The (batch-1, padded prompt bucket) prefill program.

    Args at call time: ``(params, kv, tokens [1, s], length [],
    table [w])`` where ``kv`` is the engine's KV carry
    (``init_kv_state``).  Returns ``(next_token [1], logits [1, vocab],
    kv)`` with the prompt's K/V scattered into the table's pages (pad
    positions routed to the trash page 0; int8 pools get per-page
    scales from the chunked write).

    ``table`` here is the WRITE table, and that is the prefix-cache
    seam (round 25): a cache-hit admission passes a copy with the
    shared slots zeroed, so their stores route to the trash page —
    the shared physical pages already hold bitwise-identical K/V from
    the prefill that populated them — while the full dense pass still
    runs (``next_token`` needs attention over every prompt position)
    and the request's DECODE table keeps the real shared page ids.
    Skipping a shared slot is a page-table edit, never a new program;
    under int8_kv the same routing skips the quantized chunk store,
    so a cached page is quantized once and shared in its final
    int8+scale layout.
    """
    from tpu_hc_bench.parallel.sequence import dense_attention

    def prefill(params, kv, tokens, length, table):
        s = tokens.shape[1]
        positions = jnp.arange(s)[None, :]
        x = family.embed_prefill(params, tokens)
        group = family.heads // family.kv_heads
        new_k, new_v = [], []
        for l in range(family.num_layers):
            p_l = family.layer_params(params, l)
            h = family.attn_norm(p_l, x)
            q, k, v = family.qkv(p_l, h, positions)
            new_k.append(k)
            new_v.append(v)
            if group > 1:
                k = jnp.repeat(k, group, axis=2)
                v = jnp.repeat(v, group, axis=2)
            # causal masking alone is sufficient under right-padding:
            # the only logits read are at `length - 1`, whose keys
            # j <= length - 1 are all valid prompt positions
            ctx = dense_attention(q, k, v, causal=True)
            x = x + family.attn_out(p_l, ctx)
            x = x + family.ffn(p_l, family.ffn_norm(p_l, x))
        x_last = jax.lax.dynamic_slice_in_dim(x, length - 1, 1, axis=1)
        logits = family.head(params, x_last)[:, 0]      # [1, vocab]
        next_token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        pos = jnp.arange(s)
        kn = jnp.stack([k[0] for k in new_k])       # [L, s, kvh, d]
        vn = jnp.stack([v[0] for v in new_v])
        if quant == "int8_kv":
            k_pages, v_pages, k_scales, v_scales = kv
            # zero the pad positions: their (garbage-token) K/V would
            # otherwise inflate the last page's amax scale
            valid = (pos < length)[None, :, None, None]
            kn = jnp.where(valid, kn, 0.0)
            vn = jnp.where(valid, vn, 0.0)
            k_pages, k_scales = _write_quantized_chunks(
                k_pages, k_scales, kn, table, length, page_size,
                table_width)
            v_pages, v_scales = _write_quantized_chunks(
                v_pages, v_scales, vn, table, length, page_size,
                table_width)
            return next_token, logits, (k_pages, v_pages,
                                        k_scales, v_scales)
        # scatter the prompt K/V into this request's pages; pads -> trash
        k_pages, v_pages = kv
        page_idx = jnp.where(
            pos < length,
            table[jnp.clip(pos // page_size, 0, table_width - 1)], 0)
        offset = pos % page_size
        k_pages = k_pages.at[:, page_idx, offset].set(kn)
        v_pages = v_pages.at[:, page_idx, offset].set(vn)
        return next_token, logits, (k_pages, v_pages)

    return prefill


def build_decode_fn(family: _Family, page_size: int, table_width: int,
                    attention: str = "gather", quant: str = "off",
                    block_pages: int = 0):
    """The one-token-per-row decode program for a batch bucket.

    Args at call time: ``(params, kv, tokens [b], tables [b, w],
    lengths [b], active [b])`` where ``lengths`` is each row's cache
    depth (== the fed token's position) and ``kv`` the engine's KV
    carry.  Inactive rows compute on the trash page and write back to
    it; retirement and admission are pure host-side bookkeeping, never
    a new shape.  Returns ``(next_tokens [b], logits [b, vocab], kv)``.

    ``attention="gather"`` is the dense-gather reference;
    ``"paged"`` runs ``ops.paged_decode_attention`` straight over the
    page tables with ``block_pages`` pages per kernel block and fuses
    the residual-add+norm pairs (``ops.fused_residual_norm``).
    """
    if attention not in DECODE_ATTENTION_ARMS:
        raise ValueError(f"attention must be one of "
                         f"{DECODE_ATTENTION_ARMS}: {attention!r}")
    if quant == "int8_kv" and attention != "paged":
        raise ValueError("int8_kv scales are consumed inside the paged "
                         "kernel; the gather reference has no "
                         "scale-fused read path")
    ppb = max(1, block_pages)

    def scatter_new(kv, tables, lengths, active, kn, vn):
        b = lengths.shape[0]
        rows = jnp.arange(b)
        page_idx = jnp.where(
            active,
            tables[rows, jnp.clip(lengths // page_size, 0,
                                  table_width - 1)], 0)
        offset = lengths % page_size
        if quant == "int8_kv":
            k_pages, v_pages, k_scales, v_scales = kv
            k_pages, k_scales = _append_quantized(
                k_pages, k_scales, page_idx, offset, kn)
            v_pages, v_scales = _append_quantized(
                v_pages, v_scales, page_idx, offset, vn)
            return k_pages, v_pages, k_scales, v_scales
        k_pages, v_pages = kv
        k_pages = k_pages.at[:, page_idx, offset].set(kn)
        v_pages = v_pages.at[:, page_idx, offset].set(vn)
        return k_pages, v_pages

    def decode_gather(params, kv, tokens, tables, lengths, active):
        k_pages, v_pages = kv
        b = tokens.shape[0]
        span = table_width * page_size
        x = family.embed_decode(params, tokens, lengths)
        group = family.heads // family.kv_heads
        kv_valid = jnp.arange(span)[None, :] < lengths[:, None]
        mask = jnp.concatenate(
            [kv_valid, jnp.ones((b, 1), bool)], axis=1)
        new_k, new_v = [], []
        for l in range(family.num_layers):
            p_l = family.layer_params(params, l)
            h = family.attn_norm(p_l, x)
            q, k, v = family.qkv(p_l, h, lengths[:, None])
            new_k.append(k[:, 0])
            new_v.append(v[:, 0])
            kc = k_pages[l][tables].reshape(
                b, span, family.kv_heads, family.head_dim)
            vc = v_pages[l][tables].reshape(
                b, span, family.kv_heads, family.head_dim)
            keys = jnp.concatenate([kc, k], axis=1)
            values = jnp.concatenate([vc, v], axis=1)
            if group > 1:
                keys = jnp.repeat(keys, group, axis=2)
                values = jnp.repeat(values, group, axis=2)
            ctx = _softmax_attend(q, keys, values, mask)
            x = x + family.attn_out(p_l, ctx)
            x = x + family.ffn(p_l, family.ffn_norm(p_l, x))
        logits = family.head(params, x)[:, 0]
        next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        kn = jnp.stack(new_k, axis=0)               # [L, b, kvh, d]
        vn = jnp.stack(new_v, axis=0)
        return (next_tokens, logits,
                scatter_new(kv, tables, lengths, active, kn, vn))

    def decode_paged(params, kv, tokens, tables, lengths, active):
        if quant == "int8_kv":
            k_pages, v_pages, k_scales, v_scales = kv
        else:
            k_pages, v_pages = kv
            k_scales = v_scales = None
        group = family.heads // family.kv_heads
        scale = 1.0 / family.head_dim ** 0.5
        x = family.embed_decode(params, tokens, lengths)
        new_k, new_v = [], []
        delta = None        # the pending residual add, fused into the
                            # NEXT norm (ops.fused_residual_norm)
        for l in range(family.num_layers):
            p_l = family.layer_params(params, l)
            if delta is None:
                h = family.attn_norm(p_l, x)
            else:
                g, bta = family.attn_norm_params(p_l)
                x, h = fused_residual_norm(x, delta, g, bta,
                                           kind=family.norm_kind)
            q, k, v = family.qkv(p_l, h, lengths[:, None])
            new_k.append(k[:, 0])
            new_v.append(v[:, 0])
            # the WHOLE pool rides the kernel operand with a static
            # layer index — a k_pages[l] slice here would materialize
            # a per-layer pool copy as a temp, the very bytes the
            # kernel exists to not spend
            o_cache, lse = paged_decode_attention(
                q[:, 0], k_pages, v_pages, tables, lengths,
                pages_per_block=ppb, layer=l, return_lse=True,
                k_scales=k_scales, v_scales=v_scales)
            # the fresh token's K/V are not in the pool yet: fold them
            # into the kernel's online softmax through its logsumexp
            # (softmax over [cache, fresh] == lse-weighted mix; rows
            # with an empty cache get lse ~ -inf -> weight 1 on fresh)
            kf, vf = k[:, 0], v[:, 0]               # [b, kvh, d]
            if group > 1:
                kf = jnp.repeat(kf, group, axis=1)
                vf = jnp.repeat(vf, group, axis=1)
            s_new = jnp.sum(
                q[:, 0].astype(jnp.float32) * kf.astype(jnp.float32),
                axis=-1) * scale                    # [b, heads]
            w_new = jax.nn.sigmoid(s_new - lse)
            ctx = (o_cache.astype(jnp.float32)
                   * (1.0 - w_new)[..., None]
                   + vf.astype(jnp.float32) * w_new[..., None])
            a_out = family.attn_out(p_l, ctx.astype(x.dtype)[:, None])
            g2, b2 = family.ffn_norm_params(p_l)
            x, h2 = fused_residual_norm(x, a_out, g2, b2,
                                        kind=family.norm_kind)
            delta = family.ffn(p_l, h2)
        x = x + delta
        logits = family.head(params, x)[:, 0]
        next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        kn = jnp.stack(new_k, axis=0)               # [L, b, kvh, d]
        vn = jnp.stack(new_v, axis=0)
        return (next_tokens, logits,
                scatter_new(kv, tables, lengths, active, kn, vn))

    return decode_paged if attention == "paged" else decode_gather
